"""Shared device kernels: the vectorized primitives every algorithm
composes.

These replace the reference's per-message Python hot loops (SURVEY.md §3.3):

* ``factor_messages``        ↔ maxsum.factor_costs_for_var (maxsum.py:382):
  brute-force loop over the factor's assignment space, per neighbor →
  one broadcast-add + axis-min over the stacked cost hypercubes.
* ``candidate_costs``        ↔ relations.find_optimal/assignment_cost loops
  (relations.py:1479,1594) → gather + segment-sum producing the full
  ``(n_vars, max_domain)`` best-response cost matrix in one shot.
* ``buckets_cost``           ↔ dcop.solution_cost (dcop.py:308) on device.

All shapes are static per arity bucket; everything here is jit-traceable.

Precision (ops/precision.py): the kernels are dtype-polymorphic over the
cost planes — a bf16-stored cube flows through broadcasts and ``min``
reductions in its own dtype (rounding is monotone, so min/argmin are
order-preserving), and every SUM upcasts to the accumulation dtype
(f32 by default) exactly at the reduction boundary: ``segment_sum``
contributions, per-variable belief assembly, and total-cost
accumulation.  jax's type promotion does the upcast for free wherever
a bf16 plane meets an f32 message array; the explicit ``.astype`` calls
below cover the reductions whose inputs are pure plane gathers.
"""

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..graphs.arrays import HARD, SENTINEL


def _masked(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Invalid slots replaced by the SENTINEL in the costs' OWN dtype:
    a bf16 plane stays bf16 through the min/argmin (ordering survives
    rounding — asserted at import in graphs/arrays.py), an f32 plane is
    bit-identical to the historical ``BIG * 2`` substitution."""
    return jnp.where(mask, costs, jnp.asarray(SENTINEL, costs.dtype))


def _broadcast_q(q_p: jnp.ndarray, position: int, arity: int) -> jnp.ndarray:
    """Reshape a per-position message batch (F, D) so it broadcasts along
    axis ``position + 1`` of the (F, D, ..., D) cost cube."""
    shape = [q_p.shape[0]] + [1] * arity
    shape[position + 1] = q_p.shape[1]
    return q_p.reshape(shape)


def factor_messages(cubes: jnp.ndarray,
                    q: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Min-marginal messages from every factor of one arity bucket to each
    of its variables.

    cubes: (F, D, ..., D) stacked cost hypercubes (arity axes).
    q: per-position incoming messages, each (F, D).
    Returns per-position outgoing messages, each (F, D):
      r_p[d] = min over other vars' values of (cube + sum_{p'!=p} q_{p'}).

    Dtype: the output rides ``promote_types(cubes, q)`` — bf16 cubes
    against f32 messages upcast at the first broadcast-add (the exact
    upcast, since bf16 is a prefix of f32), so the sums inside the min
    sweep never accumulate in reduced precision.
    """
    arity = cubes.ndim - 1
    total = cubes
    q_b = [_broadcast_q(q[p], p, arity) for p in range(arity)]
    for p in range(arity):
        total = total + q_b[p]
    out = []
    for p in range(arity):
        t = total - q_b[p]
        reduce_axes = tuple(i + 1 for i in range(arity) if i != p)
        out.append(jnp.min(t, axis=reduce_axes) if reduce_axes else t)
    return out


def candidate_costs(cubes: jnp.ndarray, var_ids: jnp.ndarray,
                    x: jnp.ndarray, n_vars: int,
                    accum_dtype=jnp.float32) -> jnp.ndarray:
    """Contribution of one constraint bucket to every variable's
    per-candidate-value cost, holding all *other* variables at ``x``.

    cubes: (C, D, ..., D); var_ids: (C, arity); x: (V,) value indices.
    Returns (V, D): sum over constraints of the cost slice obtained by
    fixing every scope variable except the target at its current value.

    Accumulates in ``accum_dtype`` (f32): the gathered slices may be
    bf16-stored, but a high-degree variable sums hundreds of them —
    the textbook case where reduced-precision accumulation drifts
    (tests/test_precision.py asserts the f32 path engages).
    """
    arity = cubes.ndim - 1
    C = cubes.shape[0]
    D = cubes.shape[-1]
    vals = x[var_ids]  # (C, arity)
    total = jnp.zeros((n_vars, D), dtype=accum_dtype)
    for p in range(arity):
        t = jnp.moveaxis(cubes, p + 1, arity)  # target axis last
        t = t.reshape(C, -1, D)
        idx = jnp.zeros((C,), dtype=jnp.int32)
        for q in range(arity):
            if q != p:
                idx = idx * D + vals[:, q]
        contrib = t[jnp.arange(C), idx, :]  # (C, D)
        total = total + jax.ops.segment_sum(
            contrib.astype(accum_dtype), var_ids[:, p],
            num_segments=n_vars)
    return total


def bucket_cost(cubes: jnp.ndarray, var_ids: jnp.ndarray,
                x: jnp.ndarray) -> jnp.ndarray:
    """Per-constraint cost of assignment ``x`` for one bucket: (C,).
    A pure gather — values come back in the cubes' store dtype; callers
    summing them upcast at their reduction boundary."""
    C = cubes.shape[0]
    D = cubes.shape[-1]
    arity = cubes.ndim - 1
    vals = x[var_ids]  # (C, arity)
    idx = jnp.zeros((C,), dtype=jnp.int32)
    for p in range(arity):
        idx = idx * D + vals[:, p]
    return cubes.reshape(C, -1)[jnp.arange(C), idx]


def assignment_cost_device(buckets: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
                           var_costs: jnp.ndarray,
                           x: jnp.ndarray,
                           accum_dtype=jnp.float32) -> jnp.ndarray:
    """Total cost of assignment ``x``: constraint costs + unary costs,
    accumulated in ``accum_dtype`` regardless of the planes' store
    dtype (cost traces stay f32 under the bf16 policy)."""
    V = var_costs.shape[0]
    total = jnp.sum(
        var_costs[jnp.arange(V), x].astype(accum_dtype))
    for cubes, var_ids in buckets:
        total = total + jnp.sum(
            bucket_cost(cubes, var_ids, x).astype(accum_dtype))
    return total


def assignment_cost_violations(
        buckets: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
        var_costs: jnp.ndarray, x: jnp.ndarray,
        hard: float = float(HARD)) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device twin of ``DCOP.solution_cost(assignment)``: (soft cost,
    hard-violation count) of assignment ``x`` in the compiled (signed,
    clipped) cost space.

    The array compiler clips infinite model costs to ``±HARD``
    (graphs/arrays.py _clip_costs), so an entry with ``|cost| >= hard``
    IS the compiled marker of a hard violation: it is counted and
    excluded from the soft sum, exactly like the host evaluator with
    the default ``infinity`` threshold.  (A model whose *finite* costs
    reach HARD = 1e7 is outside the compiled representation's contract
    everywhere, not just here.)  Sums accumulate in f32; the returned
    cost is signed (multiply by ``arrays.sign`` for the model-space
    value).
    """
    V = var_costs.shape[0]
    unary = var_costs[jnp.arange(V), x].astype(jnp.float32)
    u_viol = jnp.abs(unary) >= hard
    cost = jnp.sum(jnp.where(u_viol, 0.0, unary))
    violations = jnp.sum(u_viol.astype(jnp.int32))
    for cubes, var_ids in buckets:
        c = bucket_cost(cubes, var_ids, x).astype(jnp.float32)
        v = jnp.abs(c) >= hard
        cost = cost + jnp.sum(jnp.where(v, 0.0, c))
        violations = violations + jnp.sum(v.astype(jnp.int32))
    return cost, violations


def masked_argmin(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Argmin over valid domain slots, rows = variables.  Runs in the
    costs' own dtype (min is order-preserving under monotone bf16
    rounding; sums are not — see module doc)."""
    return jnp.argmin(_masked(costs, mask), axis=-1)


def masked_min(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.min(_masked(costs, mask), axis=-1)


def prefix_uniform(key: jax.Array, n: int,
                   width: Optional[int] = None) -> jnp.ndarray:
    """Per-row uniform draws that are PREFIX-STABLE in ``n``: row ``i``
    depends only on ``(key, i)``, so padding ``n`` upward (phantom
    variables appended by ``graphs.arrays.*.pad_to``) draws fresh tail
    rows without disturbing the first ``n`` — unlike
    ``jax.random.uniform(key, (n,))``, whose threefry counter layout
    couples every element to the total shape.  This is what lets a
    shape-padded fused campaign job reproduce its unpadded subprocess
    solve bit-exactly.  Returns ``(n,)`` or ``(n, width)``."""
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        key, jnp.arange(n))
    shape = () if width is None else (width,)
    return jax.vmap(lambda k: jax.random.uniform(k, shape))(keys)


# ------------------------------------------ branch-and-bound pruning

#: cube cells (D**arity) below which the pruned sweep is never worth
#: its per-block bound checks: tiny cubes stay on the unrolled fast
#: paths (the bench round-3 lesson — op count dominates FLOPs there)
BNB_MIN_CELLS = 128

#: joint assignments per while-loop iteration of the pruned sweep —
#: coarse enough that the per-iteration bound check amortizes, fine
#: enough that a good bound ordering skips most of a big cube
BNB_BLOCK_CELLS = 64


@dataclass
class PrunedPlan:
    """Build-time constants of one arity bucket's branch-and-bound
    reduction (computed alongside the PR 5 hoisted per-constraint
    optima, see ``build_pruned_plan``).  ``cube_cells``/``digits``/
    ``suffix_min`` are host numpy here; solvers device-place them
    (cubes in the precision policy's store dtype) via
    :func:`device_pruned_plan`."""

    digits: Any       # (arity, n_cells_pad) int32, bound-sorted order
    cube_cells: Any   # (n_cells_pad, F) cube values in sorted order
    suffix_min: Any   # (n_blocks + 1, F) f32 remaining-cells minima
    block: int        # cells per while-loop iteration
    n_blocks: int
    n_cells: int      # real (unpadded) joint assignments


# a registered pytree so plans ride jit/shard_map argument lists (the
# sharded solvers pass per-shard plan stacks through P("tp") specs)
jax.tree_util.register_pytree_node(
    PrunedPlan,
    lambda p: ((p.digits, p.cube_cells, p.suffix_min),
               (p.block, p.n_blocks, p.n_cells)),
    lambda aux, kids: PrunedPlan(kids[0], kids[1], kids[2], *aux))


def build_pruned_plan(cubes, block: int = BNB_BLOCK_CELLS
                      ) -> Optional[PrunedPlan]:
    """The branch-and-bound reduction plan of one arity bucket:
    ``cubes (F, D, ..., D)``.  Joint assignments are ordered ascending
    by their per-slot lower bound — the min cube value over the
    bucket's factors, a pure build-time quantity — so the runtime sweep
    (``ops.pallas_kernels.factor_messages_nary_lane_major_pruned``)
    visits optimistic cells first and the per-factor suffix minima
    bound the tail.  Returns ``None`` for buckets too small to pay for
    the bound checks (``D**arity < BNB_MIN_CELLS``) or below arity 3
    (binary buckets ride the historically-benched kernels)."""
    import numpy as np

    cubes = np.asarray(cubes)
    F = cubes.shape[0]
    arity = cubes.ndim - 1
    D = cubes.shape[-1] if arity else 1
    n_cells = int(D ** arity)
    if F == 0 or arity < 3 or n_cells < BNB_MIN_CELLS:
        return None
    flat = np.asarray(cubes, dtype=np.float32).reshape(F, n_cells)
    order = np.argsort(flat.min(axis=0), kind="stable")
    digits = np.empty((arity, n_cells), dtype=np.int32)
    rem = order.copy()
    for p in range(arity - 1, -1, -1):
        digits[p] = rem % D
        rem //= D
    n_blocks = (n_cells + block - 1) // block
    pad = n_blocks * block - n_cells
    cube_cells = np.ascontiguousarray(flat[:, order].T)  # (n_cells, F)
    if pad:
        # +inf padding: a padded cell can never win a min (inf + q =
        # inf) and an all-padding tail makes the suffix bound fire
        cube_cells = np.concatenate(
            [cube_cells, np.full((pad, F), np.inf, np.float32)])
        digits = np.concatenate(
            [digits, np.zeros((arity, pad), np.int32)], axis=1)
    return PrunedPlan(digits=digits, cube_cells=cube_cells,
                      suffix_min=pruned_suffix_min(cube_cells, block,
                                                   n_blocks),
                      block=block, n_blocks=n_blocks,
                      n_cells=n_cells)


def pruned_suffix_min(cube_cells, block: int, n_blocks: int):
    """Per-factor suffix minima over the block grid of ``cube_cells``
    (``(..., n_blocks * block, F)``, any leading batch dims), f32.

    Device placement MUST recompute the bounds from the values the
    sweep will actually read: a plan built from f32 cubes whose
    ``cube_cells`` are then rounded to a narrower store dtype (bf16
    rounds to nearest, i.e. sometimes DOWN) would otherwise carry
    suffix minima ABOVE the true floor of the stored values — an
    invalid bound that can early-out past a winning cell."""
    import numpy as np

    cc = np.asarray(cube_cells, dtype=np.float32)
    *lead, _n_pad, F = cc.shape
    bm = cc.reshape(*lead, n_blocks, block, F).min(axis=-2)
    sm = np.full((*lead, n_blocks + 1, F), np.inf, dtype=np.float32)
    for i in range(n_blocks - 1, -1, -1):
        sm[..., i, :] = np.minimum(sm[..., i + 1, :], bm[..., i, :])
    return sm


def device_pruned_plan(plan: PrunedPlan, store_dtype) -> PrunedPlan:
    """Device-placed copy of a host plan: cube values ride the
    precision policy's store dtype (the same exact-upcast-at-entry
    contract as the full-scan kernels), with the suffix bounds
    recomputed from the STORED values (see
    :func:`pruned_suffix_min`); indices untouched."""
    import numpy as np

    stored = np.asarray(plan.cube_cells).astype(store_dtype)
    return PrunedPlan(
        digits=jnp.asarray(plan.digits),
        cube_cells=jnp.asarray(stored),
        suffix_min=jnp.asarray(pruned_suffix_min(
            stored, plan.block, plan.n_blocks)),
        block=plan.block, n_blocks=plan.n_blocks,
        n_cells=plan.n_cells)


def factor_messages_pruned(plan: PrunedPlan,
                           q: Sequence[jnp.ndarray]
                           ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Edge-major adapter of the pruned bound-ordered sweep: takes the
    (F, D) per-position messages the edge-major solvers carry, runs
    the shared lane-major core, and transposes back.  Returns
    ``(messages [(F, D) ...], blocks_run)``; messages are bit-exact
    with :func:`factor_messages` on the same bucket."""
    from .pallas_kernels import factor_messages_nary_lane_major_pruned

    msgs, blocks_run = factor_messages_nary_lane_major_pruned(
        plan, [jnp.transpose(qp) for qp in q])
    return [jnp.transpose(m) for m in msgs], blocks_run


# ------------------------------------------------- decimation helpers


def belief_margins(belief: jnp.ndarray, mask: jnp.ndarray,
                   axis: int = -1) -> jnp.ndarray:
    """Per-variable confidence of the current beliefs: second-best
    minus best cost over valid domain slots (the q-margin of decimated
    Max-Sum, arXiv:1706.02209).  ``axis`` is the domain axis (-1 for
    the (V, D) edge-major layout, 0 for the lane-major (D, V) one);
    variables with fewer than two valid slots come back huge — callers
    exclude them via the eligibility mask anyway."""
    b = jnp.where(mask, belief,
                  jnp.asarray(SENTINEL, belief.dtype))
    srt = jnp.sort(b.astype(jnp.float32), axis=axis)
    lo = jax.lax.index_in_dim(srt, 0, axis=axis, keepdims=False)
    hi = jax.lax.index_in_dim(srt, 1, axis=axis, keepdims=False)
    return hi - lo


def decimation_select(margins: jnp.ndarray, frozen: jnp.ndarray,
                      eligible: jnp.ndarray, p: float) -> jnp.ndarray:
    """One decimation event: the top-``ceil(p * n_candidates)``
    most-confident (largest-margin) unfrozen eligible variables.
    Returns the newly-frozen bool mask.  The cut is an exact rank-k
    (one argsort + one scatter on device), ties broken by variable
    index — a value-threshold cut would freeze EVERY tied candidate,
    which on instances with symmetric integer beliefs can pin the
    whole graph in one event regardless of ``p``.  Phantom/fixed
    variables are excluded via ``eligible``."""
    cand = jnp.logical_and(eligible, jnp.logical_not(frozen))
    n_cand = jnp.sum(cand.astype(jnp.int32))
    k = jnp.ceil(jnp.float32(p) * n_cand.astype(jnp.float32)) \
        .astype(jnp.int32)
    k = jnp.minimum(k, n_cand)
    m = jnp.where(cand, margins.astype(jnp.float32), -jnp.inf)
    order = jnp.argsort(-m)  # descending, stable: ties by index
    ranks = jnp.zeros_like(order).at[order].set(
        jnp.arange(m.shape[0], dtype=order.dtype))
    return jnp.logical_and(cand, ranks < k)


def random_argmin(key: jax.Array, costs: jnp.ndarray,
                  mask: jnp.ndarray) -> jnp.ndarray:
    """Argmin with uniform random tie-breaking among equal minima —
    replaces the reference's ``random.choice(best_values)`` idiom.

    The tie-break noise is drawn with :func:`prefix_uniform`, so row
    ``i``'s draw depends only on ``(key, i)``: padding the variable
    plane (phantom rows appended by ``pad_to``) leaves every real row's
    tie-break unchanged.  The previous ``jax.random.uniform(key,
    c.shape)`` draw was shape-coupled through the threefry counter
    layout — the exact hazard ``prefix_uniform`` exists to kill."""
    c = _masked(costs, mask)
    m = jnp.min(c, axis=-1, keepdims=True)
    is_min = (c <= m) & mask
    noise = prefix_uniform(key, c.shape[0], width=c.shape[-1])
    return jnp.argmax(is_min * (1.0 + noise), axis=-1)


# ------------------------------------------------------- ROI window sweeps
#
# Region-of-interest warm re-solves (ISSUE 16) run the Max-Sum update
# over a small gathered WINDOW of the full message planes instead of
# sweeping every row: the activity plane picks the rows, the window
# ships as pow2-padded index/value lists (fixed shapes per capacity
# rung — masking and padding, never dynamic shapes), and these
# primitives do the per-cycle gather -> update -> scatter.  They are
# the freeze-plane trick of decimation (PR 6) applied to convergence
# state instead of decimation state: rows outside the window simply
# keep their previous values, exactly like a frozen row keeps its
# clamp.  Padding contract: factor/selection lists pad by repeating
# their last entry (duplicate scatters write identical values), the
# per-variable edge table ``wv_edges`` pads with an OUT-OF-RANGE index
# (the plane's edge-axis width) so belief sums cannot double-count —
# gathers use ``mode='fill'`` and scatters ``mode='drop'``.


def roi_gather_edges(plane: jnp.ndarray, idx: jnp.ndarray,
                     lane: bool) -> jnp.ndarray:
    """Window gather of message rows: ``(..., idx)`` columns of a
    lane-oriented ``(D, E)`` plane or ``idx`` rows of an edge-major
    ``(E, D)`` plane, always returned edge-major ``(*idx.shape, D)``.
    Out-of-range pad indices fill with 0 (callers mask them)."""
    if lane:
        g = jnp.take(plane, idx.reshape(-1), axis=1, mode="fill",
                     fill_value=0).T
    else:
        g = jnp.take(plane, idx.reshape(-1), axis=0, mode="fill",
                     fill_value=0)
    return g.reshape(idx.shape + (plane.shape[0 if lane else -1],))


def roi_scatter_edges(plane: jnp.ndarray, idx: jnp.ndarray,
                      rows: jnp.ndarray, lane: bool) -> jnp.ndarray:
    """Window scatter, the inverse of :func:`roi_gather_edges`:
    edge-major ``rows`` land on the plane's own orientation;
    out-of-range pad indices drop."""
    D = plane.shape[0] if lane else plane.shape[-1]
    flat_i = idx.reshape(-1)
    flat_v = rows.reshape(-1, D).astype(plane.dtype)
    if lane:
        return plane.at[:, flat_i].set(flat_v.T, mode="drop")
    return plane.at[flat_i].set(flat_v, mode="drop")


def roi_window_factors(cube_w: jnp.ndarray, q0: jnp.ndarray,
                       q1: jnp.ndarray, r0_old: jnp.ndarray,
                       r1_old: jnp.ndarray, damping: float,
                       damp_factors: bool
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Binary window-factor messages: :func:`factor_messages` over the
    gathered cubes, with the solver's factor-side damping blend when
    the base program runs ``damping_nodes in ('factors', 'both')`` —
    the window must replicate the full sweep's arithmetic exactly."""
    m0, m1 = factor_messages(cube_w, [q0, q1])
    if damp_factors and damping > 0:
        # python-float coefficients, exactly like MaxSumSolver.step
        m0 = damping * r0_old + (1 - damping) * m0
        m1 = damping * r1_old + (1 - damping) * m1
    return m0, m1


def roi_window_variables(r_g: jnp.ndarray, q_old: jnp.ndarray,
                         wv_costs: jnp.ndarray, wv_mask: jnp.ndarray,
                         wv_dsize: jnp.ndarray, in_range: jnp.ndarray,
                         damping: float, damp_vars: bool, big: float
                         ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                    jnp.ndarray, jnp.ndarray]:
    """The per-variable half of one ROI Max-Sum cycle, mirroring
    ``MaxSumSolver.step`` operation for operation over the window:
    belief assembly, message normalization, the variable-side damping
    blend, invalid-slot masking, selection, and the per-variable
    residual that drives the frontier logic.

    r_g / q_old: ``(C_v, K, D)`` gathered incoming messages / previous
    outgoing messages (pad slots filled with 0).  in_range:
    ``(C_v, K)`` marks real edge slots.  Returns ``(q_new, belief,
    selection, resid)`` with ``resid`` the masked max-|dq| per window
    variable — the same quantity the full sweep maxes globally into
    its convergence delta."""
    mask3 = wv_mask[:, None, :]
    valid = in_range[:, :, None] & mask3
    belief = wv_costs + jnp.sum(
        jnp.where(in_range[:, :, None], r_g, 0.0).astype(jnp.float32),
        axis=1)                                        # (C_v, D)
    q_new = belief[:, None, :] - r_g                   # (C_v, K, D)
    mean = jnp.sum(jnp.where(valid, q_new, 0.0), axis=2) \
        / wv_dsize[:, None]
    q_new = q_new - mean[:, :, None]
    if damp_vars and damping > 0:
        # python-float coefficients, exactly like MaxSumSolver.step
        q_new = damping * q_old + (1 - damping) * q_new
    q_new = jnp.where(mask3, q_new, jnp.float32(big))
    selection = masked_argmin(belief, wv_mask).astype(jnp.int32)
    resid = jnp.max(jnp.where(valid, jnp.abs(q_new - q_old), 0.0),
                    axis=(1, 2))                       # (C_v,)
    return q_new, belief, selection, resid

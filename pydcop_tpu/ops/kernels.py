"""Shared device kernels: the vectorized primitives every algorithm
composes.

These replace the reference's per-message Python hot loops (SURVEY.md §3.3):

* ``factor_messages``        ↔ maxsum.factor_costs_for_var (maxsum.py:382):
  brute-force loop over the factor's assignment space, per neighbor →
  one broadcast-add + axis-min over the stacked cost hypercubes.
* ``candidate_costs``        ↔ relations.find_optimal/assignment_cost loops
  (relations.py:1479,1594) → gather + segment-sum producing the full
  ``(n_vars, max_domain)`` best-response cost matrix in one shot.
* ``buckets_cost``           ↔ dcop.solution_cost (dcop.py:308) on device.

All shapes are static per arity bucket; everything here is jit-traceable.

Precision (ops/precision.py): the kernels are dtype-polymorphic over the
cost planes — a bf16-stored cube flows through broadcasts and ``min``
reductions in its own dtype (rounding is monotone, so min/argmin are
order-preserving), and every SUM upcasts to the accumulation dtype
(f32 by default) exactly at the reduction boundary: ``segment_sum``
contributions, per-variable belief assembly, and total-cost
accumulation.  jax's type promotion does the upcast for free wherever
a bf16 plane meets an f32 message array; the explicit ``.astype`` calls
below cover the reductions whose inputs are pure plane gathers.
"""

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..graphs.arrays import HARD, SENTINEL


def _masked(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Invalid slots replaced by the SENTINEL in the costs' OWN dtype:
    a bf16 plane stays bf16 through the min/argmin (ordering survives
    rounding — asserted at import in graphs/arrays.py), an f32 plane is
    bit-identical to the historical ``BIG * 2`` substitution."""
    return jnp.where(mask, costs, jnp.asarray(SENTINEL, costs.dtype))


def _broadcast_q(q_p: jnp.ndarray, position: int, arity: int) -> jnp.ndarray:
    """Reshape a per-position message batch (F, D) so it broadcasts along
    axis ``position + 1`` of the (F, D, ..., D) cost cube."""
    shape = [q_p.shape[0]] + [1] * arity
    shape[position + 1] = q_p.shape[1]
    return q_p.reshape(shape)


def factor_messages(cubes: jnp.ndarray,
                    q: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Min-marginal messages from every factor of one arity bucket to each
    of its variables.

    cubes: (F, D, ..., D) stacked cost hypercubes (arity axes).
    q: per-position incoming messages, each (F, D).
    Returns per-position outgoing messages, each (F, D):
      r_p[d] = min over other vars' values of (cube + sum_{p'!=p} q_{p'}).

    Dtype: the output rides ``promote_types(cubes, q)`` — bf16 cubes
    against f32 messages upcast at the first broadcast-add (the exact
    upcast, since bf16 is a prefix of f32), so the sums inside the min
    sweep never accumulate in reduced precision.
    """
    arity = cubes.ndim - 1
    total = cubes
    q_b = [_broadcast_q(q[p], p, arity) for p in range(arity)]
    for p in range(arity):
        total = total + q_b[p]
    out = []
    for p in range(arity):
        t = total - q_b[p]
        reduce_axes = tuple(i + 1 for i in range(arity) if i != p)
        out.append(jnp.min(t, axis=reduce_axes) if reduce_axes else t)
    return out


def candidate_costs(cubes: jnp.ndarray, var_ids: jnp.ndarray,
                    x: jnp.ndarray, n_vars: int,
                    accum_dtype=jnp.float32) -> jnp.ndarray:
    """Contribution of one constraint bucket to every variable's
    per-candidate-value cost, holding all *other* variables at ``x``.

    cubes: (C, D, ..., D); var_ids: (C, arity); x: (V,) value indices.
    Returns (V, D): sum over constraints of the cost slice obtained by
    fixing every scope variable except the target at its current value.

    Accumulates in ``accum_dtype`` (f32): the gathered slices may be
    bf16-stored, but a high-degree variable sums hundreds of them —
    the textbook case where reduced-precision accumulation drifts
    (tests/test_precision.py asserts the f32 path engages).
    """
    arity = cubes.ndim - 1
    C = cubes.shape[0]
    D = cubes.shape[-1]
    vals = x[var_ids]  # (C, arity)
    total = jnp.zeros((n_vars, D), dtype=accum_dtype)
    for p in range(arity):
        t = jnp.moveaxis(cubes, p + 1, arity)  # target axis last
        t = t.reshape(C, -1, D)
        idx = jnp.zeros((C,), dtype=jnp.int32)
        for q in range(arity):
            if q != p:
                idx = idx * D + vals[:, q]
        contrib = t[jnp.arange(C), idx, :]  # (C, D)
        total = total + jax.ops.segment_sum(
            contrib.astype(accum_dtype), var_ids[:, p],
            num_segments=n_vars)
    return total


def bucket_cost(cubes: jnp.ndarray, var_ids: jnp.ndarray,
                x: jnp.ndarray) -> jnp.ndarray:
    """Per-constraint cost of assignment ``x`` for one bucket: (C,).
    A pure gather — values come back in the cubes' store dtype; callers
    summing them upcast at their reduction boundary."""
    C = cubes.shape[0]
    D = cubes.shape[-1]
    arity = cubes.ndim - 1
    vals = x[var_ids]  # (C, arity)
    idx = jnp.zeros((C,), dtype=jnp.int32)
    for p in range(arity):
        idx = idx * D + vals[:, p]
    return cubes.reshape(C, -1)[jnp.arange(C), idx]


def assignment_cost_device(buckets: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
                           var_costs: jnp.ndarray,
                           x: jnp.ndarray,
                           accum_dtype=jnp.float32) -> jnp.ndarray:
    """Total cost of assignment ``x``: constraint costs + unary costs,
    accumulated in ``accum_dtype`` regardless of the planes' store
    dtype (cost traces stay f32 under the bf16 policy)."""
    V = var_costs.shape[0]
    total = jnp.sum(
        var_costs[jnp.arange(V), x].astype(accum_dtype))
    for cubes, var_ids in buckets:
        total = total + jnp.sum(
            bucket_cost(cubes, var_ids, x).astype(accum_dtype))
    return total


def assignment_cost_violations(
        buckets: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
        var_costs: jnp.ndarray, x: jnp.ndarray,
        hard: float = float(HARD)) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device twin of ``DCOP.solution_cost(assignment)``: (soft cost,
    hard-violation count) of assignment ``x`` in the compiled (signed,
    clipped) cost space.

    The array compiler clips infinite model costs to ``±HARD``
    (graphs/arrays.py _clip_costs), so an entry with ``|cost| >= hard``
    IS the compiled marker of a hard violation: it is counted and
    excluded from the soft sum, exactly like the host evaluator with
    the default ``infinity`` threshold.  (A model whose *finite* costs
    reach HARD = 1e7 is outside the compiled representation's contract
    everywhere, not just here.)  Sums accumulate in f32; the returned
    cost is signed (multiply by ``arrays.sign`` for the model-space
    value).
    """
    V = var_costs.shape[0]
    unary = var_costs[jnp.arange(V), x].astype(jnp.float32)
    u_viol = jnp.abs(unary) >= hard
    cost = jnp.sum(jnp.where(u_viol, 0.0, unary))
    violations = jnp.sum(u_viol.astype(jnp.int32))
    for cubes, var_ids in buckets:
        c = bucket_cost(cubes, var_ids, x).astype(jnp.float32)
        v = jnp.abs(c) >= hard
        cost = cost + jnp.sum(jnp.where(v, 0.0, c))
        violations = violations + jnp.sum(v.astype(jnp.int32))
    return cost, violations


def masked_argmin(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Argmin over valid domain slots, rows = variables.  Runs in the
    costs' own dtype (min is order-preserving under monotone bf16
    rounding; sums are not — see module doc)."""
    return jnp.argmin(_masked(costs, mask), axis=-1)


def masked_min(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.min(_masked(costs, mask), axis=-1)


def prefix_uniform(key: jax.Array, n: int,
                   width: Optional[int] = None) -> jnp.ndarray:
    """Per-row uniform draws that are PREFIX-STABLE in ``n``: row ``i``
    depends only on ``(key, i)``, so padding ``n`` upward (phantom
    variables appended by ``graphs.arrays.*.pad_to``) draws fresh tail
    rows without disturbing the first ``n`` — unlike
    ``jax.random.uniform(key, (n,))``, whose threefry counter layout
    couples every element to the total shape.  This is what lets a
    shape-padded fused campaign job reproduce its unpadded subprocess
    solve bit-exactly.  Returns ``(n,)`` or ``(n, width)``."""
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        key, jnp.arange(n))
    shape = () if width is None else (width,)
    return jax.vmap(lambda k: jax.random.uniform(k, shape))(keys)


def random_argmin(key: jax.Array, costs: jnp.ndarray,
                  mask: jnp.ndarray) -> jnp.ndarray:
    """Argmin with uniform random tie-breaking among equal minima —
    replaces the reference's ``random.choice(best_values)`` idiom.

    The tie-break noise is drawn with :func:`prefix_uniform`, so row
    ``i``'s draw depends only on ``(key, i)``: padding the variable
    plane (phantom rows appended by ``pad_to``) leaves every real row's
    tie-break unchanged.  The previous ``jax.random.uniform(key,
    c.shape)`` draw was shape-coupled through the threefry counter
    layout — the exact hazard ``prefix_uniform`` exists to kill."""
    c = _masked(costs, mask)
    m = jnp.min(c, axis=-1, keepdims=True)
    is_min = (c <= m) & mask
    noise = prefix_uniform(key, c.shape[0], width=c.shape[-1])
    return jnp.argmax(is_min * (1.0 + noise), axis=-1)

"""Pallas TPU kernels for the hot ops.

The MaxSum factor update is the framework's hottest op (one per cycle
over every factor).  In lane-major layout — factors in the 128-wide
lane dimension, the small domain axes in sublanes — ALL of a factor's
outgoing min-marginal messages fuse into ONE kernel: per-cycle cost on
the benched chip is dominated by the number of separate kernels, not
FLOPs (see benchmarks/PERF_NOTES.md), so fusing the broadcast-add +
axis-mins + subtraction chain into a single pallas_call removes
several kernel launches per cycle.

Layout contract (lane-major, arity a):
  cubesT: (D, ..., D, F)  cost hypercubes, factor axis last (lanes)
  q_p:    (D, F)          incoming var->factor messages per position
  m_p:    (D, F)          outgoing factor->var min-marginals

  m_p[d, f] = min over the other positions' values of
              (cubesT[..., f] + sum_{p' != p} q_p'[d_p', f])

The domain axes are small and static, so the kernels unroll the
``D**arity`` hypercube sweep into fused vector ops over (BLK,) lanes —
pure VPU work with perfect tiling.  The binary kernel is the a=2
special case kept in its historically-benched form; ``_nary_kernel``
generalizes it for the PEAV/SECP n-ary factor families.  The unroll
only pays while ``D**arity`` stays small — ``NARY_FAST_MAX_CELLS``
gates dispatch; bigger hypercubes take the generic XLA path.
"""

import functools
import itertools
import os
import warnings

import jax
import jax.numpy as jnp

BLK_F = 512  # factors per grid step (multiple of the 128-lane tile)

#: per-factor hypercube cells (D**arity) at or below which the unrolled
#: lane-major fast paths (this kernel family and the fused var-sorted
#: layout) dispatch; above it, callers fall back to the generic
#: gather/scatter XLA path, which stays the correctness oracle.
#: This is the built-in default — consult :func:`nary_fast_max_cells`
#: (overridable via ``PYDCOP_TPU_NARY_MAX_CELLS`` for A/B runs) at
#: every dispatch decision instead of reading the constant directly.
NARY_FAST_MAX_CELLS = 4096

#: environment override of the fast-path cell ceiling (A/B runs tune
#: the ladder without a code edit)
NARY_MAX_CELLS_ENV = "PYDCOP_TPU_NARY_MAX_CELLS"

#: the ONE fallback/rejection explanation every eligibility error
#: embeds — previously copied (and drifting) across the lane/fused
#: solvers and the sharded mesh family
NARY_FALLBACK_TEXT = (
    "per-factor hypercubes small enough to unroll "
    "(D**arity <= NARY_FAST_MAX_CELLS, overridable via the "
    f"{NARY_MAX_CELLS_ENV} environment variable)")

_warned_bad_env = False


def nary_fast_max_cells() -> int:
    """The effective fast-path cell ceiling: the
    ``PYDCOP_TPU_NARY_MAX_CELLS`` environment variable when set (>= 1),
    else :data:`NARY_FAST_MAX_CELLS`.  Malformed values warn once and
    fall back to the default instead of silently changing dispatch."""
    raw = os.environ.get(NARY_MAX_CELLS_ENV)
    if not raw:
        return NARY_FAST_MAX_CELLS
    try:
        v = int(raw)
        if v < 1:
            raise ValueError(raw)
        return v
    except ValueError:
        global _warned_bad_env
        if not _warned_bad_env:
            _warned_bad_env = True
            warnings.warn(
                f"ignoring malformed {NARY_MAX_CELLS_ENV}={raw!r} "
                f"(want a positive integer); using the default "
                f"{NARY_FAST_MAX_CELLS}", RuntimeWarning)
        return NARY_FAST_MAX_CELLS


def nary_fast_eligible(max_domain: int, arity: int) -> bool:
    """THE n-ary fast-path eligibility predicate, in one place: binary
    (and unary) buckets are unconditionally eligible, bigger arities
    must keep their ``D**arity`` hypercube under the (env-overridable)
    unroll ceiling.  Every lane/fused/mesh dispatch decision routes
    through here so the gate can never drift between layouts."""
    return arity <= 2 or max_domain ** arity <= nary_fast_max_cells()


def _binary_kernel(cube_ref, q0_ref, q1_ref, m0_ref, m1_ref):
    D = q0_ref.shape[0]
    for d0 in range(D):
        acc = None
        for d1 in range(D):
            v = cube_ref[d0, d1, :] + q1_ref[d1, :]
            acc = v if acc is None else jnp.minimum(acc, v)
        m0_ref[d0, :] = acc
    for d1 in range(D):
        acc = None
        for d0 in range(D):
            v = cube_ref[d0, d1, :] + q0_ref[d0, :]
            acc = v if acc is None else jnp.minimum(acc, v)
        m1_ref[d1, :] = acc


def _common_dtype(cubesT, qs):
    """The kernels' working dtype: cost planes may arrive bf16-stored
    (ops/precision.py) while messages ride the f32 accumulation dtype;
    the hand kernels sum cube + messages per joint assignment, so the
    bf16 plane upcasts ONCE at kernel entry (exact — bf16 is a prefix
    of f32) instead of re-rounding every partial sum inside the
    unrolled sweep."""
    dt = cubesT.dtype
    for q in qs:
        dt = jnp.promote_types(dt, q.dtype)
    return dt


@functools.partial(jax.jit, static_argnames=("interpret",))
def factor_messages_binary_lane_major(cubesT, q0, q1, interpret=False):
    """Fused binary-factor min-marginals, lane-major (see module doc).

    Pads F up to a BLK_F multiple; the padded tail reads zeros and its
    outputs are sliced away.
    """
    from jax.experimental import pallas as pl

    dt = _common_dtype(cubesT, (q0, q1))
    cubesT = cubesT.astype(dt)
    q0, q1 = q0.astype(dt), q1.astype(dt)
    D, _, F = cubesT.shape
    F_pad = ((F + BLK_F - 1) // BLK_F) * BLK_F
    if F_pad != F:
        cubesT = jnp.pad(cubesT, ((0, 0), (0, 0), (0, F_pad - F)))
        q0 = jnp.pad(q0, ((0, 0), (0, F_pad - F)))
        q1 = jnp.pad(q1, ((0, 0), (0, F_pad - F)))
    grid = (F_pad // BLK_F,)
    m0, m1 = pl.pallas_call(
        _binary_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((D, D, BLK_F), lambda i: (0, 0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype),
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype),
        ],
        interpret=interpret,
    )(cubesT, q0, q1)
    return m0[:, :F], m1[:, :F]


def factor_messages_binary_lane_major_ref(cubesT, q0, q1):
    """jnp reference implementation (and the non-TPU fallback)."""
    m0 = jnp.min(cubesT + q1[None, :, :], axis=1)
    m1 = jnp.min(cubesT + q0[:, None, :], axis=0)
    return m0, m1


# ------------------------------------------------------------- n-ary


def _make_nary_kernel(arity, D):
    """Kernel for one arity bucket: all ``arity`` outgoing min-marginal
    messages of a (D, ..., D, BLK) hypercube block in one pallas_call.

    Unrolls the ``D**arity`` joint-assignment sweep: each assignment
    contributes ONE summed (BLK,) lane vector, reused for every
    position's accumulator via echo subtraction — the same
    total-minus-own-message association as the generic
    ``ops.kernels.factor_messages``, so messages match it bit-exactly.
    """

    def kernel(cube_ref, *refs):
        q_refs, m_refs = refs[:arity], refs[arity:]
        acc = [[None] * D for _ in range(arity)]
        for idx in itertools.product(range(D), repeat=arity):
            total = cube_ref[idx + (slice(None),)]
            for p in range(arity):
                total = total + q_refs[p][idx[p], :]
            for p in range(arity):
                v = total - q_refs[p][idx[p], :]
                a = acc[p][idx[p]]
                acc[p][idx[p]] = v if a is None else jnp.minimum(a, v)
        for p in range(arity):
            for d in range(D):
                m_refs[p][d, :] = acc[p][d]

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def factor_messages_nary_lane_major(cubesT, qs, interpret=False):
    """Fused n-ary factor min-marginals, lane-major (see module doc).

    cubesT: (D, ..., D, F) — ``arity = cubesT.ndim - 1`` domain axes;
    qs: per-position incoming messages, each (D, F).  Returns the
    ``arity`` outgoing messages, each (D, F).  Pads F up to a BLK_F
    multiple; the padded tail reads zeros and is sliced away.
    """
    from jax.experimental import pallas as pl

    qs = list(qs)
    arity = cubesT.ndim - 1
    if arity != len(qs):
        raise ValueError(
            f"cubesT has {arity} domain axes but {len(qs)} q arrays")
    dt = _common_dtype(cubesT, qs)
    cubesT = cubesT.astype(dt)
    qs = [q.astype(dt) for q in qs]
    D, F = cubesT.shape[0], cubesT.shape[-1]
    F_pad = ((F + BLK_F - 1) // BLK_F) * BLK_F
    if F_pad != F:
        cubesT = jnp.pad(
            cubesT, ((0, 0),) * arity + ((0, F_pad - F),))
        qs = [jnp.pad(q, ((0, 0), (0, F_pad - F))) for q in qs]
    grid = (F_pad // BLK_F,)
    cube_block = (D,) * arity + (BLK_F,)

    def cube_index(i):
        return (0,) * arity + (i,)

    msgs = pl.pallas_call(
        _make_nary_kernel(arity, D),
        grid=grid,
        in_specs=[pl.BlockSpec(cube_block, cube_index)] + [
            pl.BlockSpec((D, BLK_F), lambda i: (0, i))
            for _ in range(arity)
        ],
        out_specs=[
            pl.BlockSpec((D, BLK_F), lambda i: (0, i))
            for _ in range(arity)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype)
            for _ in range(arity)
        ],
        interpret=interpret,
    )(cubesT, *qs)
    return [m[:, :F] for m in msgs]


def factor_messages_lane_major(cubesT, q_in, arity, use_pallas=False,
                               interpret=False, plan=None):
    """Per-arity-bucket kernel dispatch shared by every lane-major
    consumer (single-chip lane/fused solvers and the mesh twins):
    binary buckets keep the historically-benched binary kernel/ref,
    n-ary buckets take the arity-generic pair; ``use_pallas`` opts
    into the hand kernels (``interpret`` for off-TPU testing).

    ``plan`` (a device-placed branch-and-bound reduction plan, see
    ``ops.kernels.build_pruned_plan``) reroutes the bucket through the
    pruned bound-ordered sweep instead of the full-scan kernels; the
    caller then receives ``(messages, blocks_run)`` — messages are
    bit-exact with the full scan (the bound only excludes cells that
    cannot lower any accumulator), ``blocks_run`` counts the executed
    cell blocks for the pruned-cell telemetry."""
    if plan is not None:
        return factor_messages_nary_lane_major_pruned(plan, q_in)
    if arity == 2:
        if use_pallas:
            return list(factor_messages_binary_lane_major(
                cubesT, *q_in, interpret=interpret))
        return list(factor_messages_binary_lane_major_ref(
            cubesT, *q_in))
    if use_pallas:
        return factor_messages_nary_lane_major(
            cubesT, q_in, interpret=interpret)
    return factor_messages_nary_lane_major_ref(cubesT, q_in)


# --------------------------------------------- branch-and-bound sweep


def factor_messages_nary_lane_major_pruned(plan, qs):
    """Branch-and-bound pruned n-ary min-marginals, lane-major.

    ``plan`` is a device-placed :class:`ops.kernels.PrunedPlan` (built
    once alongside the PR 5 hoisted per-constraint optima): the
    ``D**arity`` joint assignments of the bucket's hypercubes are
    pre-sorted ascending by their per-slot lower bound (min cube value
    over the bucket's factors) and swept in blocks inside a
    ``lax.while_loop``.  The loop carries one ``(arity, D, F)``
    accumulator stack and EARLY-OUTS as soon as the remaining cells'
    bound — the build-time per-factor suffix minimum of the sorted cube
    values plus the cycle's per-position ``min_d q_p`` slack — can no
    longer lower ANY accumulator entry.  A skipped cell satisfies
    ``cube[c] + sum_{p' != p} q_p'[c_p'] >= suffix_min + qexcl_p >=
    max_d acc[p, d]``, so the produced messages equal the full scan
    BIT-EXACTLY (per-cell sums associate in the same position order as
    ``factor_messages``; min is order-insensitive).

    Unlike the unrolled fast-path kernels this sweep never
    materializes the whole hypercube walk in the program, so it stays
    usable ABOVE the ``NARY_FAST_MAX_CELLS`` ceiling.

    qs: per-position incoming messages, each ``(D, F)``.  Returns
    ``([m_p (D, F) ...], blocks_run)`` — ``blocks_run`` is the traced
    number of executed cell blocks (pruned fraction =
    ``1 - blocks_run / plan.n_blocks``).
    """
    cube_cells, digits, suffix_min = (
        plan.cube_cells, plan.digits, plan.suffix_min)
    block, n_blocks = plan.block, plan.n_blocks
    arity = len(qs)
    D = qs[0].shape[0]
    dt = _common_dtype(cube_cells, qs)
    qs = [q.astype(dt) for q in qs]
    from ..graphs.arrays import SENTINEL

    # per-position slack: the least any OTHER position's message can
    # contribute — recomputed per cycle (cheap: one min per plane)
    qmin = [jnp.min(q, axis=0) for q in qs]             # (F,) each
    qmin_all = qmin[0]
    for m in qmin[1:]:
        qmin_all = qmin_all + m
    qexcl = jnp.stack([qmin_all - m for m in qmin])     # (arity, F)
    acc0 = jnp.full((arity, D, cube_cells.shape[1]),
                    jnp.asarray(SENTINEL, dt))

    def cond(c):
        i, _acc, stop = c
        return jnp.logical_and(i < n_blocks, jnp.logical_not(stop))

    def body(c):
        i, acc, _stop = c
        cb = jax.lax.dynamic_slice_in_dim(
            cube_cells, i * block, block, axis=0)       # (BC, F)
        dg = jax.lax.dynamic_slice_in_dim(
            digits, i * block, block, axis=1)           # (arity, BC)
        # same association order as factor_messages: cube + q_0 + ...
        total = cb.astype(dt)
        gathered = []
        for p in range(arity):
            g = qs[p][dg[p], :]                         # (BC, F)
            gathered.append(g)
            total = total + g
        new_acc = []
        for p in range(arity):
            seg = jax.ops.segment_min(
                total - gathered[p], dg[p], num_segments=D)
            new_acc.append(jnp.minimum(acc[p], seg))
        acc = jnp.stack(new_acc)
        nxt = i + 1
        # remaining-cells bound per (position, factor) vs the WORST
        # accumulator entry: stop only when no entry can improve
        bound = (suffix_min[nxt][None, :].astype(jnp.float32)
                 + qexcl.astype(jnp.float32))           # (arity, F)
        worst = jnp.max(acc.astype(jnp.float32), axis=1)
        return nxt, acc, jnp.all(bound >= worst)

    blocks_run, acc, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), acc0, jnp.bool_(False)))
    return [acc[p] for p in range(arity)], blocks_run


def factor_messages_nary_lane_major_ref(cubesT, qs):
    """jnp reference implementation (and the non-TPU fallback): the
    lane-major transpose of ``ops.kernels.factor_messages`` — same
    total-minus-echo association, so messages match it bit-exactly."""
    arity = cubesT.ndim - 1
    F = cubesT.shape[-1]
    total = cubesT
    q_b = []
    for p, q in enumerate(qs):
        shape = [1] * arity + [F]
        shape[p] = q.shape[0]
        q_b.append(q.reshape(shape))
        total = total + q_b[p]
    out = []
    for p in range(arity):
        t = total - q_b[p]
        reduce_axes = tuple(i for i in range(arity) if i != p)
        out.append(jnp.min(t, axis=reduce_axes) if reduce_axes else t)
    return out

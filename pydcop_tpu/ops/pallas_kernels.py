"""Pallas TPU kernels for the hot ops.

The MaxSum binary-factor update is the framework's hottest op (one per
cycle over every factor).  In lane-major layout — factors in the
128-wide lane dimension, the small domain axis in sublanes — both
outgoing min-marginal messages fuse into ONE kernel: per-cycle cost on
the benched chip is dominated by the number of separate kernels, not
FLOPs (see benchmarks/PERF_NOTES.md), so fusing the broadcast-add +
two axis-mins + subtraction chain into a single pallas_call removes
several kernel launches per cycle.

Layout contract (lane-major):
  cubesT: (D, D, F)   cost tables, factor axis last (lanes)
  q0,q1:  (D, F)      incoming var->factor messages per endpoint
  m0,m1:  (D, F)      outgoing factor->var min-marginals

  m0[d0, f] = min_d1 (cubesT[d0, d1, f] + q1[d1, f])
  m1[d1, f] = min_d0 (cubesT[d0, d1, f] + q0[d0, f])

The domain axis D is small and static, so the kernel unrolls D*D fused
vector ops over (BLK,) lanes — pure VPU work with perfect tiling.
"""

import functools

import jax
import jax.numpy as jnp

BLK_F = 512  # factors per grid step (multiple of the 128-lane tile)


def _binary_kernel(cube_ref, q0_ref, q1_ref, m0_ref, m1_ref):
    D = q0_ref.shape[0]
    for d0 in range(D):
        acc = None
        for d1 in range(D):
            v = cube_ref[d0, d1, :] + q1_ref[d1, :]
            acc = v if acc is None else jnp.minimum(acc, v)
        m0_ref[d0, :] = acc
    for d1 in range(D):
        acc = None
        for d0 in range(D):
            v = cube_ref[d0, d1, :] + q0_ref[d0, :]
            acc = v if acc is None else jnp.minimum(acc, v)
        m1_ref[d1, :] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def factor_messages_binary_lane_major(cubesT, q0, q1, interpret=False):
    """Fused binary-factor min-marginals, lane-major (see module doc).

    Pads F up to a BLK_F multiple; the padded tail reads zeros and its
    outputs are sliced away.
    """
    from jax.experimental import pallas as pl

    D, _, F = cubesT.shape
    F_pad = ((F + BLK_F - 1) // BLK_F) * BLK_F
    if F_pad != F:
        cubesT = jnp.pad(cubesT, ((0, 0), (0, 0), (0, F_pad - F)))
        q0 = jnp.pad(q0, ((0, 0), (0, F_pad - F)))
        q1 = jnp.pad(q1, ((0, 0), (0, F_pad - F)))
    grid = (F_pad // BLK_F,)
    m0, m1 = pl.pallas_call(
        _binary_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((D, D, BLK_F), lambda i: (0, 0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype),
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype),
        ],
        interpret=interpret,
    )(cubesT, q0, q1)
    return m0[:, :F], m1[:, :F]


def factor_messages_binary_lane_major_ref(cubesT, q0, q1):
    """jnp reference implementation (and the non-TPU fallback)."""
    m0 = jnp.min(cubesT + q1[None, :, :], axis=1)
    m1 = jnp.min(cubesT + q0[:, None, :], axis=0)
    return m0, m1

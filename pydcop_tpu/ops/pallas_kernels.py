"""Pallas TPU kernels for the hot ops.

The MaxSum factor update is the framework's hottest op (one per cycle
over every factor).  In lane-major layout — factors in the 128-wide
lane dimension, the small domain axes in sublanes — ALL of a factor's
outgoing min-marginal messages fuse into ONE kernel: per-cycle cost on
the benched chip is dominated by the number of separate kernels, not
FLOPs (see benchmarks/PERF_NOTES.md), so fusing the broadcast-add +
axis-mins + subtraction chain into a single pallas_call removes
several kernel launches per cycle.

Layout contract (lane-major, arity a):
  cubesT: (D, ..., D, F)  cost hypercubes, factor axis last (lanes)
  q_p:    (D, F)          incoming var->factor messages per position
  m_p:    (D, F)          outgoing factor->var min-marginals

  m_p[d, f] = min over the other positions' values of
              (cubesT[..., f] + sum_{p' != p} q_p'[d_p', f])

The domain axes are small and static, so the kernels unroll the
``D**arity`` hypercube sweep into fused vector ops over (BLK,) lanes —
pure VPU work with perfect tiling.  The binary kernel is the a=2
special case kept in its historically-benched form; ``_nary_kernel``
generalizes it for the PEAV/SECP n-ary factor families.  The unroll
only pays while ``D**arity`` stays small — ``NARY_FAST_MAX_CELLS``
gates dispatch; bigger hypercubes take the generic XLA path.
"""

import functools
import itertools

import jax
import jax.numpy as jnp

BLK_F = 512  # factors per grid step (multiple of the 128-lane tile)

#: per-factor hypercube cells (D**arity) at or below which the unrolled
#: lane-major fast paths (this kernel family and the fused var-sorted
#: layout) dispatch; above it, callers fall back to the generic
#: gather/scatter XLA path, which stays the correctness oracle
NARY_FAST_MAX_CELLS = 4096


def _binary_kernel(cube_ref, q0_ref, q1_ref, m0_ref, m1_ref):
    D = q0_ref.shape[0]
    for d0 in range(D):
        acc = None
        for d1 in range(D):
            v = cube_ref[d0, d1, :] + q1_ref[d1, :]
            acc = v if acc is None else jnp.minimum(acc, v)
        m0_ref[d0, :] = acc
    for d1 in range(D):
        acc = None
        for d0 in range(D):
            v = cube_ref[d0, d1, :] + q0_ref[d0, :]
            acc = v if acc is None else jnp.minimum(acc, v)
        m1_ref[d1, :] = acc


def _common_dtype(cubesT, qs):
    """The kernels' working dtype: cost planes may arrive bf16-stored
    (ops/precision.py) while messages ride the f32 accumulation dtype;
    the hand kernels sum cube + messages per joint assignment, so the
    bf16 plane upcasts ONCE at kernel entry (exact — bf16 is a prefix
    of f32) instead of re-rounding every partial sum inside the
    unrolled sweep."""
    dt = cubesT.dtype
    for q in qs:
        dt = jnp.promote_types(dt, q.dtype)
    return dt


@functools.partial(jax.jit, static_argnames=("interpret",))
def factor_messages_binary_lane_major(cubesT, q0, q1, interpret=False):
    """Fused binary-factor min-marginals, lane-major (see module doc).

    Pads F up to a BLK_F multiple; the padded tail reads zeros and its
    outputs are sliced away.
    """
    from jax.experimental import pallas as pl

    dt = _common_dtype(cubesT, (q0, q1))
    cubesT = cubesT.astype(dt)
    q0, q1 = q0.astype(dt), q1.astype(dt)
    D, _, F = cubesT.shape
    F_pad = ((F + BLK_F - 1) // BLK_F) * BLK_F
    if F_pad != F:
        cubesT = jnp.pad(cubesT, ((0, 0), (0, 0), (0, F_pad - F)))
        q0 = jnp.pad(q0, ((0, 0), (0, F_pad - F)))
        q1 = jnp.pad(q1, ((0, 0), (0, F_pad - F)))
    grid = (F_pad // BLK_F,)
    m0, m1 = pl.pallas_call(
        _binary_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((D, D, BLK_F), lambda i: (0, 0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
            pl.BlockSpec((D, BLK_F), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype),
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype),
        ],
        interpret=interpret,
    )(cubesT, q0, q1)
    return m0[:, :F], m1[:, :F]


def factor_messages_binary_lane_major_ref(cubesT, q0, q1):
    """jnp reference implementation (and the non-TPU fallback)."""
    m0 = jnp.min(cubesT + q1[None, :, :], axis=1)
    m1 = jnp.min(cubesT + q0[:, None, :], axis=0)
    return m0, m1


# ------------------------------------------------------------- n-ary


def _make_nary_kernel(arity, D):
    """Kernel for one arity bucket: all ``arity`` outgoing min-marginal
    messages of a (D, ..., D, BLK) hypercube block in one pallas_call.

    Unrolls the ``D**arity`` joint-assignment sweep: each assignment
    contributes ONE summed (BLK,) lane vector, reused for every
    position's accumulator via echo subtraction — the same
    total-minus-own-message association as the generic
    ``ops.kernels.factor_messages``, so messages match it bit-exactly.
    """

    def kernel(cube_ref, *refs):
        q_refs, m_refs = refs[:arity], refs[arity:]
        acc = [[None] * D for _ in range(arity)]
        for idx in itertools.product(range(D), repeat=arity):
            total = cube_ref[idx + (slice(None),)]
            for p in range(arity):
                total = total + q_refs[p][idx[p], :]
            for p in range(arity):
                v = total - q_refs[p][idx[p], :]
                a = acc[p][idx[p]]
                acc[p][idx[p]] = v if a is None else jnp.minimum(a, v)
        for p in range(arity):
            for d in range(D):
                m_refs[p][d, :] = acc[p][d]

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def factor_messages_nary_lane_major(cubesT, qs, interpret=False):
    """Fused n-ary factor min-marginals, lane-major (see module doc).

    cubesT: (D, ..., D, F) — ``arity = cubesT.ndim - 1`` domain axes;
    qs: per-position incoming messages, each (D, F).  Returns the
    ``arity`` outgoing messages, each (D, F).  Pads F up to a BLK_F
    multiple; the padded tail reads zeros and is sliced away.
    """
    from jax.experimental import pallas as pl

    qs = list(qs)
    arity = cubesT.ndim - 1
    if arity != len(qs):
        raise ValueError(
            f"cubesT has {arity} domain axes but {len(qs)} q arrays")
    dt = _common_dtype(cubesT, qs)
    cubesT = cubesT.astype(dt)
    qs = [q.astype(dt) for q in qs]
    D, F = cubesT.shape[0], cubesT.shape[-1]
    F_pad = ((F + BLK_F - 1) // BLK_F) * BLK_F
    if F_pad != F:
        cubesT = jnp.pad(
            cubesT, ((0, 0),) * arity + ((0, F_pad - F),))
        qs = [jnp.pad(q, ((0, 0), (0, F_pad - F))) for q in qs]
    grid = (F_pad // BLK_F,)
    cube_block = (D,) * arity + (BLK_F,)

    def cube_index(i):
        return (0,) * arity + (i,)

    msgs = pl.pallas_call(
        _make_nary_kernel(arity, D),
        grid=grid,
        in_specs=[pl.BlockSpec(cube_block, cube_index)] + [
            pl.BlockSpec((D, BLK_F), lambda i: (0, i))
            for _ in range(arity)
        ],
        out_specs=[
            pl.BlockSpec((D, BLK_F), lambda i: (0, i))
            for _ in range(arity)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((D, F_pad), cubesT.dtype)
            for _ in range(arity)
        ],
        interpret=interpret,
    )(cubesT, *qs)
    return [m[:, :F] for m in msgs]


def factor_messages_lane_major(cubesT, q_in, arity, use_pallas=False,
                               interpret=False):
    """Per-arity-bucket kernel dispatch shared by every lane-major
    consumer (single-chip lane/fused solvers and the mesh twins):
    binary buckets keep the historically-benched binary kernel/ref,
    n-ary buckets take the arity-generic pair; ``use_pallas`` opts
    into the hand kernels (``interpret`` for off-TPU testing)."""
    if arity == 2:
        if use_pallas:
            return list(factor_messages_binary_lane_major(
                cubesT, *q_in, interpret=interpret))
        return list(factor_messages_binary_lane_major_ref(
            cubesT, *q_in))
    if use_pallas:
        return factor_messages_nary_lane_major(
            cubesT, q_in, interpret=interpret)
    return factor_messages_nary_lane_major_ref(cubesT, q_in)


def factor_messages_nary_lane_major_ref(cubesT, qs):
    """jnp reference implementation (and the non-TPU fallback): the
    lane-major transpose of ``ops.kernels.factor_messages`` — same
    total-minus-echo association, so messages match it bit-exactly."""
    arity = cubesT.ndim - 1
    F = cubesT.shape[-1]
    total = cubesT
    q_b = []
    for p, q in enumerate(qs):
        shape = [1] * arity + [F]
        shape[p] = q.shape[0]
        q_b.append(q.reshape(shape))
        total = total + q_b[p]
    out = []
    for p in range(arity):
        t = total - q_b[p]
        reduce_axes = tuple(i for i in range(arity) if i != p)
        out.append(jnp.min(t, axis=reduce_axes) if reduce_axes else t)
    return out

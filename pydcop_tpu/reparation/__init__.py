"""Resilience: repair of orphaned computations after agent departure.

reference parity: pydcop/reparation/ (229 LoC __init__ + removal.py).

The repair problem is itself a DCOP (reference: agents.py:1047-1258):
one binary activation variable per (orphaned computation, candidate
agent) pair, with

* a hard-ish "exactly one host per computation" constraint,
* per-agent capacity constraints,
* unary hosting costs.

The reference solves it with distributed MGM-style computations spread
over the candidate agents.  TPU-first redesign: the repair info shipped
to candidates is global and deterministic, so every candidate solves the
*same* compiled repair DCOP (our DSA/MGM engine, fixed seed) and reads
off its own wins — replicated deterministic solving replaces the repair
message protocol while keeping the decision distributed (every agent
computes its own outcome; no agent is told what to host by a peer).
"""

from typing import Dict, List

from .removal import build_repair_info, candidate_agents, \
    orphaned_computations  # noqa: F401  (re-exported)

# penalty magnitudes for the soft encodings of the hard rules
_ORPHAN_PENALTY = 10_000.0
_CAPACITY_PENALTY = 10_000.0


def build_repair_dcop(repair_info: Dict) -> "DCOP":
    """Build the repair DCOP from a repair-info dict
    (see :func:`removal.build_repair_info`)."""
    from ..dcop.dcop import DCOP
    from ..dcop.objects import BinaryVariable
    from ..dcop.relations import NAryFunctionRelation, \
        UnaryFunctionRelation

    dcop = DCOP("repair", objective="min")

    variables: Dict[str, Dict[str, BinaryVariable]] = {}
    for comp, agents in repair_info["candidates"].items():
        variables[comp] = {}
        for agent in agents:
            v = BinaryVariable(_repair_var_name(comp, agent))
            variables[comp][agent] = v
            dcop.add_variable(v)
            hosting = repair_info["hosting_costs"].get(agent, {}).get(
                comp, 0.0)
            if hosting:
                dcop.add_constraint(UnaryFunctionRelation(
                    f"hosting_{comp}_{agent}", v,
                    lambda x, h=hosting: h * x))

    # exactly one host per computation (reference: agents.py:1159-1199)
    for comp, by_agent in variables.items():
        vs = list(by_agent.values())
        if not vs:
            continue

        def one_host(*vals):
            return _ORPHAN_PENALTY * abs(sum(vals) - 1)

        dcop.add_constraint(NAryFunctionRelation(
            one_host, vs, name=f"one_host_{comp}"))

    # capacity per candidate agent, footprint-weighted
    # (reference: agents.py:1200-1246)
    footprints = repair_info.get("footprints", {})
    by_candidate: Dict[str, List] = {}
    for comp, by_agent in variables.items():
        for agent, v in by_agent.items():
            by_candidate.setdefault(agent, []).append(
                (v, float(footprints.get(comp, 1.0))))
    for agent, pairs in by_candidate.items():
        cap = repair_info["capacity"].get(agent, float("inf"))
        vs = [v for v, _ in pairs]
        fps = tuple(fp for _, fp in pairs)
        # the constraint can only bind when activating all candidates
        # would exceed the (remaining) capacity — note cap may be 0
        if cap == float("inf") or sum(fps) <= cap:
            continue

        def within_cap(*vals, _cap=cap, _fps=fps):
            extra = sum(f * v for f, v in zip(_fps, vals)) - _cap
            return _CAPACITY_PENALTY * extra if extra > 0 else 0.0

        dcop.add_constraint(NAryFunctionRelation(
            within_cap, vs, name=f"capacity_{agent}"))
    return dcop


def solve_repair(repair_info: Dict, seed: int = 0) -> Dict[str, str]:
    """Solve the repair DCOP; returns computation -> winning agent.

    Deterministic for a given ``repair_info`` + ``seed`` so that every
    candidate agent can run it independently and agree on the outcome.
    """
    if not repair_info.get("orphaned"):
        return {}
    dcop = build_repair_dcop(repair_info)
    if not dcop.variables:
        return {}
    import jax

    from ..infrastructure.run import solve_result

    # every candidate agent must reach the *same* assignment: no
    # wall-clock timeout (stop_cycle is the only, deterministic, stop
    # condition) and a forced CPU backend so float behavior cannot differ
    # between hosts with different accelerators
    with jax.default_device(jax.devices("cpu")[0]):
        res = solve_result(dcop, "mgm", timeout=None, max_cycles=50,
                           seed=seed, stop_cycle=50)
    placement: Dict[str, str] = {}
    for comp, agents in repair_info["candidates"].items():
        chosen = [a for a in agents
                  if res.assignment.get(_repair_var_name(comp, a)) == 1]
        if chosen:
            placement[comp] = sorted(chosen)[0]
        elif agents:
            # penalty solve failed to activate anyone: cheapest fallback
            placement[comp] = min(
                agents,
                key=lambda a: repair_info["hosting_costs"]
                .get(a, {}).get(comp, 0.0))
    return placement


def solve_repair_dcop(agent, repair_info: Dict) -> List[str]:
    """The wins of one candidate agent (used by
    ResilientAgent.repair_run; reference: agents.py:1260-1382)."""
    placement = solve_repair(repair_info, seed=0)
    return sorted(c for c, a in placement.items() if a == agent.name)


def _repair_var_name(comp: str, agent: str) -> str:
    return f"x_{comp}__{agent}"

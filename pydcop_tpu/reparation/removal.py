"""Repair-problem data extraction after agent departures.

reference parity: pydcop/reparation/removal.py:38-167.  Given the set of
departed agents, derive the orphaned computations, the candidate agents
(replica holders) and the per-candidate data needed to build the repair
DCOP.
"""

from typing import Dict, Iterable, List, Set


def orphaned_computations(departed: Iterable[str], discovery
                          ) -> List[str]:
    """Computations hosted (only) on departed agents
    (reference: removal.py:38-60)."""
    orphaned: List[str] = []
    for agent in departed:
        orphaned.extend(discovery.agent_computations(agent))
    return sorted(set(orphaned))


def candidate_agents(orphaned: Iterable[str], discovery,
                     departed: Iterable[str] = ()) -> Dict[str, Set[str]]:
    """For each orphaned computation, the agents holding a replica of it
    (reference: removal.py:61-100)."""
    departed = set(departed)
    return {
        comp: {a for a in discovery.replica_agents(comp)
               if a not in departed}
        for comp in orphaned}


def build_repair_info(departed: Iterable[str], discovery,
                      agent_defs: Dict[str, object] = None,
                      footprints: Dict[str, float] = None
                      ) -> Dict[str, object]:
    """Assemble the data each candidate needs to set up the repair DCOP
    (reference: removal.py:101-167 + agents.py:1047-1258).

    The info is *global and deterministic*: every candidate receives the
    same dict, so each can solve the same repair DCOP with the same seed
    and read off its own wins without further coordination.

    ``capacity`` entries are *remaining* capacity: the AgentDef capacity
    minus the footprint of the computations the candidate already hosts
    (as ``_free_capacity`` in the replication protocol computes) —
    otherwise repair could overload an agent already at capacity.
    ``footprints`` maps computation name -> footprint (default 1.0).
    """
    departed = sorted(set(departed))
    orphaned = orphaned_computations(departed, discovery)
    candidates = candidate_agents(orphaned, discovery, departed)
    agent_defs = agent_defs or {}
    footprints = footprints or {}
    hosting: Dict[str, Dict[str, float]] = {}
    capacity: Dict[str, float] = {}
    all_candidates = sorted({a for agts in candidates.values()
                             for a in agts})
    for agent in all_candidates:
        adef = agent_defs.get(agent)
        hosting[agent] = {
            comp: (adef.hosting_cost(comp) if adef is not None else 0.0)
            for comp in orphaned}
        if adef is not None and adef.capacity is not None:
            used = sum(footprints.get(c, 1.0)
                       for c in discovery.agent_computations(agent))
            capacity[agent] = max(0.0, float(adef.capacity) - used)
        else:
            capacity[agent] = float("inf")
    return {
        "departed": departed,
        "orphaned": orphaned,
        "candidates": {c: sorted(a) for c, a in candidates.items()},
        "hosting_costs": hosting,
        "capacity": capacity,
        # per-orphan footprints, so capacity constraints weigh each
        # activation by its real size rather than counting 1 per orphan
        "footprints": {c: float(footprints.get(c, 1.0))
                       for c in orphaned},
    }

"""``pydcop batch``: benchmark campaign runner.

reference parity: pydcop/commands/batch.py:55-751 — job expansion from a
YAML of parameter grids, per-job subprocess with timeout + kill,
resume via a progress file, ``--simulate`` dry-run.  TPU-first
improvement: jobs can run in parallel (``--parallel N``), resolving the
reference's acknowledged TODO (batch.py:68).

Definition format::

    sets:
      set1:
        path: "instances/*.yaml"     # glob of problem files
        iterations: 2                # optional, default 1
    batches:
      bench_maxsum:
        command: solve               # any pydcop subcommand
        command_options:
          algo: [maxsum, dsa]        # lists = cartesian product
          algo_params: ["damping:0.5"]
          timeout: 5
    global_options:
      timeout: 10                    # defaults for every job
"""

import glob
import itertools
import os
import shlex
import subprocess
import sys
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import yaml

from . import CliError

PROGRESS_FILE = "batch_progress.txt"


def read_progress(path: str) -> set:
    """The registered-done job ids.  Tolerates a torn final line
    (legacy append-mode files written by a killed campaign): a
    partial id simply re-runs its job, which is safe — results are
    idempotent per-job files.  Only a MISSING file reads as empty;
    any other read failure propagates — register_progress rewrites
    the whole file from this set, and treating a transient EIO as
    "no progress" would let the rewrite wipe every recorded job."""
    done = set()
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    done.add(line)
    except FileNotFoundError:
        pass
    return done


def register_progress(path: str, job_id: str):
    """Crash-safe progress registration: read-merge-rewrite through
    the shared atomic-write helper (write-temp + flush+fsync +
    rename, ``robustness/checkpoint.atomic_write``), so a kill at ANY
    point leaves either the previous complete file or the new one —
    the historical append could die mid-``write`` and tear the resume
    state of the whole campaign.  Read-merge (not an in-memory set)
    keeps the fused child process and the parent pool coherent: they
    run sequentially, and each rewrite folds whatever the other
    already registered.  Cost, stated honestly: one linear file scan
    + rewrite per registration — O(jobs²) lines over a campaign,
    trivial at this CLI's hundreds-to-thousands-of-jobs scale (a 1024
    job campaign is ~1M line ops total); the 100k-job regime is the
    serve daemon's workload, which tracks jobs in its own telemetry,
    not this file."""
    from ..robustness.checkpoint import atomic_write

    done = read_progress(path)
    done.add(job_id)
    atomic_write(path, "\n".join(sorted(done)) + "\n")


def register_progress_many(path: str, job_ids):
    """Register a whole fused RUNG's jobs in one atomic write.  This
    closes the per-job registration window a kill could land in:
    after a rung's solve, either every job of it is registered (a
    resumed campaign skips the rung entirely) or none is (the rung
    re-forms with the SAME job set, so its checkpoint name matches
    and the snapshot restores instead of re-solving)."""
    from ..robustness.checkpoint import atomic_write

    done = read_progress(path)
    done.update(str(j) for j in job_ids)
    atomic_write(path, "\n".join(sorted(done)) + "\n")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "batch", help="run a benchmark campaign from a yaml definition")
    parser.add_argument("bench_def", type=str,
                        help="yaml benchmark definition")
    parser.add_argument("--simulate", action="store_true",
                        help="print the jobs without running them")
    parser.add_argument("--parallel", type=int, default=1,
                        help="number of jobs to run concurrently")
    parser.add_argument("--no-fuse", dest="fuse", action="store_false",
                        help="disable data-plane fusion (homogeneous "
                             "engine solve jobs normally run as ONE "
                             "vmapped program per topology group)")
    parser.add_argument("--fuse-hetero", dest="fuse_hetero",
                        action="store_true",
                        help="also fuse jobs whose instances have "
                             "DIFFERENT topologies: instances are "
                             "padded into a small power-of-two ladder "
                             "of shared shapes (phantom variables / "
                             "factors, masked out of results) so a "
                             "mixed campaign runs in <= #ladder-rungs "
                             "compiled programs instead of one "
                             "subprocess per job; selections stay "
                             "bit-exact with the per-job solve and "
                             "padding-waste / program-count stats "
                             "land in the results")
    parser.add_argument("--no-tuned", dest="no_tuned",
                        action="store_true",
                        help="ignore autotuned per-rung configs "
                             "(`pydcop autotune` sidecars beside the "
                             "executable cache); fused-hetero rungs "
                             "normally adopt the measured-fastest "
                             "config for any knob no flag or "
                             "algo-param pinned")
    parser.add_argument("--precision", default=None,
                        choices=["f32", "bf16", "auto"],
                        help="mixed-precision policy for every solve "
                             "job of the campaign (fused and "
                             "subprocess legs): bf16 stores cost "
                             "planes at half the bytes with f32 "
                             "accumulation — bit-exact selections on "
                             "integer-cost instances "
                             "(docs/architecture.md).  Jobs already "
                             "carrying a precision algo-param keep "
                             "it; algorithms without the param reject "
                             "the flag loudly")
    parser.add_argument("--decimation", default=None,
                        metavar="P[:EVERY]",
                        help="campaign-level decimated Max-Sum for "
                             "every maxsum solve job (fused and "
                             "subprocess legs): every EVERY cycles "
                             "pin the top-P most-confident unfrozen "
                             "variables (solve --decimation).  Jobs "
                             "already carrying a decimation_p "
                             "algo-param keep their own setting")
    parser.add_argument("--bnb", action="store_true",
                        help="campaign-level branch-and-bound pruned "
                             "factor reductions for every maxsum "
                             "solve job (solve --bnb).  bnb has no "
                             "vmapped batch solver (pruning plans are "
                             "per-instance cube constants), so maxsum "
                             "jobs take the subprocess path — the "
                             "fallback is announced, never silent")
    parser.add_argument("--portfolio", default=None, metavar="SPEC",
                        help="campaign-level solver-portfolio races "
                             "for every engine-mode solve job (solve "
                             "--portfolio): 'auto' or an arm grid, "
                             "e.g. 'maxsum;maxsum,damping:0.9;dsa,"
                             "variant:A'.  Each job races the arms "
                             "over ITS instance and records the "
                             "winner; jobs already carrying a "
                             "portfolio option keep their own grid.  "
                             "Races dispatch their own one-instance x "
                             "N-arm vmapped program, so these jobs "
                             "take the subprocess path (announced, "
                             "never silent)")
    parser.add_argument("--max_rung_mb", type=float, default=None,
                        help="cap the padded per-instance memory a "
                             "--fuse-hetero consolidation rung may "
                             "reach, priced at the precision policy's "
                             "store itemsize — at bf16 each cell "
                             "costs 2 bytes instead of 4, so the same "
                             "cap admits rungs twice as large (fewer "
                             "compiled programs).  Default: no cap")
    parser.add_argument("--reserve-slots", dest="reserve_slots",
                        type=str, default=None, metavar="SPEC",
                        help="explicit phantom headroom for every "
                             "--fuse-hetero rung, as 'vars:N,ARITY:N'"
                             " (extra variable rows / per-arity "
                             "factor slots beyond the power-of-two "
                             "ladder) — provisions in-place edit "
                             "capacity for dynamic workloads "
                             "(docs/architecture.md dynamics "
                             "section).  The reservation is part of "
                             "each rung's shape and is echoed in the "
                             "fused result rows and the "
                             "[fuse-hetero] stats line")
    parser.add_argument("--checkpoint", type=str, default=None,
                        metavar="DIR",
                        help="preemption-safe campaigns: fused rung "
                             "solves snapshot their whole batched "
                             "carry into DIR at chunk boundaries "
                             "(atomic write + fingerprint manifest, "
                             "docs/architecture.md), and subprocess "
                             "solve jobs get solve --checkpoint DIR "
                             "appended — so a killed campaign "
                             "re-launched with --resume continues "
                             "INSIDE the interrupted solves instead "
                             "of only skipping registered-done jobs "
                             "via the progress file")
    parser.add_argument("--checkpoint-every", dest="checkpoint_every",
                        type=int, default=256, metavar="N",
                        help="cycles between campaign snapshots "
                             "(default 256)")
    parser.add_argument("--resume", action="store_true",
                        help="restore existing --checkpoint "
                             "snapshots (rung carries for fused "
                             "groups, per-job solve snapshots for "
                             "subprocess jobs); mismatched "
                             "precision/backend snapshots refuse "
                             "loudly, missing ones start fresh.  "
                             "Progress-file job skipping is always "
                             "on, with or without this flag")
    parser.add_argument("--job_timeout", type=float, default=300)
    parser.add_argument("--dir", dest="out_dir", default="batch_out",
                        help="output directory for job results")
    parser.add_argument("--telemetry", type=str, default=None,
                        metavar="out.jsonl",
                        help="structured JSONL campaign telemetry "
                             "(same schema as solve --telemetry, "
                             "docs/analysing_results.md): fused "
                             "groups emit one header per group plus "
                             "per-cycle metric records and a summary "
                             "PER JOB, each attributed with job_id "
                             "(and fuse_rung on the hetero path); "
                             "subprocess jobs contribute their "
                             "summary record.  All writers append "
                             "atomically, so one file serves the "
                             "whole campaign")
    parser.add_argument("--consolidated-out", dest="consolidated_out",
                        default=None, metavar="results.jsonl",
                        help="opt-in: stream ONE JSON line per job "
                             "({'job_id': ..., **result}) to this file "
                             "instead of writing per-job JSON files "
                             "(a 1024-job campaign otherwise costs "
                             "1024 file creations — PERF_NOTES round "
                             "6).  Trade: `consolidate` reads per-job "
                             "files, so consume the jsonl directly; "
                             "progress/resume tracking is unchanged")
    parser.set_defaults(func=run_cmd)
    return parser


def parameters_configuration(options: Dict[str, Any]
                             ) -> Iterator[Dict[str, Any]]:
    """Cartesian product over list-valued options
    (reference: batch.py:652)."""
    keys = sorted(options)
    value_lists = [
        options[k] if isinstance(options[k], list) else [options[k]]
        for k in keys]
    for combo in itertools.product(*value_lists):
        yield dict(zip(keys, combo))


def expand_jobs(bench_def: Dict
                ) -> List[Tuple[str, List[str], Dict[str, Any]]]:
    """All (job_id, argv, meta) triples of the campaign; ``meta``
    carries the structured (command, path, conf, iteration) the fused
    data-plane runner needs without re-parsing argv."""
    sets = bench_def.get("sets", {"default": {"path": None}})
    batches = bench_def.get("batches")
    if not batches:
        raise CliError("benchmark definition needs a 'batches' section")
    global_opts = bench_def.get("global_options", {})
    jobs = []
    for set_name, set_def in sets.items():
        paths = (sorted(glob.glob(set_def["path"]))
                 if set_def.get("path") else [None])
        if set_def.get("path") and not paths:
            raise CliError(
                f"Set {set_name}: no file matches {set_def['path']}")
        iterations = int(set_def.get("iterations", 1))
        for batch_name, batch_def in batches.items():
            command = batch_def.get("command", "solve")
            options = dict(global_opts)
            options.update(batch_def.get("command_options", {}))
            for path in paths:
                for conf in parameters_configuration(options):
                    for it in range(iterations):
                        job_id = _job_id(set_name, batch_name, path,
                                         conf, it)
                        argv = _job_argv(command, path, conf, it)
                        jobs.append((job_id, argv, {
                            "command": command, "path": path,
                            "conf": conf, "iteration": it}))
    return jobs


def _job_id(set_name, batch_name, path, conf, iteration) -> str:
    # ',' joins the k=v pairs: it cannot appear in CLI flag names and
    # is filename-safe, so consolidate can split the params segment
    # unambiguously even when keys or values contain '_'
    conf_s = ",".join(
        f"{k}={v}" for k, v in sorted(conf.items())
        if k not in ("timeout",))
    base = os.path.basename(path) if path else "nofile"
    return f"{set_name}__{batch_name}__{base}__{conf_s}__{iteration}" \
        .replace("/", "-").replace(" ", "")


def _has_seed(conf: Dict[str, Any]) -> bool:
    if "seed" in conf:
        return True
    ap = conf.get("algo_params", [])
    ap = ap if isinstance(ap, list) else [ap]
    return any(str(p).strip().startswith("seed:") for p in ap)


def _job_argv(command: str, path, conf: Dict[str, Any],
              iteration: int = 0) -> List[str]:
    argv = [sys.executable, "-m", "pydcop_tpu.dcop_cli"]
    timeout = conf.get("timeout")
    if timeout is not None:
        argv += ["--timeout", str(timeout)]
    argv.append(command)
    for k, v in sorted(conf.items()):
        if k == "timeout":
            continue
        flag = f"--{k}" if len(k) > 1 else f"-{k}"
        if isinstance(v, bool):
            if v:
                argv.append(flag)
        elif isinstance(v, list):
            for item in v:
                argv += [flag, str(item)]
        else:
            argv += [flag, str(v)]
    if command == "solve" and not _has_seed(conf):
        # replicates must be fresh draws: each iteration gets its own
        # seed (the solve CLI's fixed default would make every
        # iteration of a stochastic algorithm byte-identical)
        argv += ["--seed", str(iteration)]
    if path:
        argv.append(path)
    return argv


# ---------------------------------------------------------------------
# Fused data-plane path: homogeneous engine solve jobs become ONE
# vmapped program (parallel/batch.py) instead of one subprocess each —
# the TPU resolution of the reference's "run in parallel" TODO
# (batch.py:68): --parallel gives subprocess concurrency, fusion gives
# data-plane concurrency, and they compose (fused groups first, the
# rest through the pool).
# ---------------------------------------------------------------------

#: algorithms with a vmapped multi-instance solver
FUSABLE_ALGOS = {"maxsum": "factor", "dsa": "hyper", "mgm": "hyper"}
#: engine-level options the fused path understands; a job with any
#: other option — including a per-job `timeout`, which a single fused
#: program cannot enforce per instance — falls back to the subprocess
#: path untouched
_FUSE_CONF_KEYS = {"algo", "algo_params", "max_cycles", "mode",
                   "seed"}
#: the `solve` CLI's --max_cycles default: fused and subprocess runs of
#: the same campaign must stop at the same budget
_SOLVE_MAX_CYCLES_DEFAULT = 2000


def _job_algo_params(conf) -> List[str]:
    """A job's algo params as a flat string list (either the
    ``algo_params`` or the ``p`` spelling)."""
    ap = conf.get("algo_params", [])
    ap = list(ap) if isinstance(ap, list) else [ap]
    short = conf.get("p", [])
    ap += short if isinstance(short, list) else [short]
    return [str(p) for p in ap if p is not None]


def _job_has_bnb(conf) -> bool:
    from ..algorithms import param_bool

    for p in _job_algo_params(conf):
        k, _sep, v = p.strip().partition(":")
        if k == "bnb" and param_bool(v.strip()):
            return True
    return False


def _fuse_exclusion_reason(meta, campaign_bnb=False,
                           campaign_portfolio=False) -> Optional[str]:
    """Why a job cannot take the fused data plane, or None when it
    can.  Surfaced by ``run_cmd`` (one log line per excluded group):
    a per-job ``timeout``, a non-engine mode or an algo without a
    vmapped solver used to take the subprocess path SILENTLY, hiding
    from campaign authors why their run was slow."""
    conf = meta["conf"]
    if meta["command"] != "solve":
        return f"command '{meta['command']}' is not solve"
    if meta["path"] is None:
        return "no instance file"
    algo = conf.get("algo")
    if algo not in FUSABLE_ALGOS:
        return f"algo '{algo}' has no vmapped batch solver"
    mode = conf.get("mode", "engine")
    if mode != "engine":
        return f"mode '{mode}' is not engine"
    if conf.get("portfolio") or campaign_portfolio:
        # an arm race is its own one-instance x N-arm vmapped
        # program; the fused path vmaps instances through ONE config
        return ("portfolio arm races dispatch their own vmapped "
                "program (one instance x N arms) and cannot ride "
                "the multi-instance fused path")
    extra = sorted(set(conf) - _FUSE_CONF_KEYS)
    if extra:
        keys = ", ".join(f"'{k}'" for k in extra)
        return (f"option(s) {keys} outside the fused path "
                "(a single fused program cannot enforce per-job "
                "settings)")
    if _job_has_bnb(conf) or (campaign_bnb and algo == "maxsum"):
        # pruning plans are build-time constants of ONE instance's
        # cube contents; batched cubes are per-instance vmapped
        # arguments (parallel/batch.py rejects the combination)
        return ("bnb pruned reductions have no vmapped batch solver "
                "(pruning plans are per-instance cube constants)")
    return None


def _fuse_group_key(meta, campaign_bnb=False,
                    campaign_portfolio=False) -> Optional[Tuple]:
    conf = meta["conf"]
    algo = conf.get("algo")
    if _fuse_exclusion_reason(meta, campaign_bnb,
                              campaign_portfolio) is not None:
        return None
    ap = conf.get("algo_params", [])
    ap = tuple(sorted(ap if isinstance(ap, list) else [ap]))
    seed = conf.get("seed")
    return (algo, ap,
            int(conf.get("max_cycles", _SOLVE_MAX_CYCLES_DEFAULT)),
            int(seed) if seed is not None else None)


def _topology_signature(arrays) -> Tuple:
    """Instances fuse only when everything BUT the constraint cost
    tables matches: the vmapped solvers batch over cubes, all other
    solver constants — including declared initial values, which seed
    the local-search start state — come from the shared template."""
    buckets = [(b.arity, b.var_ids.tobytes()) for b in arrays.buckets]
    initial = (arrays.initial_idx.tobytes(),
               arrays.has_initial.tobytes()) \
        if hasattr(arrays, "initial_idx") else ()
    return (tuple(arrays.var_names), arrays.domain_size.tobytes(),
            arrays.var_costs.tobytes(), initial, tuple(buckets))


_jsonl_lock = None


def _append_jsonl(path: str, job_id: str, result: dict):
    """One line per job, written as a SINGLE os.write to an O_APPEND
    fd: a buffered text write would split lines larger than the I/O
    buffer into multiple syscalls, letting concurrent ``--parallel``
    threads interleave partial rows.  A process-local lock guards the
    encode+write pair as well (the fused child runs before the
    subprocess pool, so cross-process appends never overlap)."""
    import json as _json
    import threading

    global _jsonl_lock
    if _jsonl_lock is None:
        _jsonl_lock = threading.Lock()
    data = (_json.dumps(dict(result, job_id=job_id)) + "\n").encode()
    with _jsonl_lock:
        fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                     0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)


def _solve_direct_algo(algo) -> bool:
    """Whether ``algo`` runs a one-shot exact sweep
    (``solve_direct``) — derived from the algorithm module itself,
    the same capability test ``infrastructure/run.py`` dispatches on,
    so a new exact family can never drift out of sync with this
    check.  Unknown algo names return False: the job will fail on its
    own terms, not on a checkpoint decision."""
    try:
        from ..algorithms import load_algorithm_module

        return hasattr(load_algorithm_module(str(algo)),
                       "solve_direct")
    except Exception:
        return False


def _rung_checkpointer(checkpoint_dir, checkpoint_every, algo, sub,
                       precision_name):
    """One fused sub-group's :class:`SolveCheckpointer` (or None):
    named by the job ids it solves — unique within a campaign — and
    fingerprinted by the program identity, so a resumed campaign can
    only restore a rung carry into the same batched program."""
    if not checkpoint_dir:
        return None
    import hashlib
    import json as _json

    from ..robustness.checkpoint import (CheckpointStore,
                                         SolveCheckpointer,
                                         checkpoint_fingerprint)

    name = "batch:" + hashlib.sha256(_json.dumps(
        [algo, sorted(job_id for job_id, _p, _i in sub)]
    ).encode()).hexdigest()
    return SolveCheckpointer(
        CheckpointStore(checkpoint_dir), name,
        every=checkpoint_every,
        fingerprint=checkpoint_fingerprint(
            precision=precision_name or "f32", layout="batched",
            algo=algo))


def _run_fused_group(key, rows, out_dir, register_done,
                     consolidated_out=None, hetero=False,
                     precision=None, max_rung_mb=None,
                     telemetry=None, decimation=None,
                     reserve=None, checkpoint=None,
                     checkpoint_every=None,
                     checkpoint_resume=False,
                     register_many=None, no_tuned=False):
    """Solve every (job_id, path, iteration) row of one group as a
    handful of vmapped programs — ONE per topology by default, or (with
    ``hetero``) one per shape-bucket rung: distinct topologies are
    padded to a shared power-of-two shape (graphs.arrays pad_to +
    parallel/bucketing.py) and batched together, cost <= #rungs
    compilations for the whole mixed group.  Writes the same per-job
    result JSON the subprocess path produces, so resume files and
    ``consolidate`` CSVs are indistinguishable (or one jsonl line per
    job when the campaign opted into ``--consolidated-out``).

    Result costs/violations come from ONE vmapped device evaluation
    per rung (``runner.evaluate``) instead of a per-job Python re-walk
    of every constraint — the fused leg's remaining host cost named in
    PERF_NOTES round 8.  ``precision`` applies the campaign-level
    mixed-precision policy to rows that carry none of their own;
    ``max_rung_mb`` caps consolidation-rung memory priced at the
    policy's store itemsize (parallel/bucketing.py)."""
    import numpy as np

    from ..dcop.dcop import filter_dcop
    from ..dcop.yamldcop import load_dcop_from_file
    from ..graphs.arrays import FactorGraphArrays, HypergraphArrays
    from ..ops.precision import ENV_VAR as PRECISION_ENV
    from ..ops.precision import resolve as resolve_precision
    from ..parallel.batch import (BatchedDsa, BatchedMaxSum, BatchedMgm,
                                  runner_for_rung)
    from ..parallel.bucketing import ShapeProfile, plan_rungs
    from . import build_algo_def, output_json, parse_algo_params

    algo, algo_params, max_cycles, conf_seed = key
    # validated/cast exactly like `solve` does; only user-given params
    # travel to the vmapped solver constructor
    algo_def = build_algo_def(algo, list(algo_params), "min")
    given = parse_algo_params(list(algo_params))
    params = {k: algo_def.params[k] for k in given}
    params.pop("stop_cycle", None)
    # engine-level seed, mirroring the subprocess path exactly:
    # `--seed N` (conf) pins every row; a `-p seed:` algo-param is
    # INERT for compiled engine solvers (mp-plane only, see
    # algorithms/_mp.py) but its presence suppresses the per-iteration
    # default, so rows then share the solve CLI's default seed 0;
    # otherwise each row draws from its ITERATION index (the
    # `--seed <iteration>` _job_argv appends) so replicates are fresh
    # draws, not N identical runs
    ap_has_seed = params.pop("seed", None) is not None
    if conf_seed is not None:
        explicit_seed = conf_seed
    elif ap_has_seed:
        explicit_seed = 0
    else:
        explicit_seed = None

    # campaign-level precision: a job's own -p precision: wins, then
    # the --precision flag (threaded through the spec), then the env
    if precision and "precision" not in params:
        params["precision"] = precision

    # campaign-level decimation (maxsum only — the vmapped dsa/mgm
    # runners have no freeze plane); a job's own -p decimation_p: wins
    if decimation and algo == "maxsum" \
            and "decimation_p" not in params:
        from .solve import parse_decimation_flag

        p, every = parse_decimation_flag(decimation)
        params["decimation_p"] = p
        params["decimation_every"] = every
    requested_precision = params.get("precision") \
        or os.environ.get(PRECISION_ENV)
    policy = resolve_precision(requested_precision)
    precision_name = policy.name if requested_precision else None

    # maxsum noise draws are shape-coupled, so a shape-padded run would
    # not reproduce the per-job solve: noisy groups keep exact-topology
    # fusion only (the bit-exactness guard rail comes first)
    if float(params.get("noise", 0) or 0) != 0:
        hetero = False

    # one reporter per fused group: header now, per-job cycle records
    # + summaries from emit() below — every record lands in the ONE
    # campaign jsonl via atomic appends (observability/report.py)
    reporter = None
    if telemetry:
        from ..observability.report import RunReporter

        if checkpoint:
            # named, never silent: the batched snapshot excludes the
            # metric-plane carry, so checkpointed fused groups emit
            # header + summaries without per-cycle records
            print("[checkpoint] per-cycle telemetry records are "
                  "disabled for checkpointed fused groups (the "
                  "metric planes are not part of the snapshot); "
                  "summaries still land in the campaign jsonl")
        reporter = RunReporter(telemetry, algo=algo, mode="batch-fused")
        reporter.header(
            algo_params=list(algo_params), max_cycles=max_cycles,
            jobs=len(rows), precision=precision_name,
            hetero=bool(hetero), reserve=reserve)

    try:
        _run_fused_group_inner(
            key, rows, out_dir, register_done, consolidated_out,
            hetero, algo, params, max_cycles, explicit_seed,
            precision_name, policy, max_rung_mb, reporter,
            reserve=reserve, checkpoint=checkpoint,
            checkpoint_every=checkpoint_every,
            checkpoint_resume=checkpoint_resume,
            register_many=register_many, no_tuned=no_tuned)
    finally:
        if reporter is not None:
            reporter.close()


def _run_fused_group_inner(key, rows, out_dir, register_done,
                           consolidated_out, hetero, algo, params,
                           max_cycles, explicit_seed, precision_name,
                           policy, max_rung_mb, reporter,
                           reserve=None, checkpoint=None,
                           checkpoint_every=None,
                           checkpoint_resume=False,
                           register_many=None, no_tuned=False):
    import numpy as np

    from ..dcop.yamldcop import load_dcop_from_file
    from ..dcop.dcop import filter_dcop
    from ..graphs.arrays import FactorGraphArrays, HypergraphArrays
    from ..parallel.batch import (BatchedDsa, BatchedMaxSum, BatchedMgm,
                                  runner_for_rung)
    from ..parallel.bucketing import ShapeProfile, plan_rungs
    from . import output_json

    # autotuned per-rung configs: fused-hetero rungs consult the
    # sidecar store for any knob the campaign didn't pin (explicit
    # params always win inside resolve_knobs); --no-tuned opts out
    tuned_store = None
    if not no_tuned:
        from ..tuning.store import TunedConfigStore

        tuned_store = TunedConfigStore()
        if not tuned_store.enabled:
            tuned_store = None

    dcops, arrays_of = {}, {}
    for _job, path, _it in rows:
        if path not in dcops:
            dcop = load_dcop_from_file(path)
            dcops[path] = dcop
            if FUSABLE_ALGOS[algo] == "factor":
                # arity_sorted: the canonical factor-major edge layout
                # pad_to re-emits, and the same build the solve CLI uses
                arrays_of[path] = FactorGraphArrays.build(
                    dcop, arity_sorted=True,
                    precision=params.get("precision"))
            else:
                arrays_of[path] = HypergraphArrays.build(
                    filter_dcop(dcop),
                    precision=params.get("precision"))

    # sub-group by topology: same-shape instances share a program as-is
    by_topo: Dict[Tuple, List] = {}
    for row in rows:
        sig = _topology_signature(arrays_of[row[1]])
        by_topo.setdefault(sig, []).append(row)

    def emit(sub, sel_rows, costs, viols, cycles, finished, elapsed,
             extra_of, tag, cycle_metrics=None):
        """Per-job result files from the batched outputs.  Costs and
        violation counts arrive from the runner's ONE vmapped device
        evaluation (``runner.evaluate``); the host only decodes value
        names.  ``cycle_metrics`` (per-instance record lists from the
        runner's telemetry planes) land in the campaign jsonl
        attributed per job and per rung."""
        for i, (job_id, path, _it) in enumerate(sub):
            dcop = dcops[path]
            var_names = arrays_of[path].var_names
            assignment = {
                n: dcop.variable(n).domain.values[int(v)]
                for n, v in zip(var_names, sel_rows[i])
            }
            result = {
                "status": ("FINISHED" if bool(finished[i])
                           else "MAX_CYCLES"),
                "assignment": assignment,
                "cost": float(costs[i]),
                "violation": int(viols[i]),
                "cycle": int(cycles[i]),
                # amortized: the whole sub-group ran as one program
                "time": elapsed / len(sub),
                "msg_count": 0,
                "msg_size": 0,
                "fused_batch": len(sub),
            }
            if precision_name:
                result["precision"] = precision_name
            extra = extra_of(path)
            result.update(extra)
            if consolidated_out:
                _append_jsonl(consolidated_out, job_id, result)
            else:
                out_path = os.path.join(out_dir, f"{job_id}.json")
                output_json(result, out_path, quiet=True)
            if reporter is not None:
                attrib = {"job_id": job_id}
                if "fuse_rung" in extra:
                    attrib["fuse_rung"] = extra["fuse_rung"]
                if cycle_metrics is not None:
                    reporter.cycles(cycle_metrics[i], **attrib)
                summary_extra = dict(attrib)
                if "tuning" in extra:
                    # per-knob resolved source (tuned/explicit/
                    # default): schema minor 9
                    summary_extra["tuning"] = extra["tuning"]
                reporter.summary(
                    status=result["status"], cost=result["cost"],
                    violation=result["violation"],
                    cycle=result["cycle"], time=result["time"],
                    fused_batch=len(sub), **summary_extra)
            if register_many is None:
                register_done(job_id)
            print(f"[ok] {job_id} ({tag} x{len(sub)}, "
                  f"{elapsed:.1f}s total)")
        if register_many is not None:
            # one atomic registration per rung, AFTER every result
            # landed: a kill leaves the rung either wholly registered
            # (resume skips it) or wholly unregistered (resume
            # re-forms the SAME job set, so its checkpoint name
            # matches and the snapshot restores) — never a partial
            # survivor set that would orphan the rung snapshot
            register_many([job_id for job_id, _p, _it in sub])

    def row_seeds(sub):
        return [int(explicit_seed) if explicit_seed is not None
                else it for _j, _p, it in sub]

    def run_exact(sub, extra_of=lambda path: {}, tag="fused"):
        """Same-topology sub-group: one vmapped program over stacked
        (or broadcast) cost cubes, the pre-hetero fast path."""
        template = arrays_of[sub[0][1]]
        if len({path for _j, path, _it in sub}) == 1:
            # repeated iterations of ONE instance: the batched solvers
            # broadcast a single cube set across the batch axis — no
            # N identical host/device copies (1024 iterations of a big
            # instance would otherwise stack gigabytes)
            cubes_batches = None
        else:
            cubes_batches = [
                np.stack([np.asarray(arrays_of[path].buckets[i].cubes)
                          for _j, path, _it in sub])
                for i in range(len(template.buckets))
            ]
        cls = {"maxsum": BatchedMaxSum, "dsa": BatchedDsa,
               "mgm": BatchedMgm}[algo]
        runner = cls(template, cubes_batches=cubes_batches,
                     batch=len(sub), **params)
        ck = _rung_checkpointer(checkpoint, checkpoint_every, algo,
                                sub, precision_name)
        t0 = time.perf_counter()
        sel, cycles, finished = runner.run(
            max_cycles=max_cycles, seeds=row_seeds(sub),
            collect_metrics=reporter is not None and ck is None,
            checkpointer=ck, resume=checkpoint_resume)
        costs, viols = runner.evaluate(sel)
        elapsed = time.perf_counter() - t0
        emit(sub, list(sel), costs, viols, cycles, finished, elapsed,
             extra_of, tag,
             cycle_metrics=runner.last_cycle_metrics
             if reporter is not None and ck is None else None)
        if ck is not None:
            # every job of the rung is registered done: the snapshot
            # has nothing left to protect
            ck.store.delete(ck.name)

    topo_groups = list(by_topo.values())
    if not (hetero and len(topo_groups) > 1):
        for sub in topo_groups:
            run_exact(sub)
        return

    # ---- shape-bucketed hetero fusion: pad distinct topologies into a
    # power-of-two ladder and run each rung as ONE vmapped program
    templates = [arrays_of[sub[0][1]] for sub in topo_groups]
    profiles = [ShapeProfile.of(t) for t in templates]
    # rung memory is priced at the policy's store itemsize: a bf16
    # campaign advertises 2-byte cells, so a --max_rung_mb budget
    # admits rungs twice as large (fewer compiled programs)
    rungs = plan_rungs(
        profiles,
        max_rung_bytes=(None if max_rung_mb is None
                        else int(max_rung_mb * 2 ** 20)),
        bytes_per_cell=policy.store_itemsize,
        reserve=reserve)
    programs = 0
    job_true = job_padded = 0
    for ri, rung in enumerate(rungs):
        if len(rung.members) == 1:
            # a rung of one topology needs no padding at all
            sub = topo_groups[rung.members[0]]
            run_exact(sub,
                      lambda path, ri=ri: dict(
                          {"fuse_rung": ri, "padding_waste": 1.0},
                          **({"reserve": reserve} if reserve
                             else {})))
            programs += 1
            job_true += profiles[rung.members[0]].cells * len(sub)
            job_padded += profiles[rung.members[0]].cells * len(sub)
            continue
        padded_of = {}           # path -> padded arrays (shared by rows)
        waste_of = {}
        sub = []
        for ti in rung.members:
            grp = topo_groups[ti]
            tpl = templates[ti]
            padded = rung.pad(tpl)
            for _j, path, _it in grp:
                padded_of[path] = padded
                waste_of[path] = round(rung.waste_for(profiles[ti]), 3)
            sub.extend(grp)
            job_true += profiles[ti].cells * len(grp)
            job_padded += rung.cells * len(grp)
        instances = [padded_of[path] for _j, path, _it in sub]
        runner = runner_for_rung(algo, instances, params,
                                 rung_signature=rung.signature,
                                 tuned_store=tuned_store)
        tuning_sources = getattr(runner, "tuning_sources", None)
        ck = _rung_checkpointer(checkpoint, checkpoint_every, algo,
                                sub, precision_name)
        t0 = time.perf_counter()
        sel, cycles, finished = runner.run(
            max_cycles=max_cycles, seeds=row_seeds(sub),
            collect_metrics=reporter is not None and ck is None,
            checkpointer=ck, resume=checkpoint_resume)
        # ONE vmapped device evaluation per rung (phantom rows
        # contribute exactly zero, so padded costs == true costs)
        costs, viols = runner.evaluate(sel)
        elapsed = time.perf_counter() - t0
        # masked decode: phantom variables never reach the results
        emit(sub, runner.decode(sel), costs, viols, cycles, finished,
             elapsed,
             lambda path, ri=ri, ts=tuning_sources: dict(
                 {"fuse_rung": ri,
                  "padding_waste": waste_of[path]},
                 **({"reserve": reserve} if reserve else {}),
                 **({"tuning": ts} if ts else {})),
             "fused-hetero",
             cycle_metrics=runner.last_cycle_metrics
             if reporter is not None and ck is None else None)
        if ck is not None:
            ck.store.delete(ck.name)
        programs += 1
    # one parsable stats line per group: the bench_hetero_batch
    # program-count contract reads it, campaign authors grep it
    print(f"[fuse-hetero] jobs={len(rows)} programs={programs} "
          f"rungs={len(rungs)} "
          f"waste={job_padded / max(job_true, 1):.3f}"
          + (f" reserve={reserve}" if reserve else ""))


def _fused_child_main(argv=None) -> int:
    """Child entry for one fused group (`python -m
    pydcop_tpu.commands.batch <spec.json>`): isolates the vmapped run
    so the parent can enforce --job_timeout with a kill, exactly like
    the subprocess job path."""
    import json

    spec_path = (argv or sys.argv[1:])[0]
    with open(spec_path) as f:
        spec = json.load(f)
    key = (spec["key"][0], tuple(spec["key"][1]), spec["key"][2],
           spec["key"][3])
    rows = [tuple(r) for r in spec["rows"]]

    def register_done(job_id):
        register_progress(spec["progress_path"], job_id)

    def register_many(job_ids):
        register_progress_many(spec["progress_path"], job_ids)

    # rung-atomic registration ONLY under --checkpoint, where the
    # snapshot name hashes the rung's job set and a partial survivor
    # set would orphan it; without checkpointing the historical
    # per-job registration keeps the re-emit window (duplicate
    # consolidated rows after a kill mid-rung) at one job, not a rung
    _run_fused_group(key, rows, spec["out_dir"], register_done,
                     register_many=(register_many
                                    if spec.get("checkpoint")
                                    else None),
                     consolidated_out=spec.get("consolidated_out"),
                     hetero=spec.get("hetero", False),
                     no_tuned=spec.get("no_tuned", False),
                     precision=spec.get("precision"),
                     max_rung_mb=spec.get("max_rung_mb"),
                     telemetry=spec.get("telemetry"),
                     decimation=spec.get("decimation"),
                     reserve=spec.get("reserve"),
                     checkpoint=spec.get("checkpoint"),
                     checkpoint_every=spec.get("checkpoint_every"),
                     checkpoint_resume=spec.get("checkpoint_resume",
                                                False))
    return 0


def run_cmd(args, timeout=None):
    from ..ops.precision import ENV_VAR as _PRECISION_ENV
    from ..ops.precision import resolve as _resolve_precision
    from .solve import parse_decimation_flag

    # fail the campaign up front on a malformed --decimation instead
    # of letting every job die on it
    parse_decimation_flag(getattr(args, "decimation", None))
    if getattr(args, "portfolio", None):
        # same rule for the arm-grid grammar: every arm names its
        # family explicitly, so the spec validates without a base algo
        from ..parallel.portfolio import (PortfolioSpecError,
                                          parse_portfolio_spec)

        try:
            parse_portfolio_spec(args.portfolio)
        except PortfolioSpecError as e:
            raise CliError(str(e))
    if os.environ.get(_PRECISION_ENV):
        # fail the campaign up front on a malformed environment value
        # instead of letting every fused child / solve job die on it
        try:
            _resolve_precision(os.environ[_PRECISION_ENV])
        except ValueError as e:
            raise CliError(str(e))
    if getattr(args, "reserve_slots", None):
        # same rule for a malformed --reserve-slots grammar: die at
        # campaign startup, not inside every fused child
        from ..parallel.bucketing import parse_reserve

        try:
            parse_reserve(args.reserve_slots)
        except ValueError as e:
            raise CliError(str(e))
    with open(args.bench_def) as f:
        bench_def = yaml.safe_load(f)
    jobs = expand_jobs(bench_def)
    if args.simulate:
        for job_id, argv, _meta in jobs:
            print(job_id, "->", " ".join(shlex.quote(a) for a in argv))
        print(f"{len(jobs)} jobs")
        return 0
    os.makedirs(args.out_dir, exist_ok=True)
    progress_path = os.path.join(args.out_dir, PROGRESS_FILE)
    done = read_progress(progress_path)
    todo = [job for job in jobs if job[0] not in done]
    print(f"{len(jobs)} jobs, {len(done)} done, {len(todo)} to run")

    import threading
    from concurrent.futures import ThreadPoolExecutor

    progress_lock = threading.Lock()

    def register_done(job_id):
        # atomic rewrite (see register_progress): a kill mid-write
        # can no longer truncate the campaign's resume state
        with progress_lock:
            register_progress(progress_path, job_id)

    # partition: fusable engine-solve jobs by group key (>= 2 rows,
    # else the subprocess path is simpler and equally fast)
    fused_groups: Dict[Tuple, List] = {}
    if getattr(args, "fuse", True):
        fallbacks: Dict[Tuple, int] = {}
        campaign_bnb = bool(getattr(args, "bnb", False))
        campaign_portfolio = bool(getattr(args, "portfolio", None))
        for job_id, _argv, meta in todo:
            fkey = _fuse_group_key(meta, campaign_bnb,
                                   campaign_portfolio)
            if fkey is not None:
                fused_groups.setdefault(fkey, []).append(
                    (job_id, meta["path"], meta["iteration"]))
            else:
                reason = _fuse_exclusion_reason(meta, campaign_bnb,
                                                campaign_portfolio)
                k = (reason, meta["conf"].get("algo"),
                     meta["conf"].get("mode", "engine"))
                fallbacks[k] = fallbacks.get(k, 0) + 1
        # name WHY each excluded group takes the subprocess path — a
        # silently-degraded campaign (e.g. one per-job `timeout` key)
        # used to be indistinguishable from a fused one
        for (reason, f_algo, f_mode), n in sorted(fallbacks.items()):
            print(f"[fuse fallback] {n} job(s) (algo={f_algo}, "
                  f"mode={f_mode}): {reason}")
    singletons = sum(1 for v in fused_groups.values() if len(v) < 2)
    if singletons:
        # these ARE fusable but alone in their group: say so instead
        # of silently handing them to the subprocess pool
        print(f"[fuse fallback] {singletons} job(s): group of one "
              "(fusion needs >= 2 jobs sharing command options)")
    fused_groups = {k: v for k, v in fused_groups.items()
                    if len(v) >= 2}
    fused_ids = {job_id for rows in fused_groups.values()
                 for job_id, _p, _i in rows}
    for gi, (fkey, rows) in enumerate(fused_groups.items()):
        # one child process per group: --job_timeout bounds the WHOLE
        # fused group (fusion's amortization promise: a group costs
        # about one job) and a kill cannot corrupt the parent
        import json as _json

        spec_path = os.path.join(args.out_dir, f".fused_{gi}.json")
        with open(spec_path, "w") as f:
            _json.dump({"key": list(fkey), "rows": [list(r)
                                                    for r in rows],
                        "out_dir": args.out_dir,
                        "progress_path": progress_path,
                        "hetero": getattr(args, "fuse_hetero", False),
                        "no_tuned": getattr(args, "no_tuned", False),
                        "precision": getattr(args, "precision", None),
                        "decimation": getattr(args, "decimation",
                                              None),
                        "max_rung_mb": getattr(args, "max_rung_mb",
                                               None),
                        "reserve": getattr(args, "reserve_slots",
                                           None),
                        "telemetry": getattr(args, "telemetry", None),
                        "checkpoint": getattr(args, "checkpoint",
                                              None),
                        "checkpoint_every": getattr(
                            args, "checkpoint_every", None),
                        "checkpoint_resume": getattr(
                            args, "resume", False),
                        "consolidated_out": getattr(
                            args, "consolidated_out", None)}, f)
        failure = None
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "pydcop_tpu.commands.batch",
                 spec_path], capture_output=True, text=True,
                timeout=args.job_timeout)
            sys.stdout.write(proc.stdout)
            if proc.returncode != 0:
                failure = (proc.stderr.strip().splitlines()
                           or ["no output"])[-1][:300]
        except subprocess.TimeoutExpired:
            failure = f"fused group timed out ({args.job_timeout}s)"
        finally:
            try:
                os.remove(spec_path)
            except OSError:
                pass
        if failure is not None:
            print(f"[fuse FAIL -> subprocess fallback] {fkey}: "
                  f"{failure}", file=sys.stderr)
            # the child registers each job as it completes: only rows
            # it did NOT finish return to the subprocess path (never
            # re-run — and overwrite — an already-registered result)
            registered = read_progress(progress_path)
            fused_ids -= ({job_id for job_id, _p, _i in rows}
                          - registered)
    todo = [job for job in jobs
            if job[0] not in done and job[0] not in fused_ids]

    consolidated_out = getattr(args, "consolidated_out", None)
    telemetry_out = getattr(args, "telemetry", None)

    def run_one(job):
        job_id, argv, _meta = job
        out_path = os.path.join(args.out_dir, f"{job_id}.json")
        argv = argv[:3] + ["--output", out_path] + argv[3:]
        conf = _meta["conf"]
        # -p and --algo_params are the same solve option: a campaign
        # may spell the key either way in command_options
        ap = list(conf.get("algo_params", []) if isinstance(
            conf.get("algo_params", []), list)
            else [conf.get("algo_params")])
        short = conf.get("p", [])
        ap += short if isinstance(short, list) else [short]
        job_has_precision = "precision" in conf or any(
            str(p).strip().startswith("precision:") for p in ap)
        if getattr(args, "precision", None) \
                and _meta["command"] == "solve" \
                and not job_has_precision:
            # campaign-level policy for subprocess solve jobs too; a
            # job's own precision setting wins (trailing options are
            # fine after the positional files)
            argv += ["--precision", args.precision]
        if getattr(args, "checkpoint", None) \
                and _meta["command"] == "solve" \
                and conf.get("mode", "engine") in ("engine",
                                                   "sharded") \
                and not _solve_direct_algo(conf.get("algo")):
            # (the exact one-shot sweeps have no chunk boundaries to
            # snapshot at — solve rejects the flag for them)
            # subprocess solve jobs ride the same checkpoint
            # directory (per-job snapshot names, see
            # robustness/checkpoint.solve_checkpoint_name); a
            # resumed campaign continues them mid-solve too
            argv += ["--checkpoint", args.checkpoint,
                     "--checkpoint-every",
                     str(getattr(args, "checkpoint_every", 256))]
            if getattr(args, "resume", False):
                argv += ["--resume"]
        if _meta["command"] == "solve" \
                and conf.get("algo") == "maxsum":
            # campaign-level decimation/bnb for subprocess maxsum
            # jobs; a job's own algo-param wins
            if getattr(args, "decimation", None) and not any(
                    str(p).strip().startswith("decimation_p:")
                    for p in ap):
                argv += ["--decimation", args.decimation]
            if getattr(args, "bnb", False) and not any(
                    str(p).strip().startswith("bnb:") for p in ap):
                argv += ["--bnb"]
        if getattr(args, "portfolio", None) \
                and _meta["command"] == "solve" \
                and conf.get("mode", "engine") == "engine" \
                and conf.get("algo") in FUSABLE_ALGOS \
                and not conf.get("portfolio"):
            # campaign-level arm races for subprocess solve jobs; a
            # job's own portfolio option wins (solve --portfolio
            # requires engine mode and a racing-capable base algo)
            argv += ["--portfolio", args.portfolio]
        t0 = time.perf_counter()
        failure = None
        try:
            proc = subprocess.run(
                argv, capture_output=True, text=True,
                timeout=args.job_timeout)
            if proc.returncode != 0:
                failure = (f"exit {proc.returncode}\n--- stdout ---\n"
                           f"{proc.stdout}\n--- stderr ---\n"
                           f"{proc.stderr}")
        except subprocess.TimeoutExpired:
            failure = f"timed out after {args.job_timeout}s"
        if failure is None and (consolidated_out or telemetry_out):
            import json as _json

            try:
                with open(out_path) as f:
                    result = _json.load(f)
                if telemetry_out:
                    # subprocess jobs contribute their summary record
                    # to the campaign telemetry (cycle metrics live in
                    # the fused path; a subprocess child writes only
                    # its own --telemetry file when asked per job)
                    from ..observability.report import RunReporter

                    rep = RunReporter(
                        telemetry_out,
                        algo=_meta["conf"].get("algo", "unknown"),
                        mode="batch-subprocess")
                    try:
                        rep.summary(
                            job_id=job_id,
                            status=result.get("status"),
                            cost=result.get("cost"),
                            violation=result.get("violation"),
                            cycle=result.get("cycle"),
                            time=result.get("time"))
                    finally:
                        rep.close()
                if consolidated_out:
                    # opt-in jsonl stream: fold the job's result file
                    # into one consolidated line and drop the per-job
                    # artifact
                    _append_jsonl(consolidated_out, job_id, result)
                    os.remove(out_path)
            except (OSError, ValueError) as e:
                failure = f"consolidated/telemetry fold failed: {e}"
        if failure is None:
            # register immediately (not in submission order) so an
            # interrupted --parallel campaign never re-runs a finished
            # job on resume (reference: batch.py:501)
            register_done(job_id)
        else:
            with open(os.path.join(args.out_dir,
                                   f"{job_id}.log"), "w") as f:
                f.write(failure)
        print(f"[{'ok' if failure is None else 'FAIL'}] {job_id} "
              f"({time.perf_counter() - t0:.1f}s)")
        return failure is None

    with ThreadPoolExecutor(max_workers=max(1, args.parallel)) as pool:
        outcomes = list(pool.map(run_one, todo))
    failed = outcomes.count(False)
    if failed:
        print(f"{failed}/{len(outcomes)} jobs failed "
              f"(see *.log in {args.out_dir})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(_fused_child_main())

"""``pydcop batch``: benchmark campaign runner.

reference parity: pydcop/commands/batch.py:55-751 — job expansion from a
YAML of parameter grids, per-job subprocess with timeout + kill,
resume via a progress file, ``--simulate`` dry-run.  TPU-first
improvement: jobs can run in parallel (``--parallel N``), resolving the
reference's acknowledged TODO (batch.py:68).

Definition format::

    sets:
      set1:
        path: "instances/*.yaml"     # glob of problem files
        iterations: 2                # optional, default 1
    batches:
      bench_maxsum:
        command: solve               # any pydcop subcommand
        command_options:
          algo: [maxsum, dsa]        # lists = cartesian product
          algo_params: ["damping:0.5"]
          timeout: 5
    global_options:
      timeout: 10                    # defaults for every job
"""

import glob
import itertools
import os
import shlex
import subprocess
import sys
import time
from typing import Any, Dict, Iterator, List, Tuple

import yaml

from . import CliError

PROGRESS_FILE = "batch_progress.txt"


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "batch", help="run a benchmark campaign from a yaml definition")
    parser.add_argument("bench_def", type=str,
                        help="yaml benchmark definition")
    parser.add_argument("--simulate", action="store_true",
                        help="print the jobs without running them")
    parser.add_argument("--parallel", type=int, default=1,
                        help="number of jobs to run concurrently")
    parser.add_argument("--job_timeout", type=float, default=300)
    parser.add_argument("--dir", dest="out_dir", default="batch_out",
                        help="output directory for job results")
    parser.set_defaults(func=run_cmd)
    return parser


def parameters_configuration(options: Dict[str, Any]
                             ) -> Iterator[Dict[str, Any]]:
    """Cartesian product over list-valued options
    (reference: batch.py:652)."""
    keys = sorted(options)
    value_lists = [
        options[k] if isinstance(options[k], list) else [options[k]]
        for k in keys]
    for combo in itertools.product(*value_lists):
        yield dict(zip(keys, combo))


def expand_jobs(bench_def: Dict) -> List[Tuple[str, List[str]]]:
    """All (job_id, argv) pairs of the campaign."""
    sets = bench_def.get("sets", {"default": {"path": None}})
    batches = bench_def.get("batches")
    if not batches:
        raise CliError("benchmark definition needs a 'batches' section")
    global_opts = bench_def.get("global_options", {})
    jobs = []
    for set_name, set_def in sets.items():
        paths = (sorted(glob.glob(set_def["path"]))
                 if set_def.get("path") else [None])
        if set_def.get("path") and not paths:
            raise CliError(
                f"Set {set_name}: no file matches {set_def['path']}")
        iterations = int(set_def.get("iterations", 1))
        for batch_name, batch_def in batches.items():
            command = batch_def.get("command", "solve")
            options = dict(global_opts)
            options.update(batch_def.get("command_options", {}))
            for path in paths:
                for conf in parameters_configuration(options):
                    for it in range(iterations):
                        job_id = _job_id(set_name, batch_name, path,
                                         conf, it)
                        argv = _job_argv(command, path, conf)
                        jobs.append((job_id, argv))
    return jobs


def _job_id(set_name, batch_name, path, conf, iteration) -> str:
    # ',' joins the k=v pairs: it cannot appear in CLI flag names and
    # is filename-safe, so consolidate can split the params segment
    # unambiguously even when keys or values contain '_'
    conf_s = ",".join(
        f"{k}={v}" for k, v in sorted(conf.items())
        if k not in ("timeout",))
    base = os.path.basename(path) if path else "nofile"
    return f"{set_name}__{batch_name}__{base}__{conf_s}__{iteration}" \
        .replace("/", "-").replace(" ", "")


def _job_argv(command: str, path, conf: Dict[str, Any]) -> List[str]:
    argv = [sys.executable, "-m", "pydcop_tpu.dcop_cli"]
    timeout = conf.get("timeout")
    if timeout is not None:
        argv += ["--timeout", str(timeout)]
    argv.append(command)
    for k, v in sorted(conf.items()):
        if k == "timeout":
            continue
        flag = f"--{k}" if len(k) > 1 else f"-{k}"
        if isinstance(v, bool):
            if v:
                argv.append(flag)
        elif isinstance(v, list):
            for item in v:
                argv += [flag, str(item)]
        else:
            argv += [flag, str(v)]
    if path:
        argv.append(path)
    return argv


def run_cmd(args, timeout=None):
    with open(args.bench_def) as f:
        bench_def = yaml.safe_load(f)
    jobs = expand_jobs(bench_def)
    if args.simulate:
        for job_id, argv in jobs:
            print(job_id, "->", " ".join(shlex.quote(a) for a in argv))
        print(f"{len(jobs)} jobs")
        return 0
    os.makedirs(args.out_dir, exist_ok=True)
    progress_path = os.path.join(args.out_dir, PROGRESS_FILE)
    done = set()
    if os.path.exists(progress_path):
        with open(progress_path) as f:
            done = {line.strip() for line in f if line.strip()}
    todo = [(j, a) for j, a in jobs if j not in done]
    print(f"{len(jobs)} jobs, {len(done)} done, {len(todo)} to run")

    import threading
    from concurrent.futures import ThreadPoolExecutor

    progress_lock = threading.Lock()

    def run_one(job):
        job_id, argv = job
        out_path = os.path.join(args.out_dir, f"{job_id}.json")
        argv = argv[:3] + ["--output", out_path] + argv[3:]
        t0 = time.perf_counter()
        failure = None
        try:
            proc = subprocess.run(
                argv, capture_output=True, text=True,
                timeout=args.job_timeout)
            if proc.returncode != 0:
                failure = (f"exit {proc.returncode}\n--- stdout ---\n"
                           f"{proc.stdout}\n--- stderr ---\n"
                           f"{proc.stderr}")
        except subprocess.TimeoutExpired:
            failure = f"timed out after {args.job_timeout}s"
        if failure is None:
            # register_job immediately (not in submission order) so an
            # interrupted --parallel campaign never re-runs a finished
            # job on resume (reference: batch.py:501)
            with progress_lock, open(progress_path, "a") as f:
                f.write(job_id + "\n")
        else:
            with open(os.path.join(args.out_dir,
                                   f"{job_id}.log"), "w") as f:
                f.write(failure)
        print(f"[{'ok' if failure is None else 'FAIL'}] {job_id} "
              f"({time.perf_counter() - t0:.1f}s)")
        return failure is None

    with ThreadPoolExecutor(max_workers=max(1, args.parallel)) as pool:
        outcomes = list(pool.map(run_one, todo))
    failed = outcomes.count(False)
    if failed:
        print(f"{failed}/{len(outcomes)} jobs failed "
              f"(see *.log in {args.out_dir})", file=sys.stderr)
        return 1
    return 0

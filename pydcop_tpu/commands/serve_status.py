"""``pydcop serve-status``: ask a running daemon for its snapshot.

The operator's one-liner over the daemon's ``stats`` request
(``serving/schema.py STATS_FIELDS``): connect to the unix socket a
``pydcop serve --socket PATH`` daemon listens on, send one stats
line, pretty-print the snapshot — queue depth, lifetime stats and
rates, cache effectiveness, memory accounting, and the registry's
latency quantiles.  ``--json`` dumps the raw snapshot for scripts;
for HTTP-side scraping the same payload lives at
``serve --metrics-port``'s ``/stats`` endpoint.

Fleet-aware (ISSUE 19): ``--socket`` is repeatable — each socket
gets its own labeled section and a final aggregated view sums the
lifetime counters and queue depths across them.  Pointing one
``--socket`` at a ``pydcop fleet`` router renders the router's own
aggregation plus the per-worker snapshots that rode along in its
reply.
"""

import json
import socket

from . import CliError


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "serve-status",
        help="query a running serve daemon's operational snapshot "
             "(queue depth, rates, latency quantiles, memory) over "
             "its unix socket")
    parser.add_argument("--socket", dest="sockets", type=str,
                        required=True, action="append",
                        metavar="PATH",
                        help="a daemon's --socket path; repeatable "
                             "— with several sockets (e.g. one per "
                             "fleet worker) each renders its own "
                             "section plus one aggregated view")
    parser.add_argument("--json", dest="as_json", action="store_true",
                        help="print the raw JSON snapshot instead of "
                             "the human summary")
    parser.add_argument("--connect-timeout", dest="connect_timeout",
                        type=float, default=5.0, metavar="S",
                        help="socket connect/read timeout (s)")
    parser.set_defaults(func=run_cmd)
    return parser


def fetch_status(path: str, timeout: float = 5.0) -> dict:
    """One stats round-trip over the daemon socket; raises
    ``CliError`` with an actionable message on every failure mode
    (no daemon, wrong path, a daemon that never answers)."""
    import os

    request = json.dumps({"op": "stats",
                          "id": f"status-{os.getpid()}"})
    try:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(timeout)
        conn.connect(path)
    except OSError as e:
        raise CliError(
            f"cannot connect to serve daemon at {path}: {e}")
    try:
        conn.sendall((request + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = conn.recv(65536)
            if not chunk:
                raise CliError(
                    f"daemon at {path} closed the connection "
                    f"without answering the stats request")
            buf += chunk
    except socket.timeout:
        raise CliError(
            f"daemon at {path} did not answer the stats request "
            f"within {timeout}s")
    finally:
        conn.close()
    try:
        snap = json.loads(buf.decode())
    except ValueError as e:
        raise CliError(f"unparseable stats reply: {e}")
    # the reply must BE a stats snapshot before it is rendered as
    # one: a daemon predating the stats op (or any rejection path)
    # answers with a REJECTED summary, and rendering that as a
    # healthy idle daemon would hide a live, loaded service
    if not (isinstance(snap, dict) and snap.get("record") == "serve"
            and snap.get("event") == "stats"):
        if isinstance(snap, dict):
            detail = snap.get("error") or (
                f"got record={snap.get('record')!r} "
                f"status={snap.get('status')!r}")
        else:
            detail = f"got {type(snap).__name__}"
        raise CliError(
            f"daemon at {path} did not answer with a stats "
            f"snapshot ({detail}); is it an older daemon without "
            f"the stats op?")
    return snap


def human_bytes(n) -> str:
    if n is None:
        return "n/a"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.0f} {unit}" if unit == "B"
                    else f"{n:.1f} {unit}")
        n /= 1024
    return f"{n:.1f} TiB"


def _cache_line(name: str, stats) -> str:
    if not stats:
        return f"  {name:<10} disabled"
    hits = stats.get("hits", 0)
    misses = stats.get("misses", 0)
    total = hits + misses
    rate = f"{100.0 * hits / total:.1f}%" if total else "n/a"
    extras = ", ".join(f"{k}={v}" for k, v in sorted(stats.items())
                       if k not in ("hits", "misses"))
    return (f"  {name:<10} hit-rate {rate} "
            f"(hits={hits}, misses={misses}"
            f"{', ' + extras if extras else ''})")


def aggregate_snapshots(snaps: dict) -> dict:
    """Fold several daemons' snapshots into one fleet-wide view
    (pure function): lifetime counters and queue depths sum, uptime
    takes the longest-lived member.  ``snaps`` maps a label (socket
    path or worker id) to its snapshot."""
    agg_stats: dict = {}
    queue_depth = 0
    uptime = 0.0
    for snap in snaps.values():
        queue_depth += snap.get("queue_depth", 0) or 0
        uptime = max(uptime, snap.get("uptime_s", 0) or 0)
        for k, v in (snap.get("stats") or {}).items():
            if isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                agg_stats[k] = agg_stats.get(k, 0) + v
    return {"record": "serve", "event": "stats",
            "aggregated": sorted(snaps),
            "uptime_s": uptime, "queue_depth": queue_depth,
            "stats": agg_stats}


def render_status(snap: dict) -> str:
    """The human rendering of one stats snapshot (pure function: the
    test tier feeds it canned snapshots)."""
    members = snap.get("aggregated")
    if members:
        head = (f"fleet aggregate over {len(members)} daemon(s) "
                f"(max uptime {snap.get('uptime_s', 0):.1f}s)")
    else:
        who = snap.get("worker_id")
        head = (f"serve daemon status"
                f"{f' [{who}]' if who else ''} "
                f"(uptime {snap.get('uptime_s', 0):.1f}s)")
    lines = [head]
    build = snap.get("build") or {}
    if build:
        lines.append(
            f"  build       pydcop {build.get('version', '?')} | "
            f"jax {build.get('jax', '?')} "
            f"[{build.get('backend', '?')}] | "
            f"schema {build.get('schema', '?')}")
    st = snap.get("stats", {})
    lines.append(
        f"  queue depth {snap.get('queue_depth', 0)} | "
        f"received {st.get('received', 0)}, "
        f"admitted {st.get('admitted', 0)}, "
        f"completed {st.get('completed', 0)}, "
        f"rejected {st.get('rejected', 0)}")
    fleet = snap.get("fleet")
    if fleet is not None:
        # a `pydcop fleet` router snapshot: its routing counters,
        # membership, and the per-worker snapshots that rode along
        router = fleet.get("router") or {}
        lines.append(
            f"  fleet       workers "
            f"{'/'.join(fleet.get('workers') or []) or 'none'} "
            f"(of {'/'.join(fleet.get('members') or []) or '-'}) | "
            f"routed {router.get('routed', 0)}, "
            f"spilled {router.get('spilled', 0)}, "
            f"resent {router.get('resent', 0)}, "
            f"failovers {router.get('failovers', 0)}, "
            f"requeue-merged {router.get('requeue_merged', 0)} | "
            f"in-flight {fleet.get('pending', 0)}")
        for wid, wsnap in sorted(
                (snap.get("workers") or {}).items()):
            wst = wsnap.get("stats") or {}
            lines.append(
                f"    {wid:<8} queue {wsnap.get('queue_depth', 0)}"
                f" | received {wst.get('received', 0)}, "
                f"completed {wst.get('completed', 0)}, "
                f"rejected {wst.get('rejected', 0)} | "
                f"uptime {wsnap.get('uptime_s', 0):.1f}s")
    slo = snap.get("slo")
    if slo:
        # the SLO engine's last evaluation (serve/fleet --slo): one
        # row per objective; on a router snapshot the rows are the
        # worst-worker aggregation
        lines.append(
            "  slo (objective: value / target | burn | budget):")
        for row in slo:
            value = row.get("value")
            burn = row.get("burn_rate")
            budget = row.get("budget_remaining")
            ok = row.get("ok")
            verdict = ("n/a" if ok is None
                       else "ok" if ok else "VIOLATED")
            workers = row.get("workers")
            via = (f"  [worst of {'/'.join(workers)}]"
                   if workers else "")
            lines.append(
                f"    {row.get('objective', '?'):<20} "
                f"{row.get('kind', '?'):<12} "
                f"{'n/a' if value is None else f'{value:.6g}'} / "
                f"{row.get('target', '?'):<8} | "
                f"{'n/a' if burn is None else f'{burn:.2f}'} | "
                f"{'n/a' if budget is None else f'{budget:.0%}'} "
                f"{verdict}{via}")
    fr = snap.get("flightrec")
    if fr:
        lines.append(
            f"  flightrec   {fr.get('events', 0)} event(s) recorded"
            f", {fr.get('ring', 0)} in ring | "
            f"spills {fr.get('spills', 0)}, "
            f"dumps {fr.get('dumps', 0)}"
            + (f" (last: {fr['last_dump_reason']})"
               if fr.get("last_dump_reason") else "")
            + f" | {fr.get('path', '?')}")
    for name in ("runner_cache", "exec_cache", "instance_cache",
                 "sessions"):
        lines.append(_cache_line(name.replace("_cache", ""),
                                 snap.get(name)))
    ts = snap.get("tuning_store")
    if ts is not None:
        # the autotuned-config store (`pydcop autotune` sidecars):
        # hit/miss/refused counters plus each rung's persisted winner
        # and its age — a stale age after an upgrade says re-tune
        tstats = ts.get("stats") or {}
        lines.append(_cache_line("tuned", tstats))
        for entry in ts.get("entries", []):
            best = entry.get("best") or {}
            label = (",".join(f"{k}:{v}" for k, v in sorted(
                best.items())) or "default")
            age = entry.get("age_s")
            lines.append(
                f"    {entry.get('algo', '?')}/"
                f"{entry.get('rung_label') or '?':<20} "
                f"{label:<28} "
                f"age {'n/a' if age is None else f'{age:.0f}s'}")
    ck = snap.get("checkpoints")
    if ck is not None:
        # the preemption-safety counters (serve --checkpoint):
        # snapshots written/restored, corrupt-quarantined, plus the
        # session-store restore counters and requeued-on-preempt
        sessions = snap.get("sessions") or {}
        lines.append(
            f"  checkpoint  written {ck.get('saved', 0)}, "
            f"restored {ck.get('restored', 0)}, "
            f"corrupt-quarantined {ck.get('corrupt', 0)} | "
            f"session snapshots saved "
            f"{sessions.get('checkpoint_saved', 0)}, restored "
            f"{sessions.get('checkpoint_restored', 0)} | "
            f"requeued-on-preempt {st.get('requeued', 0)}")
    memory = snap.get("memory") or {}
    if memory:
        lines.append("  memory:")
        for k in sorted(memory):
            v = memory[k]
            if isinstance(v, dict):
                continue
            pretty = (human_bytes(v) if k.endswith("bytes")
                      else ("n/a" if v is None else str(v)))
            lines.append(f"    {k:<24} {pretty}")
        for rung, b in sorted(
                (memory.get("runner_cache_by_rung") or {}).items()):
            lines.append(f"      {rung:<22} {human_bytes(b)}")
    metrics = snap.get("metrics") or {}
    roi_af = (metrics.get("gauges") or {}).get(
        "pydcop_roi_active_fraction", {})
    roi_fx = (metrics.get("counters") or {}).get(
        "pydcop_roi_frontier_expansions_total", {})
    if roi_af or roi_fx:
        # region-of-interest warm-solve telemetry (serve --roi):
        # per-target last-dispatch active fraction + total frontier
        # hops the residual gate granted
        lines.append("  roi (active fraction | frontier expansions):")
        for target in sorted(set(roi_af) | set(roi_fx)):
            af = roi_af.get(target)
            fx = roi_fx.get(target, 0)
            lines.append(
                f"    {target or '<all>':<24} "
                f"{'n/a' if af is None else f'{af:.4f}'} | "
                f"{int(fx)}")
    pf_started = (metrics.get("counters") or {}).get(
        "pydcop_portfolio_arms_started_total", {})
    pf_killed = (metrics.get("counters") or {}).get(
        "pydcop_portfolio_arms_killed_total", {})
    pf_margin = (metrics.get("gauges") or {}).get(
        "pydcop_portfolio_win_margin", {})
    if pf_started or pf_killed or pf_margin:
        # arm-race telemetry (portfolio jobs), per base algorithm:
        # started minus killed is the work early-kill reclaimed; a
        # near-zero win margin says the grid's arms are near-ties
        lines.append(
            "  portfolio (arms started / killed | last win margin):")
        for algo in sorted(set(pf_started) | set(pf_killed)
                           | set(pf_margin)):
            wm = pf_margin.get(algo)
            lines.append(
                f"    {algo or '<all>':<24} "
                f"{int(pf_started.get(algo, 0))} / "
                f"{int(pf_killed.get(algo, 0))} | "
                f"{'n/a' if wm is None else f'{wm:.6g}'}")
    hists = metrics.get("histograms", {})
    stage = hists.get("pydcop_serve_stage_seconds", {})
    if stage:
        lines.append("  stage latency (p50 / p99, s):")
        for key in sorted(stage):
            entry = stage[key]
            if not entry.get("count"):
                continue
            lines.append(
                f"    {key:<40} {entry.get('p50', 0):.6f} / "
                f"{entry.get('p99', 0):.6f}  (n={entry['count']})")
    return "\n".join(lines)


def run_cmd(args, timeout=None):
    sockets = args.sockets
    snaps = {path: fetch_status(path, timeout=args.connect_timeout)
             for path in sockets}
    if len(snaps) == 1:
        # single-socket back-compat: the raw snapshot / one section
        snap = next(iter(snaps.values()))
        if args.as_json:
            print(json.dumps(snap, indent=2, sort_keys=True))
        else:
            print(render_status(snap))
        return 0
    agg = aggregate_snapshots(snaps)
    if args.as_json:
        print(json.dumps({"sockets": snaps, "aggregate": agg},
                         indent=2, sort_keys=True))
        return 0
    for path in sockets:
        print(f"== {path} ==")
        print(render_status(snaps[path]))
        print()
    print("== aggregate ==")
    print(render_status(agg))
    return 0

"""``pydcop autotune``: measure the knob grid per rung, persist the
winners.

Three ways to say which rungs to tune — explicit labels
(``--rung factor:d3:v17:a2x32``, the grammar ``serve-status`` and the
dispatch metrics already print), a corpus of DCOP files (grouped by
their ``home_rung``, the same rung each file would dispatch on), or a
serve telemetry JSONL (``--from-telemetry``: replay the rungs a
daemon actually saw).  Every valid config runs through the real
batched runners (warmup + best-of-N medians, successive-halving
pruning); the measured-fastest config and the full ms/cycle table
persist as JSON sidecars beside the executable cache, where
``solve``/``batch --fuse-hetero``/serve dispatch resolve un-pinned
knobs from them (explicit flags always win; see
``docs/analysing_results.md``).
"""

from . import CliError, output_json, parse_algo_params


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "autotune",
        help="benchmark the knob grid per rung through the real "
             "runners and persist the measured-fastest configs "
             "beside the executable cache for dispatch to consume")
    parser.add_argument("corpus", nargs="*", metavar="DCOP_FILE",
                        help="DCOP files whose home rungs to tune "
                             "(measured on the files themselves)")
    parser.add_argument("-a", "--algo", type=str, default="maxsum",
                        help="algorithm family to tune "
                             "(batched families: maxsum, dsa, mgm)")
    parser.add_argument("--rung", action="append", default=None,
                        metavar="LABEL",
                        help="explicit rung label to tune (e.g. "
                             "factor:d3:v17:a2x32; repeatable; "
                             "measured on synthetic instances padded "
                             "to the rung)")
    parser.add_argument("--from-telemetry", dest="from_telemetry",
                        type=str, default=None, metavar="JSONL",
                        help="replay the (algo, rung) pairs a serve "
                             "daemon's telemetry recorded")
    parser.add_argument("-p", "--algo_params", action="append",
                        default=None, metavar="NAME:VALUE",
                        help="pinned params (searched around, never "
                             "overridden — explicit always wins at "
                             "dispatch too)")
    parser.add_argument("--cycles", type=int, default=32,
                        help="full measurement budget per repeat "
                             "(cycles; the halving stage runs a "
                             "quarter of it)")
    parser.add_argument("--repeats", type=int, default=3,
                        help="timed repeats at full budget "
                             "(best-of-N)")
    parser.add_argument("--batch", type=int, default=4,
                        help="synthetic instances per rung for "
                             "--rung/--from-telemetry modes")
    parser.add_argument("--store-dir", dest="store_dir", type=str,
                        default=None, metavar="DIR",
                        help="tuned-store directory (default: the "
                             "'tuned' dir beside the executable "
                             "cache, PYDCOP_TPU_CACHE_DIR-relative)")
    parser.add_argument("--dry-run", dest="dry_run",
                        action="store_true",
                        help="measure and print, persist nothing")
    parser.set_defaults(func=run_cmd)
    return parser


def _coerce(value):
    """``-p name:value`` strings into the types the runner
    constructors expect (the same coercion AlgorithmDef applies on
    the solve path)."""
    low = str(value).strip().lower()
    if low in ("true", "false"):
        return low == "true"
    for cast in (int, float):
        try:
            return cast(value)
        except (TypeError, ValueError):
            continue
    return value


def run_cmd(args, timeout=None):
    from ..engine._cache import ExecutableCache
    from ..tuning.autotune import (autotune, parse_rung_label,
                                   rungs_from_corpus,
                                   rungs_from_telemetry,
                                   synthetic_instances)
    from ..tuning.store import TunedConfigStore

    modes = sum(bool(x) for x in
                (args.corpus, args.rung, args.from_telemetry))
    if modes != 1:
        raise CliError(
            "autotune wants exactly one rung source: DCOP corpus "
            "files, --rung labels, or --from-telemetry JSONL")
    pinned = {k: _coerce(v) for k, v in
              parse_algo_params(args.algo_params).items()}

    rung_sets = []
    try:
        if args.corpus:
            for rung, instances in rungs_from_corpus(
                    args.corpus, args.algo):
                rung_sets.append(
                    (args.algo, rung.signature, instances))
        elif args.rung:
            for label in args.rung:
                sig = parse_rung_label(label)
                rung_sets.append((args.algo, sig, synthetic_instances(
                    sig, args.algo, batch=args.batch)))
        else:
            for algo, sig in rungs_from_telemetry(
                    args.from_telemetry, algo=None):
                rung_sets.append((algo, sig, synthetic_instances(
                    sig, algo, batch=args.batch)))
    except (OSError, ValueError) as e:
        raise CliError(str(e))

    store = None
    if not args.dry_run:
        store = TunedConfigStore(path=args.store_dir)
        if not store.enabled:
            raise CliError(
                f"tuned-config store disabled or unavailable at "
                f"{store.path}; nothing would persist — pass "
                f"--dry-run to measure anyway")
    try:
        results = autotune(
            rung_sets, cycles=args.cycles, repeats=args.repeats,
            pinned=pinned, store=store,
            exec_cache=ExecutableCache(), progress=print)
    except ValueError as e:
        raise CliError(str(e))
    output_json({
        "command": "autotune",
        "algo": args.algo,
        "pinned": pinned,
        "store": None if store is None else store.path,
        "rungs": results,
    }, getattr(args, "output", None), quiet=True)
    summary = {
        "store": None if store is None else store.path,
        "rungs": [
            {"algo": r["algo"], "rung": r["rung_label"],
             "best": r["best_label"],
             "ms_per_cycle": r["best_ms_per_cycle"],
             "default_ms_per_cycle": r["default_ms_per_cycle"],
             "speedup": r["speedup_vs_default"]}
            for r in results],
    }
    output_json(summary)
    return 0

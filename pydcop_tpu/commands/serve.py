"""``pydcop serve``: the persistent solver-as-a-service daemon.

No reference-parity anchor — the reference framework's long-running
shape is its agent/orchestrator runtime; this command is the compiled
data plane's equivalent (ROADMAP: solver-as-a-service).  Jobs arrive
continuously as JSONL (``serving/schema.py``), are admitted onto the
power-of-two bucketing ladder, and dispatch as batched vmapped
programs when a rung fills or the oldest job's latency deadline
expires.  Results and daemon telemetry stream to ``--out`` over the v1
JSONL schema; socket clients additionally receive their own jobs'
records back on their connection.

Three input modes::

    pydcop serve --oneshot jobs.jsonl       # file -> drain -> exit
    cat jobs.jsonl | pydcop serve           # stdin (EOF drains)
    pydcop serve --socket /tmp/pydcop.sock  # unix socket daemon

SIGTERM stops gracefully: the in-flight rung completes, every queued
job is rejected with a structured reason.

The ops plane (ISSUE 11): a metrics registry instruments admission
and dispatch by default (`--no-metrics` disables), `--metrics-port`
exposes it as a Prometheus endpoint, `--heartbeat-s` emits periodic
queue/rate/memory records to ``--out``, a ``stats`` request (or
``pydcop serve-status``) snapshots a running daemon, and every job's
pipeline life is reconstructable from its ``trace_id``.

Warm delta traffic (ISSUE 12): ``delta`` jobs apply in place against
resident device planes (compiled scatter, O(touched-rows) upload per
event) in a byte-budgeted LRU session store — ``--session-budget-mb``
bounds the summed resident bytes, ``--session-cap`` the session
count; eviction closes the engine and the next delta against the
target reopens through the executable cache.
"""

import os
import signal
import sys

from . import CliError


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "serve",
        help="run the persistent solver daemon (JSONL jobs in, "
             "dynamic batching over the rung ladder, JSONL results "
             "out)")
    parser.add_argument("--oneshot", type=str, default=None,
                        metavar="JOBS.jsonl",
                        help="read job requests from this file, drain "
                             "the queue, exit — the daemon's "
                             "socket-free smoke path (CI runs it)")
    parser.add_argument("--socket", type=str, default=None,
                        metavar="PATH",
                        help="accept JSONL job requests on a unix "
                             "domain socket at PATH; each client gets "
                             "its own jobs' result records streamed "
                             "back on its connection.  Default (no "
                             "--socket, no --oneshot): read requests "
                             "from stdin, EOF drains")
    parser.add_argument("--out", type=str, default="serve_out.jsonl",
                        metavar="out.jsonl",
                        help="JSONL output: per-job summary records "
                             "plus serve telemetry records (queue "
                             "depth, wait times, compile/deserialize/"
                             "execute spans, cache counters), same v1 "
                             "schema as solve/batch --telemetry "
                             "(docs/analysing_results.md)")
    parser.add_argument("--max-batch", dest="max_batch", type=int,
                        default=8,
                        help="dispatch a rung as soon as this many "
                             "jobs share it (the rung-fills trigger)")
    parser.add_argument("--max-delay-ms", dest="max_delay_ms",
                        type=float, default=50.0,
                        help="dispatch a rung when its oldest job has "
                             "waited this long even if not full (the "
                             "latency-deadline trigger; per-job "
                             "deadline_ms can only tighten it)")
    parser.add_argument("--max_cycles", "--max-cycles",
                        dest="max_cycles", type=int, default=2000,
                        help="default cycle budget for jobs that do "
                             "not carry max_cycles (same default and "
                             "spelling as solve; the dash alias "
                             "matches this parser's other flags)")
    parser.add_argument("--seed", type=int, default=0,
                        help="default engine seed for jobs without "
                             "one")
    parser.add_argument("--precision", default=None,
                        choices=["f32", "bf16", "auto"],
                        help="default mixed-precision policy for jobs "
                             "that do not request one; jobs carrying "
                             "their own precision keep it (and never "
                             "share a rung with differently-policied "
                             "jobs)")
    parser.add_argument("--reserve-slots", dest="reserve_slots",
                        type=str, default=None,
                        metavar="SPEC",
                        help="explicit phantom-slot headroom every "
                             "admitted rung is provisioned with, as "
                             "'vars:N,ARITY:N' (e.g. vars:8,2:16): "
                             "extra variable rows / per-arity factor "
                             "slots beyond the power-of-two ladder, "
                             "the edit capacity 'delta' jobs activate "
                             "in place.  Part of the rung signature "
                             "(jobs batch only with like-provisioned "
                             "jobs); the remaining budget is echoed "
                             "in delta dispatch telemetry")
    parser.add_argument("--session-budget-mb",
                        dest="session_budget_mb", type=float,
                        default=None, metavar="MB",
                        help="byte budget for the warm delta-session "
                             "store: sessions keep their instance "
                             "planes and message state resident on "
                             "device, and the least-recently-used "
                             "sessions are closed (buffers released, "
                             "evicted bytes counted) whenever the "
                             "summed resident estimate exceeds this "
                             "budget.  An evicted target's next delta "
                             "reopens through the executable cache "
                             "(deserialize, not compile).  Default: "
                             "no byte budget (count cap only)")
    parser.add_argument("--session-cap", dest="session_cap",
                        type=int, default=16, metavar="N",
                        help="maximum number of warm delta sessions "
                             "held open regardless of bytes "
                             "(default 16); LRU eviction past it")
    parser.add_argument("--layout", default="edge_major",
                        choices=["edge_major", "lane_major", "fused",
                                 "auto"],
                        help="warm-engine step layout delta sessions "
                             "open at: edge_major (generic oracle, "
                             "default), lane_major (edges on the "
                             "128-wide lane dim — the TPU-tile "
                             "layout; all event types), fused "
                             "(fastest compiled cycle, ~2x on host "
                             "CPU; cost and variable edits only — "
                             "constraint add/remove rejects "
                             "structurally), auto (lane_major when "
                             "eligible).  All layouts are bit-exact; "
                             "a target job's own -p layout:... "
                             "overrides per session.  Echoed in "
                             "dispatch records and the session "
                             "journal (recovery replays under the "
                             "journaled layout)")
    parser.add_argument("--warm-budget", dest="warm_budget",
                        default="adaptive",
                        choices=["adaptive", "fixed"],
                        help="warm re-solve cycle-budget schedule: "
                             "adaptive (default) dispatches a "
                             "geometric chunk schedule and stops at "
                             "the first chunk boundary where the "
                             "on-device stability rule fired "
                             "(settle_chunk in dispatch records); "
                             "fixed keeps constant chunk_size "
                             "chunks.  Identical selections and "
                             "cycles either way")
    parser.add_argument("--roi", nargs="?", const=True,
                        default=False, metavar="auto",
                        help="region-of-interest warm re-solves for "
                             "delta sessions: each delta's solve "
                             "sweeps only the activity window seeded "
                             "from the touched rows, grown one "
                             "neighborhood hop at chunk boundaries "
                             "while boundary residuals stay hot — "
                             "delta cost scales with the "
                             "perturbation, not instance size.  "
                             "'--roi auto' starts windowed and "
                             "permanently falls back to full sweeps "
                             "for a session whose deltas keep "
                             "touching most of the instance.  "
                             "Dispatch records carry "
                             "active_fraction / frontier_expansions "
                             "(also Prometheus gauges, see "
                             "serve-status)")
    parser.add_argument("--roi-residual-threshold",
                        dest="roi_residual_threshold", type=float,
                        default=None, metavar="EPS",
                        help="--roi frontier gate: grow the active "
                             "region while chunk-boundary residuals "
                             "are >= EPS (default: the solver's "
                             "damping-scaled stability threshold)")
    parser.add_argument("--exec-cache", dest="exec_cache",
                        type=str, default=None, metavar="DIR",
                        help="directory for serialized jax.stages rung "
                             "executables (default: "
                             "$PYDCOP_TPU_CACHE_DIR/executables, i.e. "
                             "~/.cache/pydcop_tpu/executables) — a "
                             "restarted daemon cold-starts a known "
                             "rung by DESERIALIZING it instead of "
                             "retracing+recompiling; "
                             "PYDCOP_TPU_NO_CACHE=1 disables")
    parser.add_argument("--no-exec-cache", dest="no_exec_cache",
                        action="store_true",
                        help="disable the executable cache for this "
                             "daemon (every cold rung recompiles)")
    parser.add_argument("--tuned-store", dest="tuned_store",
                        type=str, default=None, metavar="DIR",
                        help="directory of autotuned per-rung config "
                             "sidecars (`pydcop autotune`; default: "
                             "the 'tuned' dir beside the executable "
                             "cache) — dispatch adopts the measured-"
                             "fastest config for any knob the request "
                             "didn't pin; explicit params always win")
    parser.add_argument("--no-tuned", dest="no_tuned",
                        action="store_true",
                        help="never consult autotuned configs: every "
                             "un-pinned knob stays at its default")
    parser.add_argument("--metrics-port", dest="metrics_port",
                        type=int, default=None, metavar="PORT",
                        help="serve Prometheus metrics over HTTP on "
                             "127.0.0.1:PORT (/metrics: text "
                             "exposition; /stats: the JSON snapshot a "
                             "daemon-socket stats request returns). "
                             "PORT 0 picks an ephemeral port, printed "
                             "to stderr")
    parser.add_argument("--heartbeat-s", dest="heartbeat_s",
                        type=float, default=None, metavar="SECONDS",
                        help="emit a periodic heartbeat serve record "
                             "(queue depth, per-second rates, memory "
                             "accounting) every SECONDS to --out; "
                             "default: no heartbeats")
    parser.add_argument("--fault-plan", dest="fault_plan",
                        type=str, default=None, metavar="FILE",
                        help="inject faults from this JSON plan "
                             "(serving/faults.py: seeded rate + "
                             "explicit schedule over compile_error / "
                             "execute_error / execute_hang / "
                             "cache_corrupt / nan_planes) — the "
                             "deterministic chaos harness.  Absent "
                             "(the default), every injection hook is "
                             "dead code and dispatch behavior is "
                             "byte-identical")
    parser.add_argument("--session-journal", dest="session_journal",
                        type=str, default=None, metavar="DIR",
                        help="journal every warm delta session to "
                             "this directory (append-only fsync'd "
                             "JSONL: base job + each answered "
                             "delta); after a daemon CRASH, a delta "
                             "against a journaled target rebuilds "
                             "the warm engine bit-exactly by "
                             "replaying through the executable "
                             "cache.  Clean shutdown and eviction "
                             "truncate the journal.  Default: no "
                             "journaling")
    parser.add_argument("--checkpoint", type=str, default=None,
                        metavar="DIR",
                        help="preemption-safe serving (ISSUE 15): "
                             "SIGTERM becomes a preemption DRAIN — "
                             "still-queued jobs and unread request "
                             "lines are REQUEUED to DIR/requeue.jsonl "
                             "(atomic, fsync'd) instead of rejected, "
                             "and warm delta sessions keep their "
                             "crash journals plus a post-base-solve "
                             "state snapshot in DIR.  A restarted "
                             "daemon with the same --checkpoint "
                             "re-admits the requeued jobs first and "
                             "rebuilds journaled sessions by restore+"
                             "replay (bit-exact), so preemption "
                             "costs a restart, not the work.  "
                             "Corrupt snapshots are quarantined "
                             "(*.corrupt + counter); counters "
                             "surface in serve-status and as "
                             "pydcop_checkpoint_* metrics")
    parser.add_argument("--execute-deadline-s",
                        dest="execute_deadline_s", type=float,
                        default=None, metavar="SECONDS",
                        help="wall-clock watchdog over each "
                             "dispatch's device span: a dispatch "
                             "exceeding it FAILS (then retries / "
                             "bisects / sheds like any failure) "
                             "instead of freezing the daemon behind "
                             "a hang.  Default: no deadline")
    parser.add_argument("--worker-id", dest="worker_id",
                        type=str, default=None, metavar="ID",
                        help="fleet identity of this daemon (`pydcop "
                             "fleet` sets it): stamps worker_id on "
                             "every record written to --out (schema "
                             "minor 10, so N workers can share one "
                             "out file) and names this worker's "
                             "requeue file requeue-ID.jsonl inside a "
                             "SHARED --checkpoint directory.  "
                             "Default: solo daemon, no stamp, legacy "
                             "requeue.jsonl")
    parser.add_argument("--slo", type=str, default=None,
                        metavar="FILE",
                        help="declarative service-level objectives "
                             "(YAML, observability/slo.py): p99 "
                             "latency per job kind, error rate, queue "
                             "depth.  Evaluated from the metrics "
                             "registry at every heartbeat, emitting "
                             "'slo' records to --out plus "
                             "pydcop_slo_burn_rate / "
                             "pydcop_slo_budget_remaining gauges; "
                             "serve-status renders the table.  "
                             "Needs the registry (not --no-metrics)")
    parser.add_argument("--no-flightrec", dest="no_flightrec",
                        action="store_true",
                        help="disable the crash-surviving flight "
                             "recorder (a bounded in-memory ring of "
                             "recent daemon events, spilled to an "
                             "mmap-backed file beside --out at a "
                             "fixed cadence and dumped eagerly on "
                             "breaker-open / watchdog timeout / "
                             "preempt drain, so `pydcop trace` can "
                             "see a kill -9'd worker's last moments)")
    parser.add_argument("--no-metrics", dest="no_metrics",
                        action="store_true",
                        help="disable the in-process metrics registry "
                             "(counters/gauges/latency histograms); "
                             "the JSONL telemetry in --out is "
                             "unaffected.  Mostly for the bench's "
                             "instrumentation-overhead control")
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..engine._cache import ExecutableCache
    from ..observability.report import RunReporter
    from ..serving.daemon import ServeLoop
    from ..serving.dispatcher import Dispatcher
    from ..serving.queue import AdmissionQueue

    if args.oneshot and args.socket:
        raise CliError("--oneshot and --socket are mutually exclusive")
    if args.max_batch < 1:
        raise CliError("--max-batch must be >= 1")
    if args.max_delay_ms < 0:
        raise CliError("--max-delay-ms must be >= 0")
    roi = getattr(args, "roi", False)
    if isinstance(roi, str) and roi != "auto":
        raise CliError(
            f"--roi takes no value or 'auto', got {roi!r}")
    heartbeat_s = getattr(args, "heartbeat_s", None)
    if heartbeat_s is not None and heartbeat_s <= 0:
        raise CliError("--heartbeat-s must be > 0")
    session_budget_mb = getattr(args, "session_budget_mb", None)
    if session_budget_mb is not None and session_budget_mb <= 0:
        raise CliError("--session-budget-mb must be > 0")
    session_cap = getattr(args, "session_cap", 16)
    if session_cap < 1:
        raise CliError("--session-cap must be >= 1")
    session_budget_bytes = (int(session_budget_mb * 1024 * 1024)
                            if session_budget_mb is not None
                            else None)
    metrics_port = getattr(args, "metrics_port", None)
    if metrics_port is not None and getattr(args, "no_metrics", False):
        raise CliError("--metrics-port needs the registry; drop "
                       "--no-metrics")
    from ..parallel.batch import runner_cache_cap
    from ..parallel.bucketing import parse_reserve

    try:
        # a malformed PYDCOP_TPU_RUNNER_CACHE must kill the daemon at
        # STARTUP, not poison every dispatch's telemetry call later
        runner_cache_cap()
        # same rule for a malformed --reserve-slots grammar
        parse_reserve(getattr(args, "reserve_slots", None))
    except ValueError as e:
        raise CliError(str(e))

    execute_deadline_s = getattr(args, "execute_deadline_s", None)
    if execute_deadline_s is not None and execute_deadline_s <= 0:
        raise CliError("--execute-deadline-s must be > 0")
    faults = None
    fault_plan = getattr(args, "fault_plan", None)
    if fault_plan:
        from ..serving.faults import FaultPlan

        try:
            # a malformed plan kills the daemon at startup with the
            # offending field, never mid-dispatch
            faults = FaultPlan.load(fault_plan)
        except ValueError as e:
            raise CliError(str(e))
    journal = None
    session_journal = getattr(args, "session_journal", None)
    if session_journal:
        from ..dynamics.journal import JournalStore

        try:
            journal = JournalStore(session_journal)
        except OSError as e:
            raise CliError(
                f"--session-journal directory unusable: {e}")

    checkpoints = None
    checkpoint_dir = getattr(args, "checkpoint", None)
    if checkpoint_dir:
        from ..robustness.checkpoint import CheckpointStore

        try:
            checkpoints = CheckpointStore(checkpoint_dir)
        except OSError as e:
            raise CliError(f"--checkpoint directory unusable: {e}")
        if faults is not None:
            checkpoints.faults = faults

    exec_cache = None
    if not args.no_exec_cache:
        exec_cache = ExecutableCache(path=args.exec_cache)
        if faults is not None:
            exec_cache.faults = faults

    # autotuned per-rung configs (`pydcop autotune` sidecars beside
    # the executable cache): dispatch resolves un-pinned knobs from
    # them; --no-tuned (or a disabled cache dir) keeps dispatch on
    # explicit/default resolution only
    tuned_store = None
    if not getattr(args, "no_tuned", False):
        from ..tuning.store import TunedConfigStore

        tuned_store = TunedConfigStore(
            path=getattr(args, "tuned_store", None))
        if not tuned_store.enabled:
            tuned_store = None

    registry = None
    if not getattr(args, "no_metrics", False):
        from ..observability.registry import MetricsRegistry

        registry = MetricsRegistry()
        from ..observability.buildinfo import build_info_metric

        build_info_metric(registry)

    slo_objectives = None
    slo_file = getattr(args, "slo", None)
    if slo_file:
        if registry is None:
            raise CliError("--slo needs the metrics registry; drop "
                           "--no-metrics")
        from ..observability.slo import SLOError, load_objectives

        try:
            # a malformed objectives file kills the daemon at
            # startup naming the offending field, never mid-serve
            slo_objectives = load_objectives(slo_file)
        except SLOError as e:
            raise CliError(str(e))
        except OSError as e:
            raise CliError(f"--slo file unusable: {e}")

    worker_id = getattr(args, "worker_id", None)
    reporter = RunReporter(args.out, algo="serve", mode="serve",
                           worker_id=worker_id)
    flightrec = None
    if not getattr(args, "no_flightrec", False):
        from ..observability.flightrec import (FlightRecorder,
                                               flightrec_path)

        try:
            flightrec = FlightRecorder(
                flightrec_path(os.path.dirname(args.out) or ".",
                               worker_id),
                worker_id=worker_id)
        except OSError as e:
            # best-effort by design: a read-only telemetry dir must
            # not take the daemon down
            print(f"[serve] flight recorder disabled: {e}",
                  file=sys.stderr)
    metrics_server = None
    try:
        reserve = getattr(args, "reserve_slots", None)
        reporter.header(
            max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
            max_cycles=args.max_cycles, precision=args.precision,
            reserve=reserve,
            session_budget_mb=session_budget_mb,
            session_cap=session_cap,
            session_layout=getattr(args, "layout", "edge_major"),
            warm_budget=getattr(args, "warm_budget", "adaptive"),
            exec_cache=(exec_cache.path
                        if exec_cache is not None
                        and exec_cache.enabled else None),
            fault_plan=fault_plan,
            session_journal=session_journal,
            checkpoint=checkpoint_dir,
            execute_deadline_s=execute_deadline_s,
            slo=slo_file,
            source=("oneshot" if args.oneshot
                    else "socket" if args.socket else "stdin"))
        admission = AdmissionQueue(
            max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1000.0)
        dispatcher = Dispatcher(
            reporter=reporter, exec_cache=exec_cache,
            reserve=reserve, registry=registry,
            session_cap=session_cap,
            session_budget_bytes=session_budget_bytes,
            faults=faults, execute_deadline_s=execute_deadline_s,
            journal=journal,
            session_layout=getattr(args, "layout", "edge_major"),
            warm_budget=getattr(args, "warm_budget", "adaptive"),
            checkpoints=checkpoints,
            session_roi=roi,
            roi_residual_threshold=getattr(
                args, "roi_residual_threshold", None),
            tuned_store=tuned_store)
        loop = ServeLoop(admission, dispatcher, reporter=reporter,
                         default_max_cycles=args.max_cycles,
                         default_seed=args.seed,
                         default_precision=args.precision,
                         reserve=reserve,
                         registry=registry,
                         heartbeat_s=heartbeat_s,
                         faults=faults,
                         checkpoints=checkpoints,
                         worker_id=worker_id,
                         slo_objectives=slo_objectives,
                         flightrec=flightrec)
        if checkpoints is not None:
            # a previous daemon's preemption drain left requeued
            # jobs: re-admit them FIRST, ahead of the live sources —
            # continue, don't recompute
            from ..serving.daemon import requeue_take

            requeued = requeue_take(checkpoints.directory,
                                    worker_id=worker_id)
            for line in requeued:
                loop.feed(line)
            if requeued:
                print(f"[serve] re-admitted {len(requeued)} "
                      f"requeued job(s) from {checkpoints.directory}",
                      file=sys.stderr)
        if metrics_port is not None:
            from ..observability.registry import MetricsHTTPServer

            metrics_server = MetricsHTTPServer(
                registry, port=metrics_port,
                snapshot_fn=loop.stats_snapshot)
            print(f"[serve] metrics on "
                  f"http://127.0.0.1:{metrics_server.port}/metrics",
                  file=sys.stderr)

        # the SIGTERM contract: finish the in-flight rung, reject the
        # rest with a structured reason.  Registered here (not in
        # dcop_cli) so only the serve command changes signal behavior
        prev_term = signal.signal(
            signal.SIGTERM, lambda _s, _f: loop.request_stop())
        try:
            if args.oneshot:
                if not os.path.exists(args.oneshot):
                    raise CliError(
                        f"oneshot jobs file not found: {args.oneshot}")
                with open(args.oneshot) as f:
                    stats = loop.run_oneshot(f.readlines())
            elif args.socket:
                from ..serving.sources import SocketServer

                server = SocketServer(loop, args.socket)
                try:
                    stats = loop.run()
                finally:
                    server.close()
            else:
                from ..serving.sources import stdin_source

                stdin_source(loop)
                stats = loop.run()
        finally:
            signal.signal(signal.SIGTERM, prev_term)
        print(f"[serve] received={stats['received']} "
              f"admitted={stats['admitted']} "
              f"completed={stats['completed']} "
              f"rejected={stats['rejected']}", file=sys.stderr)
    finally:
        if metrics_server is not None:
            metrics_server.close()
        if flightrec is not None:
            # final spill so a clean exit leaves the same artifact a
            # crash would — `pydcop trace` reads it either way
            flightrec.dump("shutdown")
            flightrec.close()
        reporter.close()
    return 0

"""``pydcop orchestrator``: standalone orchestrator for multi-machine
deployment.

reference parity: pydcop/commands/orchestrator.py:185-618.  Starts an
orchestrator with an HTTP communication layer; remote ``pydcop agent``
processes join it over the network (DCN in a TPU-pod deployment), then
the DCOP is deployed, run and the result printed.  Carries the same
observability surface as ``pydcop solve``: ``--collect_on`` /
``--period`` select when assignments are observed, ``--run_metrics``
streams them to CSV during the run, ``--end_metrics`` appends one
end-of-run summary row, ``--uiport`` starts the websocket UI server.
"""

import csv
import os
import time

from . import build_algo_def, output_json
from ..dcop.yamldcop import load_dcop_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "orchestrator", help="standalone orchestrator (multi-machine)")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-p", "--algo_params", action="append",
                        default=None)
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--address", default="127.0.0.1",
                        help="address advertised to agents (and bound, "
                             "unless --bind_address is given)")
    parser.add_argument("--bind_address", default=None,
                        help="address to bind the HTTP server to when it "
                             "differs from --address (NAT / container "
                             "port mapping, e.g. 0.0.0.0)")
    parser.add_argument("-c", "--collect_on", default="value_change",
                        choices=["value_change", "cycle_change",
                                 "period"],
                        help="when a new assignment is observed "
                             "(reference: orchestrator.py:219-233)")
    parser.add_argument("--period", type=float, default=None,
                        help="metrics period (seconds) for "
                             "--collect_on period")
    parser.add_argument("--run_metrics", type=str, default=None,
                        help="CSV file streaming run metrics")
    parser.add_argument("--end_metrics", type=str, default=None,
                        help="CSV file to append end-of-run metrics to")
    parser.add_argument("--uiport", type=int, default=None,
                        help="websocket UI server port (none = no UI)")
    parser.add_argument("-s", "--scenario", default=None)
    parser.add_argument("-k", "--ktarget", type=int, default=None)
    parser.add_argument("--deploy_timeout", type=float, default=60,
                        help="max wait for agents to join (s)")
    parser.add_argument("--max_cycles", type=int, default=100000)
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..dcop.yamldcop import load_scenario_from_file
    from ..infrastructure.communication import HttpCommunicationLayer
    from ..infrastructure.orchestrator import Orchestrator
    from ..infrastructure.run import _prepare_run

    t0 = time.perf_counter()
    dcop = load_dcop_from_file(args.dcop_files)
    algo_def = build_algo_def(args.algo, args.algo_params,
                              mode=dcop.objective)
    algo_def, cg, dist = _prepare_run(dcop, algo_def,
                                      args.distribution)
    scenario = (load_scenario_from_file(args.scenario)
                if args.scenario else None)

    collector = None
    if args.run_metrics:
        # lossless stop contract: queue drained, file fsynced,
        # discarded rows counted and warned (observability/collector)
        from ..observability.collector import CsvCollector

        collector = CsvCollector(args.run_metrics)

    comm = HttpCommunicationLayer(
        (args.address, args.port),
        bind_host=getattr(args, "bind_address", None))
    orchestrator = Orchestrator(
        algo_def, cg, dist, comm, dcop=dcop,
        collector=collector,
        collect_moment=args.collect_on,
        collect_period=args.period,
        ui_port=getattr(args, "uiport", None))
    orchestrator.start()
    try:
        orchestrator.deploy_computations(timeout=args.deploy_timeout)
        if args.ktarget:
            orchestrator.start_replication(args.ktarget)
        res = orchestrator.run(scenario=scenario, timeout=timeout,
                               max_cycles=args.max_cycles)
        orchestrator.stop_agents()
        metrics = orchestrator.global_metrics()
        result = {
            "status": res.status if res else orchestrator.status,
            "assignment": metrics["assignment"],
            "cost": metrics["cost"],
            "violation": metrics["violation_count"],
            "cycle": metrics["cycle"],
            "msg_count": metrics["msg_count"],
            "msg_size": metrics["msg_size"],
            "time": time.perf_counter() - t0,
        }
        if args.end_metrics:
            _append_end_metrics(args.end_metrics, result)
        output_json(result, args.output)
    finally:
        if collector is not None:
            collector.stop()
        orchestrator.stop()
    return 0


def _append_end_metrics(path: str, result: dict):
    """One end-of-run summary row, appended (reference:
    commands/orchestrator.py:476-521 end metrics)."""
    new_file = not os.path.exists(path)
    with open(path, "a", newline="") as f:
        writer = csv.writer(f)
        if new_file:
            writer.writerow(["time", "status", "cost", "violation",
                             "cycle", "msg_count", "msg_size"])
        writer.writerow([
            round(result["time"], 4), result["status"], result["cost"],
            result["violation"], result["cycle"], result["msg_count"],
            result["msg_size"],
        ])

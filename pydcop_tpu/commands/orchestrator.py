"""``pydcop orchestrator``: standalone orchestrator for multi-machine
deployment.

reference parity: pydcop/commands/orchestrator.py:185-618.  Starts an
orchestrator with an HTTP communication layer; remote ``pydcop agent``
processes join it over the network (DCN in a TPU-pod deployment), then
the DCOP is deployed, run and the result printed.
"""

import time

from . import build_algo_def, output_json
from ..dcop.yamldcop import load_dcop_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "orchestrator", help="standalone orchestrator (multi-machine)")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-p", "--algo_params", action="append",
                        default=None)
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--address", default="127.0.0.1",
                        help="address advertised to agents (and bound, "
                             "unless --bind_address is given)")
    parser.add_argument("--bind_address", default=None,
                        help="address to bind the HTTP server to when it "
                             "differs from --address (NAT / container "
                             "port mapping, e.g. 0.0.0.0)")
    parser.add_argument("-s", "--scenario", default=None)
    parser.add_argument("-k", "--ktarget", type=int, default=None)
    parser.add_argument("--deploy_timeout", type=float, default=60,
                        help="max wait for agents to join (s)")
    parser.add_argument("--max_cycles", type=int, default=100000)
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..dcop.yamldcop import load_scenario_from_file
    from ..infrastructure.communication import HttpCommunicationLayer
    from ..infrastructure.orchestrator import Orchestrator
    from ..infrastructure.run import _prepare_run

    t0 = time.perf_counter()
    dcop = load_dcop_from_file(args.dcop_files)
    algo_def = build_algo_def(args.algo, args.algo_params,
                              mode=dcop.objective)
    algo_def, cg, dist = _prepare_run(dcop, algo_def,
                                      args.distribution)
    scenario = (load_scenario_from_file(args.scenario)
                if args.scenario else None)
    comm = HttpCommunicationLayer(
        (args.address, args.port),
        bind_host=getattr(args, "bind_address", None))
    orchestrator = Orchestrator(algo_def, cg, dist, comm, dcop=dcop)
    orchestrator.start()
    try:
        orchestrator.deploy_computations(timeout=args.deploy_timeout)
        if args.ktarget:
            orchestrator.start_replication(args.ktarget)
        res = orchestrator.run(scenario=scenario, timeout=timeout,
                               max_cycles=args.max_cycles)
        orchestrator.stop_agents()
        metrics = orchestrator.global_metrics()
        result = {
            "status": res.status if res else orchestrator.status,
            "assignment": metrics["assignment"],
            "cost": metrics["cost"],
            "violation": metrics["violation_count"],
            "cycle": metrics["cycle"],
            "msg_count": metrics["msg_count"],
            "msg_size": metrics["msg_size"],
            "time": time.perf_counter() - t0,
        }
        output_json(result, args.output)
    finally:
        orchestrator.stop()
    return 0

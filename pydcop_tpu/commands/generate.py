"""``pydcop generate``: benchmark problem generators.

reference parity: pydcop/commands/generate.py:879 + generators/
(graph_coloring, ising, meeting_scheduling, secp, iot, small_world,
agents, scenario).  Emits YAML on stdout or to ``--output``.
"""

import yaml


def _emit(args, text: str):
    try:
        print(text)
    except BrokenPipeError:
        pass
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "generate", help="generate benchmark problems")
    sub = parser.add_subparsers(dest="generator", required=True)

    gc = sub.add_parser("graph_coloring")
    gc.add_argument("-v", "--variables_count", type=int, required=True)
    gc.add_argument("-c", "--colors_count", type=int, default=3)
    gc.add_argument("-g", "--graph", default="random",
                    choices=["random", "scalefree", "grid"])
    gc.add_argument("--p_edge", type=float, default=None)
    gc.add_argument("--m_edge", type=int, default=None)
    gc.add_argument("--allow_subgraph", action="store_true")
    gc.add_argument("--soft", action="store_true",
                    help="soft coloring (cost-1 conflicts + noise)")
    gc.add_argument("--noise", type=float, default=0.02)
    gc.add_argument("--extensive", action="store_true",
                    help="extensional (matrix) constraints")
    gc.add_argument("--seed", type=int, default=None)
    gc.set_defaults(func=_gen_graph_coloring)

    ising = sub.add_parser("ising")
    ising.add_argument("--row_count", type=int, required=True)
    ising.add_argument("--col_count", type=int, default=None)
    ising.add_argument("--bin_range", type=float, default=1.6)
    ising.add_argument("--un_range", type=float, default=0.05)
    ising.add_argument("--seed", type=int, default=None)
    ising.set_defaults(func=_gen_ising)

    ms = sub.add_parser("meeting_scheduling")
    ms.add_argument("--slots_count", type=int, default=5)
    ms.add_argument("--events_count", type=int, default=4)
    ms.add_argument("--resources_count", type=int, default=3)
    ms.add_argument("--max_resources_event", type=int, default=2)
    ms.add_argument("--seed", type=int, default=None)
    ms.set_defaults(func=_gen_meetings)

    secp = sub.add_parser("secp")
    secp.add_argument("-l", "--lights", type=int, default=9)
    secp.add_argument("-m", "--models", type=int, default=3)
    secp.add_argument("-r", "--rules", type=int, default=2)
    secp.add_argument("--levels", type=int, default=5)
    secp.add_argument("--capacity", type=int, default=100)
    secp.add_argument("--seed", type=int, default=None)
    secp.set_defaults(func=_gen_secp)

    iot = sub.add_parser("iot")
    iot.add_argument("-n", "--num_device", type=int, default=30)
    iot.add_argument("--m_edge", type=int, default=2)
    iot.add_argument("--states", type=int, default=3)
    iot.add_argument("--seed", type=int, default=None)
    iot.set_defaults(func=_gen_iot)

    sw = sub.add_parser("small_world")
    sw.add_argument("-v", "--variables_count", type=int, default=20)
    sw.add_argument("-k", type=int, default=4)
    sw.add_argument("-p", type=float, default=0.1)
    sw.add_argument("-c", "--colors_count", type=int, default=3)
    sw.add_argument("--seed", type=int, default=None)
    sw.set_defaults(func=_gen_small_world)

    mixed = sub.add_parser(
        "mixed_problem",
        help="weighted-sum problem with a fraction of hard "
             "constraints (reference: generate.py:449)")
    mixed.add_argument("-v", "--variable_count", type=int,
                       required=True)
    mixed.add_argument("-c", "--constraint_count", type=int, default=0,
                       help="number of constraints (ignored for arity "
                            "2, where the graph's edges are the "
                            "constraints)")
    mixed.add_argument("-H", "--hard_constraint", type=float,
                       required=True,
                       help="proportion of hard constraints in [0, 1]")
    mixed.add_argument("-A", "--arity", type=int, default=2)
    mixed.add_argument("-r", "--range", type=int, default=10,
                       dest="domain_range",
                       help="variable domain: 0 .. r-1")
    mixed.add_argument("-d", "--density", type=float, default=0.3)
    mixed.add_argument("-a", "--agents", type=int, default=None)
    mixed.add_argument("--capacity", type=int, default=0)
    mixed.add_argument("--seed", type=int, default=None)
    mixed.set_defaults(func=_gen_mixed)

    agts = sub.add_parser("agents")
    agts.add_argument("--count", type=int, default=None)
    agts.add_argument("--dcop_files", nargs="*", default=None)
    agts.add_argument("--capacity", type=int, default=100)
    agts.add_argument("--hosting", default="none",
                      choices=["none", "name_mapping"])
    agts.add_argument("--hosting_default", type=float, default=100)
    agts.add_argument("--routes", default="none",
                      choices=["none", "uniform"])
    agts.add_argument("--routes_default", type=float, default=1)
    agts.add_argument("--agent_prefix", default="a")
    agts.add_argument("--seed", type=int, default=None)
    agts.set_defaults(func=_gen_agents)

    sc = sub.add_parser("scenario")
    sc.add_argument("--evts_count", type=int, default=3)
    sc.add_argument("--actions_count", type=int, default=1)
    sc.add_argument("--delay", type=float, default=10)
    sc.add_argument("--dcop_files", nargs="*", default=None)
    sc.add_argument("--agents", nargs="*", default=None)
    sc.add_argument("--keep", nargs="*", default=None)
    sc.add_argument("--seed", type=int, default=None)
    sc.set_defaults(func=_gen_scenario)
    return parser


def _gen_graph_coloring(args, timeout=None):
    from ..dcop.yamldcop import dcop_yaml
    from ..generators.graphcoloring import generate_graph_coloring

    dcop = generate_graph_coloring(
        args.variables_count, args.colors_count, graph_type=args.graph,
        p_edge=args.p_edge, m_edge=args.m_edge,
        allow_subgraph=args.allow_subgraph, soft=args.soft,
        noise_level=args.noise, extensive=args.extensive,
        seed=args.seed)
    _emit(args, dcop_yaml(dcop))
    return 0


def _gen_ising(args, timeout=None):
    from ..dcop.yamldcop import dcop_yaml
    from ..generators.ising import generate_ising

    dcop = generate_ising(
        args.row_count, args.col_count or args.row_count,
        bin_range=args.bin_range, un_range=args.un_range,
        seed=args.seed)
    _emit(args, dcop_yaml(dcop))
    return 0


def _gen_meetings(args, timeout=None):
    from ..dcop.yamldcop import dcop_yaml
    from ..generators.meetingscheduling import generate_meetings

    dcop = generate_meetings(
        slots_count=args.slots_count, events_count=args.events_count,
        resources_count=args.resources_count,
        max_resources_event=args.max_resources_event, seed=args.seed)
    _emit(args, dcop_yaml(dcop))
    return 0


def _gen_secp(args, timeout=None):
    from ..dcop.yamldcop import dcop_yaml
    from ..generators.secp import generate_secp

    dcop = generate_secp(
        lights_count=args.lights, models_count=args.models,
        rules_count=args.rules, levels=args.levels,
        capacity=args.capacity, seed=args.seed)
    _emit(args, dcop_yaml(dcop))
    return 0


def _gen_iot(args, timeout=None):
    from ..dcop.yamldcop import dcop_yaml
    from ..generators.iot import generate_iot

    dcop = generate_iot(num_device=args.num_device, m_edge=args.m_edge,
                        states_count=args.states, seed=args.seed)
    _emit(args, dcop_yaml(dcop))
    return 0


def _gen_small_world(args, timeout=None):
    from ..dcop.yamldcop import dcop_yaml
    from ..generators.smallworld import generate_small_world

    dcop = generate_small_world(
        args.variables_count, k=args.k, p=args.p,
        colors_count=args.colors_count, seed=args.seed)
    _emit(args, dcop_yaml(dcop))
    return 0


def _gen_mixed(args, timeout=None):
    from ..dcop.yamldcop import dcop_yaml
    from ..generators.mixed import generate_mixed_problem

    dcop = generate_mixed_problem(
        args.variable_count, args.constraint_count,
        hard_proportion=args.hard_constraint, arity=args.arity,
        domain_range=args.domain_range, density=args.density,
        agents=args.agents, capacity=args.capacity, seed=args.seed)
    _emit(args, dcop_yaml(dcop))
    return 0


def _gen_agents(args, timeout=None):
    from ..dcop.yamldcop import load_dcop_from_file
    from ..generators.agents import generate_agents

    dcop = (load_dcop_from_file(args.dcop_files)
            if args.dcop_files else None)
    agents = generate_agents(
        count=args.count, dcop=dcop, agent_prefix=args.agent_prefix,
        capacity=args.capacity, hosting=args.hosting,
        hosting_default=args.hosting_default, routes=args.routes,
        routes_default=args.routes_default, seed=args.seed)
    data = {"agents": {
        a.name: {
            "capacity": a.capacity,
            "hosting": {"default": a.default_hosting_cost,
                        **a.hosting_costs},
            "routes": {"default": a.default_route, **a.routes},
        } for a in agents}}
    _emit(args, yaml.safe_dump(data, default_flow_style=False))
    return 0


def _gen_scenario(args, timeout=None):
    from ..dcop.yamldcop import load_dcop_from_file
    from ..generators.scenario import generate_scenario

    if args.agents:
        agent_names = args.agents
    elif args.dcop_files:
        agent_names = sorted(
            load_dcop_from_file(args.dcop_files).agents)
    else:
        from . import CliError

        raise CliError("scenario generation needs --agents or "
                       "--dcop_files")
    scenario = generate_scenario(
        agent_names, evts_count=args.evts_count,
        actions_count=args.actions_count, delay=args.delay,
        keep=args.keep, seed=args.seed)
    events = []
    for e in scenario.events:
        if e.is_delay:
            events.append({"id": e.id, "delay": e.delay})
        else:
            events.append({"id": e.id, "actions": [
                {"type": a.type, **a.args} for a in e.actions]})
    _emit(args, yaml.safe_dump({"events": events},
                               default_flow_style=False))
    return 0

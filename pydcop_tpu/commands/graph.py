"""``pydcop graph``: computation-graph statistics for a DCOP.

reference parity: pydcop/commands/graph.py:144-198.
"""

from . import CliError, output_json
from ..dcop.yamldcop import load_dcop_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "graph", help="computation graph statistics")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument("-g", "--graph", required=True,
                        help="graph model: factor_graph | "
                             "constraints_hypergraph | pseudotree | "
                             "ordered_graph")
    parser.add_argument("--display", default=None, metavar="FILE",
                        help="render the constraint graph to an image "
                             "at FILE (reference's --display opens a "
                             "window — headless here)")
    parser.set_defaults(func=run_cmd)
    return parser


def _render(dcop, graph_type: str, path: str):
    """Draw the constraint graph with networkx + matplotlib (reference:
    graph.py:130-155 display_graph/display_bipartite_graph)."""
    import matplotlib

    matplotlib.use("Agg")  # headless: render to file, never open a UI
    import matplotlib.pyplot as plt
    import networkx as nx

    g = nx.Graph()
    if graph_type == "factor_graph":
        var_names = list(dcop.variables)
        g.add_nodes_from(var_names, bipartite=0)
        g.add_nodes_from(dcop.constraints, bipartite=1)
        for c_name, c in dcop.constraints.items():
            for v in c.scope_names:
                g.add_edge(c_name, v)
        colors = ["#7fb3d5" if n in dcop.variables else "#f5b041"
                  for n in g.nodes]
    else:
        g.add_nodes_from(dcop.variables)
        for c in dcop.constraints.values():
            scope = c.scope_names
            for i, a in enumerate(scope):
                for b in scope[i + 1:]:
                    g.add_edge(a, b)
        colors = "#7fb3d5"
    plt.figure(figsize=(8, 6))
    nx.draw_networkx(g, pos=nx.spring_layout(g, seed=1),
                     node_color=colors, font_size=8,
                     node_size=450, edge_color="#888888")
    plt.axis("off")
    plt.tight_layout()
    plt.savefig(path, dpi=120)
    plt.close()


def run_cmd(args, timeout=None):
    from ..graphs import load_graph_module

    dcop = load_dcop_from_file(args.dcop_files)
    cg = load_graph_module(args.graph).build_computation_graph(dcop)
    if args.display:
        if args.display.endswith((".yaml", ".yml")):
            # almost certainly a problem file swallowed by --display
            raise CliError(
                f"--display expects an image output path, got "
                f"{args.display!r} (a yaml file — did you mean "
                f"`--display out.png {args.display}`?)")
        _render(dcop, args.graph, args.display)
    edges_count = len(cg.links)
    nodes_count = len(cg.nodes)
    result = {
        "graph": {
            "nodes_count": nodes_count,
            "edges_count": edges_count,
            "density": cg.density(),
        },
        "inputs": {
            "dcop": [str(f) for f in args.dcop_files],
            "graph": args.graph,
            "variables_count": len(dcop.variables),
            "constraints_count": len(dcop.constraints),
            "agents_count": len(dcop.agents),
        },
    }
    output_json(result, args.output)
    return 0

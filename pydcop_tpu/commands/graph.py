"""``pydcop graph``: computation-graph statistics for a DCOP.

reference parity: pydcop/commands/graph.py:144-198.
"""

from . import output_json
from ..dcop.yamldcop import load_dcop_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "graph", help="computation graph statistics")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument("-g", "--graph", required=True,
                        help="graph model: factor_graph | "
                             "constraints_hypergraph | pseudotree | "
                             "ordered_graph")
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..graphs import load_graph_module

    dcop = load_dcop_from_file(args.dcop_files)
    cg = load_graph_module(args.graph).build_computation_graph(dcop)
    edges_count = len(cg.links)
    nodes_count = len(cg.nodes)
    result = {
        "graph": {
            "nodes_count": nodes_count,
            "edges_count": edges_count,
            "density": cg.density(),
        },
        "inputs": {
            "dcop": [str(f) for f in args.dcop_files],
            "graph": args.graph,
            "variables_count": len(dcop.variables),
            "constraints_count": len(dcop.constraints),
            "agents_count": len(dcop.agents),
        },
    }
    output_json(result, args.output)
    return 0

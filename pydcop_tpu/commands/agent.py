"""``pydcop agent``: standalone agent(s) joining a remote orchestrator.

reference parity: pydcop/commands/agent.py:33-350.  Starts N agents in
this process (one thread + one HTTP port each) pointed at the
orchestrator's address; they register through the directory protocol and
then follow orchestrator commands until stopped.
"""

import time

from . import CliError


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "agent", help="standalone agents joining an orchestrator")
    parser.add_argument("-n", "--names", nargs="+", required=True,
                        help="agent names (one per agent)")
    parser.add_argument("-p", "--port", type=int, default=9001,
                        help="base port; agent i listens on port+i")
    parser.add_argument("--address", default="127.0.0.1",
                        help="address advertised to peers (and bound, "
                             "unless --bind_address is given)")
    parser.add_argument("--bind_address", default=None,
                        help="address to bind the HTTP server to when it "
                             "differs from --address (NAT / container "
                             "port mapping, e.g. 0.0.0.0)")
    parser.add_argument("-o", "--orchestrator", required=True,
                        help="orchestrator ip:port")
    parser.add_argument("--uiport", type=int, default=None,
                        help="base websocket UI port (one per agent)")
    parser.add_argument("--replication",
                        default="dist_ucs_hostingcosts")
    parser.add_argument("--restart", action="store_true",
                        help="restart agents if they stop")
    parser.add_argument("--delay", type=float, default=0)
    parser.set_defaults(func=run_cmd)
    return parser


def _start_agents(args, orchestrator_address):
    from ..infrastructure.communication import HttpCommunicationLayer
    from ..infrastructure.orchestratedagents import OrchestratedAgent

    agents = []
    for i, name in enumerate(args.names):
        comm = HttpCommunicationLayer(
            (args.address, args.port + i),
            bind_host=getattr(args, "bind_address", None))
        ui_port = args.uiport + i if args.uiport else None
        agent = OrchestratedAgent(
            name, comm, orchestrator_address,
            replication=args.replication, ui_port=ui_port,
            delay=args.delay)
        agent.start()
        agents.append(agent)
    return agents


def run_cmd(args, timeout=None):
    from ..infrastructure.communication import Address

    try:
        host, _, port = args.orchestrator.partition(":")
        orchestrator_address = Address(host, int(port))
    except ValueError:
        raise CliError(
            f"Invalid orchestrator address {args.orchestrator!r}; "
            "use ip:port")
    agents = _start_agents(args, orchestrator_address)
    deadline = time.perf_counter() + timeout if timeout else None
    try:
        while True:
            time.sleep(0.2)
            alive = [a for a in agents if a.is_running]
            if not alive:
                if args.restart and (deadline is None
                                     or time.perf_counter() < deadline):
                    agents = _start_agents(args, orchestrator_address)
                    continue
                break
            if deadline and time.perf_counter() > deadline:
                break
    except KeyboardInterrupt:
        pass
    finally:
        for a in agents:
            a.clean_shutdown(1)
    return 0

"""``pydcop replica_dist``: compute a replica placement only.

reference parity: pydcop/commands/replica_dist.py:160-279.  Runs the
orchestrated runtime just long enough to deploy + replicate, then
prints the replica distribution YAML.
"""

from . import build_algo_def, output_json
from ..dcop.yamldcop import load_dcop_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "replica_dist", help="compute k-replica placement")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument("-k", "--ktarget", type=int, required=True)
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-p", "--algo_params", action="append",
                        default=None)
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..infrastructure.run import _prepare_run, \
        run_local_thread_dcop

    dcop = load_dcop_from_file(args.dcop_files)
    algo_def = build_algo_def(args.algo, args.algo_params,
                              mode=dcop.objective)
    algo_def, cg, dist = _prepare_run(dcop, algo_def,
                                      args.distribution)
    orchestrator = run_local_thread_dcop(
        algo_def, cg, dist, dcop,
        replication="dist_ucs_hostingcosts")
    try:
        orchestrator.deploy_computations(timeout=timeout or 30)
        merged = orchestrator.start_replication(
            args.ktarget, timeout=timeout or 30)
        output_json({"replica_dist": merged}, args.output)
    finally:
        orchestrator.stop_agents(2)
        orchestrator.stop()
        for a in getattr(orchestrator, "local_agents", []):
            a.clean_shutdown(1)
    return 0

"""``pydcop run``: dynamic DCOP run with scenario + replication.

reference parity: pydcop/commands/run.py:33-507.
"""

import time

from . import build_algo_def, output_json
from ..dcop.yamldcop import load_dcop_from_file, load_scenario_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "run", help="run a dynamic DCOP with scenario events")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-p", "--algo_params", action="append",
                        default=None)
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument("-m", "--mode", default="thread",
                        choices=["thread", "process"])
    parser.add_argument("-s", "--scenario", required=True,
                        help="scenario yaml file")
    parser.add_argument("-k", "--ktarget", type=int, default=3,
                        help="replication factor")
    parser.add_argument("--replication_method",
                        default="dist_ucs_hostingcosts")
    parser.add_argument("-c", "--collect_on", default="value_change",
                        choices=["value_change", "cycle_change",
                                 "period"])
    parser.add_argument("--period", type=float, default=None)
    parser.add_argument("--run_metrics", type=str, default=None,
                        help="CSV file streaming run metrics")
    parser.add_argument("--end_metrics", type=str, default=None,
                        help="CSV file to append one end-of-run "
                             "summary row to")
    parser.add_argument("-i", "--infinity", type=float,
                        default=float("inf"),
                        help="threshold AT OR ABOVE which a constraint "
                             "cost counts as a hard violation, either "
                             "sign (|cost| >= infinity; stricter than "
                             "the reference's ==infinity test — see "
                             "docs/analysing_results.md); violations "
                             "are counted separately and excluded from "
                             "the (always finite) reported cost "
                             "(reference: run.py:290-297)")
    parser.add_argument("--max_cycles", type=int, default=1_000_000)
    parser.add_argument("--seed", type=int, default=0)
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from .solve import _append_end_metrics

    t0 = time.perf_counter()
    dcop = load_dcop_from_file(args.dcop_files)
    scenario = load_scenario_from_file(args.scenario)
    algo_def = build_algo_def(args.algo, args.algo_params,
                              mode=dcop.objective)
    from ..infrastructure.run import run_dcop

    collector = None
    if args.run_metrics:
        # lossless stop contract: queue drained, file fsynced,
        # discarded rows counted and warned (observability/collector)
        from ..observability.collector import CsvCollector

        collector = CsvCollector(args.run_metrics)

    res = run_dcop(
        dcop, algo_def, distribution=args.distribution, mode=args.mode,
        scenario=scenario, timeout=timeout, ktarget=args.ktarget,
        replication=args.replication_method,
        collect_moment=args.collect_on, collect_period=args.period,
        seed=args.seed, max_cycles=args.max_cycles,
        collector=collector)
    if collector is not None:
        collector.stop()

    cost, violations = res.cost, res.violations
    if res.assignment and set(res.assignment) == set(dcop.variables):
        # cost and violation derive from the same solution_cost call so
        # the reported pair is always consistent
        cost, violations = dcop.solution_cost(res.assignment,
                                              infinity=args.infinity)
    result = {
        "status": res.status,
        "assignment": res.assignment,
        "cost": cost,
        "violation": violations,
        "cycle": res.cycles,
        "time": time.perf_counter() - t0,
        "msg_count": res.metrics.get("msg_count", 0),
        "msg_size": res.metrics.get("msg_size", 0),
    }
    if args.end_metrics:
        _append_end_metrics(args.end_metrics, result)
    output_json(result, args.output)
    return 0

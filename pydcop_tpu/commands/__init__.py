"""CLI subcommands.

reference parity: pydcop/commands/ — solve, run, orchestrator, agent,
distribute, graph, generate, replica_dist, batch, consolidate.

Shared helpers here mirror pydcop/commands/_utils.py: algorithm-parameter
parsing (`-p name:value`), numpy-aware JSON encoding, output-file
handling.
"""

import json
from typing import Any, Dict, List, Optional

import numpy as np


class CliError(Exception):
    pass


def parse_algo_params(param_strs: Optional[List[str]]) -> Dict[str, Any]:
    """Parse repeated ``-p name:value`` options
    (reference: commands/_utils.py)."""
    params: Dict[str, Any] = {}
    for p in param_strs or []:
        if ":" not in p:
            raise CliError(
                f"Invalid algorithm parameter {p!r}; use name:value")
        name, _, value = p.partition(":")
        params[name.strip()] = value.strip()
    return params


def build_algo_def(algo: str, param_strs: Optional[List[str]],
                   mode: str = "min"):
    """Build an AlgorithmDef from CLI args, validating parameters
    (reference: commands/_utils.py build_algo_def)."""
    from ..algorithms import (AlgoParameterException, AlgorithmDef,
                              list_available_algorithms)

    try:
        return AlgorithmDef.build_with_default_param(
            algo, params=parse_algo_params(param_strs), mode=mode)
    except ModuleNotFoundError:
        raise CliError(
            f"Unknown algorithm {algo!r}; available: "
            f"{', '.join(list_available_algorithms())}")
    except AlgoParameterException as e:
        raise CliError(str(e))


class NumpyEncoder(json.JSONEncoder):
    """JSON encoder accepting numpy scalars/arrays
    (reference: commands/solve.py:602)."""

    def default(self, o):
        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
        return super().default(o)


def output_json(data: Dict, output: Optional[str] = None):
    """Dump result JSON to stdout and optionally a file."""
    txt = json.dumps(data, sort_keys=True, indent=2, cls=NumpyEncoder)
    try:
        print(txt)
    except BrokenPipeError:  # e.g. piped into `head`
        pass
    if output:
        with open(output, "w") as f:
            f.write(txt)

"""CLI subcommands.

reference parity: pydcop/commands/ — solve, run, orchestrator, agent,
distribute, graph, generate, replica_dist, batch, consolidate.

Shared helpers here mirror pydcop/commands/_utils.py: algorithm-parameter
parsing (`-p name:value`), numpy-aware JSON encoding, output-file
handling.
"""

import json
import math
from typing import Any, Dict, List, Optional

import numpy as np


class CliError(Exception):
    pass


def parse_algo_params(param_strs: Optional[List[str]]) -> Dict[str, Any]:
    """Parse repeated ``-p name:value`` options
    (reference: commands/_utils.py)."""
    params: Dict[str, Any] = {}
    for p in param_strs or []:
        if ":" not in p:
            raise CliError(
                f"Invalid algorithm parameter {p!r}; use name:value")
        name, _, value = p.partition(":")
        params[name.strip()] = value.strip()
    return params


def build_algo_def(algo: str, param_strs: Optional[List[str]],
                   mode: str = "min"):
    """Build an AlgorithmDef from CLI args, validating parameters
    (reference: commands/_utils.py build_algo_def)."""
    from ..algorithms import (AlgoParameterException, AlgorithmDef,
                              list_available_algorithms)

    try:
        return AlgorithmDef.build_with_default_param(
            algo, params=parse_algo_params(param_strs), mode=mode)
    except ModuleNotFoundError:
        raise CliError(
            f"Unknown algorithm {algo!r}; available: "
            f"{', '.join(list_available_algorithms())}")
    except AlgoParameterException as e:
        raise CliError(str(e))


class NumpyEncoder(json.JSONEncoder):
    """JSON encoder accepting numpy scalars/arrays
    (reference: commands/solve.py:602)."""

    def default(self, o):
        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
        return super().default(o)


def _finitize(o):
    """Replace non-finite floats with their string form so the emitted
    JSON stays RFC 8259-valid (json.dumps would otherwise print the
    non-standard ``Infinity``/``NaN`` literals that strict parsers —
    jq, Go, Rust — reject).  Numpy scalars/arrays are normalized FIRST:
    NumpyEncoder only sees values after this pass, so a float32 inf or
    an ndarray cell would otherwise slip through the builtin-float
    check."""
    if isinstance(o, np.ndarray):
        return _finitize(o.tolist())
    if isinstance(o, np.floating):
        o = float(o)
    if isinstance(o, float) and not math.isfinite(o):
        return str(o)
    if isinstance(o, dict):
        return {k: _finitize(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_finitize(v) for v in o]
    return o


def output_json(data: Dict, output: Optional[str] = None,
                quiet: bool = False):
    """Dump result JSON to stdout (unless ``quiet``) and optionally a
    file (``quiet`` is for bulk writers like the fused batch runner —
    one campaign would otherwise print a thousand results)."""
    txt = json.dumps(_finitize(data), sort_keys=True, indent=2,
                     cls=NumpyEncoder)
    if not quiet:
        try:
            print(txt)
        except BrokenPipeError:  # e.g. piped into `head`
            pass
    if output:
        with open(output, "w") as f:
            f.write(txt)

"""``pydcop distribute``: compute/evaluate a distribution offline.

reference parity: pydcop/commands/distribute.py:226-407.
"""

from . import CliError, output_json
from ..dcop.yamldcop import load_dcop_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "distribute", help="distribute computations onto agents")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument("-d", "--distribution", required=True,
                        help="distribution method")
    parser.add_argument("-a", "--algo", default=None,
                        help="algorithm (for memory/load footprints)")
    parser.add_argument("-g", "--graph", default=None,
                        help="graph model, if no algo given")
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..algorithms import load_algorithm_module
    from ..distribution import load_distribution_module
    from ..distribution.objects import distribution_cost
    from ..graphs import load_graph_module

    dcop = load_dcop_from_file(args.dcop_files)
    if args.algo:
        algo_module = load_algorithm_module(args.algo)
        graph_name = args.graph or algo_module.GRAPH_TYPE
        footprint = algo_module.computation_memory
        load = algo_module.communication_load
    elif args.graph:
        graph_name, footprint, load = args.graph, None, None
        algo_module = None
    else:
        raise CliError("distribute needs --algo or --graph")
    cg = load_graph_module(graph_name).build_computation_graph(dcop)
    # some algorithms declare no footprint model (dpop raises, like the
    # reference's dpop.py:80-85): distribute without one instead of
    # failing — methods then treat computations as unit-sized
    if footprint is not None and cg.nodes:
        probe = cg.nodes[0]
        try:
            footprint(probe)
        except NotImplementedError:
            footprint = None
        except Exception:
            pass  # probe-node mismatch etc.: keep the callback
        try:
            load(probe, "")
        except NotImplementedError:
            load = None
        except Exception:
            pass  # a real target may be needed; keep the callback
    dist_module = load_distribution_module(args.distribution)
    dist = dist_module.distribute(
        cg, dcop.agents_def, dcop.dist_hints, footprint, load)
    result = {
        "distribution": dist.mapping(),
        "inputs": {
            "dcop": [str(f) for f in args.dcop_files],
            "dist_algo": args.distribution,
            "algo": args.algo,
            "graph": graph_name,
        },
    }
    try:
        cost, comm, hosting = distribution_cost(
            dist, cg, dcop.agents_def, computation_memory=footprint,
            communication_load=load)
        result["cost"] = cost
        result["communication_cost"] = comm
        result["hosting_cost"] = hosting
    except Exception:
        result["cost"] = None
    output_json(result, args.output)
    return 0

"""``pydcop telemetry-validate FILE``: schema-check a telemetry file.

Streams every line of a v1 JSONL telemetry file through
:func:`~pydcop_tpu.observability.report.validate_record` and exits
non-zero at the FIRST invalid record, naming the line and the
offending field.  This is the CI teeth of the schema contract: the
test tier runs it over the files the serving/dynamics suites already
produce, so an emitter that drifts from the documented schema fails
the build with a line number instead of surviving until some
downstream reader chokes.

Streaming, not slurping: a serve daemon's output file can be
gigabytes; memory use here is one line.
"""

import json
import sys

from . import CliError


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "telemetry-validate",
        help="validate a v1 JSONL telemetry file against the record "
             "schema; non-zero exit (with file:line) on the first "
             "invalid record")
    parser.add_argument("file", type=str, metavar="FILE.jsonl",
                        help="telemetry file to validate (solve/"
                             "batch --telemetry, serve --out)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress the per-kind summary on "
                             "success")
    parser.set_defaults(func=run_cmd)
    return parser


def validate_file(path: str):
    """(record-kind counts, schema minor ceiling) for a valid file;
    raises ``CliError`` carrying ``file:line: reason`` on the first
    invalid line."""
    from ..observability.report import validate_record

    counts = {}
    max_minor = 0
    try:
        f = open(path)
    except OSError as e:
        raise CliError(str(e))
    with f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise CliError(
                    f"{path}:{lineno}: not valid JSON: {e}")
            try:
                validate_record(rec)
            except ValueError as e:
                raise CliError(f"{path}:{lineno}: {e}")
            kind = rec["record"]
            counts[kind] = counts.get(kind, 0) + 1
            if kind == "header":
                max_minor = max(max_minor,
                                rec.get("schema_minor") or 0)
    return counts, max_minor


def run_cmd(args, timeout=None):
    counts, minor = validate_file(args.file)
    if not args.quiet:
        total = sum(counts.values())
        kinds = ", ".join(f"{k}={counts[k]}" for k in sorted(counts))
        print(f"{args.file}: {total} records valid "
              f"(schema 1.{minor}; {kinds or 'empty file'})",
              file=sys.stderr)
    return 0

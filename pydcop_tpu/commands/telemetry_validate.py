"""``pydcop telemetry-validate PATH``: schema-check telemetry.

Streams every line of a v1 JSONL telemetry file through
:func:`~pydcop_tpu.observability.report.validate_record` and exits
non-zero at the FIRST invalid record, naming the line and the
offending field.  This is the CI teeth of the schema contract: the
test tier runs it over the files the serving/dynamics suites already
produce, so an emitter that drifts from the documented schema fails
the build with a line number instead of surviving until some
downstream reader chokes.

PATH may also be a DIRECTORY (a fleet's ``--fleet-dir``, schema
minor 11): every ``*.jsonl`` inside is validated, plus two
cross-file fleet invariants no single-file pass can see —

* a file named after one emitter (``w0.jsonl``, ``router.jsonl``)
  must only contain that emitter's ``worker_id`` stamps (a worker
  writing into another's file is a mis-wired ``--out``);
* every ``parent_span_id`` and ``link.ref`` must resolve to a
  ``span_id`` defined SOMEWHERE in the directory — a dangling parent
  is exactly the broken-tree symptom ``pydcop trace`` would render
  as DISCONNECTED, caught here with a line number instead.

Streaming, not slurping: a serve daemon's output file can be
gigabytes; memory use here is one line (plus the directory mode's
span-id set).
"""

import json
import os
import re
import sys

from . import CliError

#: filenames that pin an emitter: w<K>.jsonl / router.jsonl (the
#: fleet's per-worker capture convention); shared out files
#: (fleet_out.jsonl, serve_out.jsonl) match nothing and may mix
_EMITTER_STEM = re.compile(r"^(w\d+|router)$")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "telemetry-validate",
        help="validate a v1 JSONL telemetry file — or a whole "
             "telemetry directory with cross-file trace checks — "
             "against the record schema; non-zero exit (with "
             "file:line) on the first invalid record")
    parser.add_argument("file", type=str, metavar="PATH",
                        help="telemetry file to validate (solve/"
                             "batch --telemetry, serve --out), or a "
                             "directory of them (fleet --fleet-dir)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress the per-kind summary on "
                             "success")
    parser.set_defaults(func=run_cmd)
    return parser


def _validate_lines(path: str, counts, on_record=None):
    """Stream-validate one file into ``counts``; returns its schema
    minor ceiling.  ``on_record(rec, lineno)`` feeds the directory
    mode's cross-file collectors."""
    from ..observability.report import validate_record

    max_minor = 0
    try:
        f = open(path)
    except OSError as e:
        raise CliError(str(e))
    with f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise CliError(
                    f"{path}:{lineno}: not valid JSON: {e}")
            try:
                validate_record(rec)
            except ValueError as e:
                raise CliError(f"{path}:{lineno}: {e}")
            kind = rec["record"]
            counts[kind] = counts.get(kind, 0) + 1
            if kind == "header":
                max_minor = max(max_minor,
                                rec.get("schema_minor") or 0)
            if on_record is not None:
                on_record(rec, lineno)
    return max_minor


def validate_file(path: str):
    """(record-kind counts, schema minor ceiling) for a valid file;
    raises ``CliError`` carrying ``file:line: reason`` on the first
    invalid line."""
    counts = {}
    max_minor = _validate_lines(path, counts)
    return counts, max_minor


def validate_dir(directory: str):
    """(record-kind counts, minor ceiling, file count) over every
    ``*.jsonl`` in ``directory``, plus the two cross-file
    invariants: emitter-named files carry only their own worker_id,
    and every trace parent reference resolves somewhere in the
    directory."""
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.endswith(".jsonl"))
    except OSError as e:
        raise CliError(str(e))
    if not names:
        raise CliError(f"{directory}: no *.jsonl telemetry files")
    counts = {}
    max_minor = 0
    defined = set()       # every span_id seen anywhere in the dir
    references = []       # (path, lineno, field, span_id)
    for name in names:
        path = os.path.join(directory, name)
        stem = name[:-len(".jsonl")]
        pinned = _EMITTER_STEM.match(stem)

        def on_record(rec, lineno, path=path, pinned=pinned,
                      stem=stem):
            wid = rec.get("worker_id")
            if pinned and wid and wid != stem:
                raise CliError(
                    f"{path}:{lineno}: worker_id {wid!r} in a file "
                    f"named for emitter {stem!r} — mis-wired --out?")
            sid = rec.get("span_id")
            if sid:
                defined.add(sid)
            parent = rec.get("parent_span_id")
            if parent:
                references.append((path, lineno,
                                   "parent_span_id", parent))
            link = rec.get("link")
            if isinstance(link, dict) and link.get("ref"):
                references.append((path, lineno,
                                   "link.ref", link["ref"]))
        max_minor = max(max_minor,
                        _validate_lines(path, counts, on_record))
    for path, lineno, field, sid in references:
        if sid not in defined:
            raise CliError(
                f"{path}:{lineno}: {field} {sid!r} does not resolve "
                f"to any span_id in {directory} — the trace tree is "
                f"broken (missing file, or an emitter dropped its "
                f"span record)")
    return counts, max_minor, len(names)


def run_cmd(args, timeout=None):
    if os.path.isdir(args.file):
        counts, minor, nfiles = validate_dir(args.file)
        if not args.quiet:
            total = sum(counts.values())
            kinds = ", ".join(
                f"{k}={counts[k]}" for k in sorted(counts))
            print(f"{args.file}: {total} records in {nfiles} "
                  f"file(s) valid, trace references resolve "
                  f"(schema 1.{minor}; {kinds or 'empty'})",
                  file=sys.stderr)
        return 0
    counts, minor = validate_file(args.file)
    if not args.quiet:
        total = sum(counts.values())
        kinds = ", ".join(f"{k}={counts[k]}" for k in sorted(counts))
        print(f"{args.file}: {total} records valid "
              f"(schema 1.{minor}; {kinds or 'empty file'})",
              file=sys.stderr)
    return 0

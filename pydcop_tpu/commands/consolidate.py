"""``pydcop consolidate``: aggregate batch results into one CSV.

reference parity: pydcop/commands/consolidate.py:129-235.
"""

import csv
import glob
import json
import os
import sys
from typing import List


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "consolidate", help="aggregate result JSON files into a CSV")
    parser.add_argument("result_files", nargs="+",
                        help="result json files (or globs)")
    parser.add_argument("-o", "--csv", dest="csv_out",
                        default=None, help="output CSV path")
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    files: List[str] = []
    for pattern in args.result_files:
        matched = sorted(glob.glob(pattern))
        files.extend(matched if matched else [pattern])
    rows = []
    for path in files:
        if not os.path.exists(path):
            print(f"warning: no such file {path}", file=sys.stderr)
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"warning: cannot read {path}: {e}", file=sys.stderr)
            continue
        rows.append({
            "file": os.path.basename(path),
            "status": data.get("status"),
            "cost": data.get("cost"),
            "violation": data.get("violation"),
            "cycle": data.get("cycle"),
            "time": data.get("time"),
            "msg_count": data.get("msg_count"),
            "msg_size": data.get("msg_size"),
        })
    fieldnames = ["file", "status", "cost", "violation", "cycle",
                  "time", "msg_count", "msg_size"]
    out = open(args.csv_out, "w", newline="") if args.csv_out \
        else sys.stdout
    try:
        writer = csv.DictWriter(out, fieldnames=fieldnames)
        writer.writeheader()
        writer.writerows(rows)
    finally:
        if args.csv_out:
            out.close()
    return 0

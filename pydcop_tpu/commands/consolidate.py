"""``pydcop consolidate``: aggregate batch results into one CSV.

reference parity: pydcop/commands/consolidate.py:129-235.
"""

import csv
import glob
import json
import os
import sys
from typing import List


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "consolidate", help="aggregate result JSON files into a CSV")
    parser.add_argument("result_files", nargs="+",
                        help="result json files (or globs)")
    parser.add_argument("-o", "--csv", dest="csv_out",
                        default=None, help="output CSV path")
    parser.set_defaults(func=run_cmd)
    return parser


BASE_COLUMNS = ("file", "status", "cost", "violation", "cycle",
                "time", "msg_count", "msg_size")


def _job_id_params(filename: str) -> dict:
    """Batch job ids encode the campaign coordinates
    (``set__batch__problem__k=v_k=v__iteration.json``, see
    batch._job_id); recover them as columns so campaign CSVs group by
    algorithm / parameters directly (the reference's consolidate
    extracts job metadata the same way, consolidate.py:129-235)."""
    stem = filename[:-5] if filename.endswith(".json") else filename
    parts = stem.split("__")
    if len(parts) != 5:
        return {}
    out = {"set": parts[0], "batch": parts[1], "problem": parts[2],
           "iteration": parts[4]}
    # batch._job_id joins k=v pairs with ',' (collision-free: keys and
    # values may both contain '_').  Legacy '_'-joined ids are detected
    # by multiple '=' without a ',': a single param (one '=') must NOT
    # be split on '_' — its key may contain one (damping_nodes=vars)
    seg = parts[3]
    sep = "," if "," in seg or seg.count("=") <= 1 else "_"
    for kv in seg.split(sep):
        if "=" in kv:
            k, v = kv.split("=", 1)
            if k not in BASE_COLUMNS:  # never clobber a measured value
                out[k] = v
    return out


def run_cmd(args, timeout=None):
    files: List[str] = []
    for pattern in args.result_files:
        matched = sorted(glob.glob(pattern))
        files.extend(matched if matched else [pattern])
    rows = []
    for path in files:
        if not os.path.exists(path):
            print(f"warning: no such file {path}", file=sys.stderr)
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"warning: cannot read {path}: {e}", file=sys.stderr)
            continue
        row = {
            "file": os.path.basename(path),
            "status": data.get("status"),
            "cost": data.get("cost"),
            "violation": data.get("violation"),
            "cycle": data.get("cycle"),
            "time": data.get("time"),
            "msg_count": data.get("msg_count"),
            "msg_size": data.get("msg_size"),
        }
        row.update(_job_id_params(os.path.basename(path)))
        rows.append(row)
    fieldnames = list(BASE_COLUMNS)
    extra = sorted({k for r in rows for k in r} - set(fieldnames))
    fieldnames += extra
    out = open(args.csv_out, "w", newline="") if args.csv_out \
        else sys.stdout
    try:
        writer = csv.DictWriter(out, fieldnames=fieldnames)
        writer.writeheader()
        writer.writerows(rows)
    finally:
        if args.csv_out:
            out.close()
    return 0

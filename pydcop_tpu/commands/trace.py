"""``pydcop trace``: assemble one job's fleet-wide causal story.

A job admitted through ``pydcop fleet`` leaves records in several
files — the router's routing audit, each worker's trace/summary
records (all sharing one ``trace_id``), and, after a crash, the
flight-recorder spills of processes that never wrote their JSONL
tail.  This command reads a telemetry DIRECTORY and stitches them
back into one indented span tree with timing attribution::

    pydcop trace ft00000001 --dir fleet_dir
    pydcop trace j42 --dir fleet_dir          # by job id
    pydcop trace sess-a --dir fleet_dir       # by delta target

A query naming a session (delta target) may resolve to several
traces — one per delta — and every matching tree is rendered.
``--json`` emits the machine view: one object per trace with the
span tree, connectivity verdict and attribution table.
"""

import json
import sys

from . import CliError


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "trace",
        help="assemble and render one trace's span tree from a "
             "telemetry directory (router + worker JSONL + "
             "flight-recorder spills)")
    parser.add_argument("query",
                        help="a trace id (t.../ft...), a job id, or "
                             "a session (delta target) id")
    parser.add_argument("--dir", dest="directory", required=True,
                        metavar="DIR",
                        help="telemetry directory to read: every "
                             "*.jsonl plus every flightrec-*.bin "
                             "spill (a fleet's --fleet-dir, or any "
                             "directory of --out files)")
    parser.add_argument("--json", dest="as_json",
                        action="store_true",
                        help="emit the assembled tree(s) as JSON "
                             "instead of the indented human view")
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..observability.tracing import (assemble, attribution,
                                         find_trace_ids,
                                         is_connected,
                                         load_telemetry_dir,
                                         render_tree, span_to_dict)

    try:
        records, spills = load_telemetry_dir(args.directory)
    except ValueError as e:
        raise CliError(str(e))
    if not records:
        raise CliError(f"no telemetry records under "
                       f"{args.directory!r}")
    trace_ids = find_trace_ids(records, args.query)
    if not trace_ids:
        raise CliError(
            f"no trace matches {args.query!r} in {args.directory!r} "
            f"(tried trace_id, job_id and session target)")
    out = []
    for tid in trace_ids:
        roots = assemble(records, spills, tid)
        if not roots:
            continue
        if args.as_json:
            out.append({
                "trace_id": tid,
                "connected": is_connected(roots),
                "roots": [span_to_dict(r) for r in roots],
                "attribution": attribution(roots),
            })
        else:
            out.append(render_tree(roots, trace_id=tid))
    if not out:
        raise CliError(f"trace {args.query!r} resolved but has no "
                       f"spans (records predate schema 1.11?)")
    if args.as_json:
        print(json.dumps(out if len(out) > 1 else out[0], indent=2))
    else:
        print("\n\n".join(out))
    disconnected = sum(
        1 for o in out
        if (isinstance(o, dict) and not o["connected"])
        or (isinstance(o, str) and "[DISCONNECTED" in o))
    if disconnected:
        print(f"[trace] {disconnected} trace(s) DISCONNECTED — "
              f"records are missing or predate the failover links",
              file=sys.stderr)
    return 0

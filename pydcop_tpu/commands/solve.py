"""``pydcop solve``: single-machine end-to-end solve.

reference parity: pydcop/commands/solve.py:444-632.  Loads YAML dcop
file(s), builds the algorithm's graph, distributes, solves — by default
on the compiled engine (the fast path), or through the orchestrated
thread/process runtime with ``--mode thread|process`` when the
distributed fabric (metrics reporting, HTTP messaging) should be
exercised.  Prints a JSON result.
"""

import csv
import os
import time
from typing import Optional

from . import CliError, build_algo_def, output_json
from ..dcop.yamldcop import load_dcop_from_file


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "solve", help="solve a static DCOP on this machine")
    parser.add_argument("dcop_files", type=str, nargs="+",
                        help="dcop yaml file(s), concatenated")
    parser.add_argument("-a", "--algo", required=True,
                        help="algorithm name")
    parser.add_argument("-p", "--algo_params", action="append",
                        default=None, help="algorithm param name:value")
    parser.add_argument("-d", "--distribution", default="oneagent",
                        help="distribution method or yaml file")
    parser.add_argument("-m", "--mode", default="engine",
                        choices=["engine", "thread", "process",
                                 "sharded"],
                        help="engine = compiled fast path (default); "
                             "thread/process = orchestrated runtime; "
                             "sharded = dp x tp device-mesh data "
                             "plane (multi-chip)")
    parser.add_argument("--batch", type=int, default=None,
                        help="sharded mode: independent restarts on "
                             "the dp axis (default: one per dp row)")
    parser.add_argument("-c", "--collect_on", default="value_change",
                        choices=["value_change", "cycle_change",
                                 "period"])
    parser.add_argument("--period", type=float, default=None,
                        help="metrics collection period: seconds in "
                             "thread/process mode, cycles in engine "
                             "mode")
    parser.add_argument("--run_metrics", type=str, default=None,
                        help="CSV file for run metrics")
    parser.add_argument("--telemetry", type=str, default=None,
                        metavar="out.jsonl",
                        help="structured JSONL run telemetry: one "
                             "header record (solver/layout/precision/"
                             "mesh/compile_stats), one record per "
                             "executed cycle (message residual, "
                             "selection flips, conflicted-constraint "
                             "count — recorded ON DEVICE, drained at "
                             "chunk boundaries, zero extra host "
                             "syncs) and one summary record; same "
                             "schema as batch --telemetry "
                             "(docs/analysing_results.md).  Engine "
                             "and sharded modes record cycle metrics; "
                             "thread/process modes emit header + "
                             "summary only")
    parser.add_argument("--profile", type=str, default=None,
                        metavar="DIR",
                        help="write a jax.profiler trace (Perfetto-"
                             "readable, kernel families named via "
                             "jax.named_scope) for the solve into "
                             "DIR")
    parser.add_argument("--end_metrics", type=str, default=None,
                        help="CSV file to append one end-of-run summary "
                             "row to (reference: solve.py:162)")
    parser.add_argument("-i", "--infinity", type=float,
                        default=float("inf"),
                        help="threshold AT OR ABOVE which a constraint "
                             "cost counts as a hard violation, either "
                             "sign (|cost| >= infinity; stricter than "
                             "the reference's ==infinity test — see "
                             "docs/analysing_results.md); violations "
                             "are counted separately and excluded from "
                             "the (always finite) reported cost "
                             "(reference: solve.py:316-323 + "
                             "dcop.py:319-369)")
    parser.add_argument("--delay", type=float, default=None,
                        help="inter-message delay (thread/process mode)")
    parser.add_argument("--uiport", type=int, default=None,
                        help="websocket UI port base (thread mode)")
    parser.add_argument("--max_cycles", type=int, default=2000)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--checkpoint", type=str, default=None,
                        metavar="DIR",
                        help="preemption-safe solving "
                             "(engine/sharded modes): snapshot the "
                             "solver carry (q/r message planes, "
                             "selections, cycle, RNG key, telemetry "
                             "planes) into DIR at the engine's "
                             "existing chunk sync boundaries — "
                             "atomic write-temp+fsync+rename, "
                             "manifest keyed to the "
                             "jax/backend/arch/precision/layout "
                             "fingerprint.  A killed run re-launched "
                             "with --resume continues from the last "
                             "snapshot and reproduces the "
                             "uninterrupted run's selections AND "
                             "convergence cycles bit-exactly "
                             "(docs/architecture.md).  Off (the "
                             "default): byte-identical programs, "
                             "zero overhead")
    parser.add_argument("--checkpoint-every", dest="checkpoint_every",
                        type=int, default=256, metavar="N",
                        help="cycles between snapshots (landing on "
                             "the first chunk boundary at or past "
                             "each multiple; the final boundary "
                             "always snapshots).  Default 256")
    parser.add_argument("--resume", action="store_true",
                        help="restore the --checkpoint snapshot for "
                             "this exact job identity (files + algo "
                             "+ params + seed + budget) and continue "
                             "from its cycle; a snapshot from a "
                             "different precision/layout/backend/"
                             "mesh refuses with a structured "
                             "mismatch error, a missing or "
                             "quarantined-corrupt snapshot starts "
                             "fresh")
    parser.add_argument("--scenario", type=str, default=None,
                        metavar="FILE",
                        help="dynamic-DCOP replay (maxsum, "
                             "engine/sharded modes): after the "
                             "initial solve, apply the scenario "
                             "yaml's events (add_variable / "
                             "remove_variable / add_constraint / "
                             "remove_constraint / change_costs) as "
                             "in-place edits of the phantom-padded "
                             "instance and re-solve WARM — no "
                             "retrace, no recompile, message state "
                             "carried over for untouched regions "
                             "(docs/architecture.md dynamics "
                             "section).  Per-event results land in "
                             "the 'scenario' result field and, with "
                             "--telemetry, as summary records "
                             "carrying edit/warm_start")
    parser.add_argument("--reserve-slots", dest="reserve_slots",
                        type=str, default=None, metavar="SPEC",
                        help="explicit phantom headroom for "
                             "--scenario: 'vars:N,ARITY:N' extra "
                             "variable rows / per-arity factor slots "
                             "beyond the power-of-two padding, the "
                             "capacity add events activate (an event "
                             "exceeding it is rejected loudly); the "
                             "remaining budget is echoed in the "
                             "result")
    parser.add_argument("--warm-budget", dest="warm_budget",
                        default="adaptive",
                        choices=["adaptive", "fixed"],
                        help="--scenario warm re-solve budget "
                             "schedule: 'adaptive' (default) "
                             "dispatches a geometric chunk schedule "
                             "— small first chunk growing toward the "
                             "engine chunk size — and stops at the "
                             "first chunk boundary where the "
                             "on-device stability rule fired "
                             "(settle_chunk in the telemetry); "
                             "'fixed' keeps constant chunks.  Both "
                             "return identical selections and "
                             "cycles.  The warm LAYOUT is the maxsum "
                             "'layout' algo param (-p "
                             "layout:fused): fused re-solves the "
                             "same edits ~2x faster per cycle on "
                             "host CPU, bit-exactly (but rejects "
                             "constraint add/remove); lane_major is "
                             "the TPU-tile layout and speaks every "
                             "event type")
    parser.add_argument("--roi", nargs="?", const=True,
                        default=False, metavar="auto",
                        help="--scenario region-of-interest warm "
                             "re-solves: each event's solve sweeps "
                             "only an activity window seeded from "
                             "the delta's touched rows and grown "
                             "one neighborhood hop at chunk "
                             "boundaries while boundary residuals "
                             "stay hot — event cost scales with the "
                             "perturbation, not |V|.  Rows outside "
                             "the region keep the carried fixed "
                             "point bit-exactly.  Needs mode "
                             "engine, carry messages; telemetry "
                             "records carry active_fraction / "
                             "frontier_expansions.  '--roi auto' "
                             "adds the escape hatch: when the "
                             "active fraction trends toward 1 over "
                             "a sliding window of events (edits "
                             "touching the whole graph), the "
                             "session permanently flips to full "
                             "sweeps and stops paying window "
                             "overhead; the flip lands in telemetry "
                             "as roi_flipped")
    parser.add_argument("--roi-residual-threshold",
                        dest="roi_residual_threshold", type=float,
                        default=None, metavar="EPS",
                        help="--roi frontier gate: expand the "
                             "active region while chunk-boundary "
                             "residuals are >= EPS (default: the "
                             "solver's own damping-scaled stability "
                             "threshold).  Lower = chase smaller "
                             "ripples further (closer to the full "
                             "sweep); higher = tighter regions, "
                             "faster events")
    parser.add_argument("--carry", default="messages",
                        choices=["messages", "reset"],
                        help="--scenario warm-state policy: "
                             "'messages' (default) carries the "
                             "previous fixed point's q/r planes for "
                             "untouched regions (conditional-Max-Sum "
                             "partial update); 'reset' starts each "
                             "re-solve from neutral messages — still "
                             "retrace-free, and structurally "
                             "bit-exact with a cold solve of the "
                             "edited instance")
    parser.add_argument("--no-tuned", dest="no_tuned",
                        action="store_true",
                        help="ignore the per-rung tuned-config store "
                             "(pydcop autotune): run pure defaults "
                             "for every knob not given explicitly.  "
                             "By default, knobs you did not pin are "
                             "resolved from the instance's home-rung "
                             "sidecar when one exists; the per-knob "
                             "resolution (explicit/tuned/default) is "
                             "echoed in the result's 'tuning' field")
    parser.add_argument("--precision", default=None,
                        choices=["f32", "bf16", "auto"],
                        help="mixed-precision policy for the compiled "
                             "data plane (engine/sharded modes): bf16 "
                             "stores cost cubes + unary planes at half "
                             "the bytes while sums and messages "
                             "accumulate in f32 — integer-cost "
                             "instances reproduce f32 selections and "
                             "convergence cycles bit-exactly "
                             "(docs/architecture.md).  auto = bf16 on "
                             "TPU only.  Default: the "
                             "PYDCOP_TPU_PRECISION env var, then f32. "
                             "Equivalent to -p precision:<value> (the "
                             "flag wins when both are given)")
    parser.add_argument("--decimation", default=None,
                        metavar="P[:EVERY]",
                        help="decimated Max-Sum (maxsum only, "
                             "engine/sharded modes): every EVERY "
                             "cycles pin the top-P fraction of the "
                             "most-confident (largest belief-margin) "
                             "unfrozen variables and clamp their "
                             "outgoing messages, so loopy instances "
                             "settle instead of oscillating "
                             "(docs/architecture.md).  EVERY defaults "
                             "to the engines' chunk size (32), so "
                             "freeze events land on existing sync "
                             "boundaries.  Equivalent to "
                             "-p decimation_p:P -p "
                             "decimation_every:EVERY")
    parser.add_argument("--bnb", action="store_true",
                        help="branch-and-bound pruned factor "
                             "reductions (maxsum family): arity >= 3 "
                             "cost hypercubes big enough to pay for "
                             "bound checks sweep their cells in "
                             "build-time bound-sorted order and "
                             "early-out cells a per-factor suffix "
                             "bound excludes — messages (and thus "
                             "selections AND convergence cycles) stay "
                             "bit-exact with the full scan.  "
                             "Equivalent to -p bnb:1")
    parser.add_argument("--portfolio", type=str, default=None,
                        metavar="SPEC",
                        help="race N solver arms over this instance "
                             "as vmapped lanes and keep the winner "
                             "(parallel/portfolio.py).  SPEC is "
                             "'auto' (the built-in 8-arm preset) or "
                             "a ';'-separated arm grid — each arm "
                             "'family[,name:value...]' with seed:N / "
                             "seeds:N specials; arms of the -a "
                             "family inherit the -p params as their "
                             "baseline.  Losing arms are killed "
                             "early at chunk boundaries (see the "
                             "--portfolio-* knobs) and their lanes "
                             "become no-ops; survivors rebatch down "
                             "the pow2 ladder.  The result reports "
                             "the winning arm, per-arm best costs "
                             "and cycles survived; --checkpoint/"
                             "--resume make long races "
                             "preemption-safe (the survivor set "
                             "snapshots at boundaries and a resumed "
                             "race reproduces the uninterrupted "
                             "winner bit-exactly)")
    parser.add_argument("--portfolio-every", dest="portfolio_every",
                        type=int, default=32, metavar="N",
                        help="--portfolio scoring cadence in cycles: "
                             "every arm is scored (and the kill rule "
                             "applied) each N cycles, at the chunked "
                             "drive's existing host sync.  Default "
                             "32")
    parser.add_argument("--portfolio-margin",
                        dest="portfolio_margin", type=float,
                        default=0.05, metavar="F",
                        help="--portfolio kill rule: an arm is "
                             "'trailing' when its best cost sits "
                             "more than this relative fraction "
                             "behind the leader's (violations "
                             "compare first).  Default 0.05")
    parser.add_argument("--portfolio-patience",
                        dest="portfolio_patience", type=int,
                        default=3, metavar="K",
                        help="--portfolio kill rule: kill an arm "
                             "after K consecutive trailing "
                             "boundaries.  Default 3")
    parser.add_argument("--portfolio-plateau",
                        dest="portfolio_plateau", type=int,
                        default=6, metavar="K",
                        help="--portfolio kill rule: kill an arm "
                             "whose own best has not improved for K "
                             "consecutive boundaries.  Default 6")
    parser.set_defaults(func=run_cmd)
    return parser


def parse_decimation_flag(value) -> Optional[tuple]:
    """``--decimation P[:EVERY]`` -> ``(p, every)`` (``every`` 0 =
    the solver's chunk-aligned default), or None when the flag is
    absent.  Shared with ``batch`` so the two CLIs can never drift on
    the flag grammar; malformed values die as clean CLI errors."""
    if value is None:
        return None
    parts = str(value).split(":")
    try:
        if len(parts) == 1:
            p, every = float(parts[0]), 0
        elif len(parts) == 2:
            p, every = float(parts[0]), int(parts[1])
        else:
            raise ValueError(value)
        from ..algorithms.maxsum import normalize_decimation

        p, _enabled, every = normalize_decimation(p, every)
    except ValueError as e:
        raise CliError(
            f"--decimation wants P[:EVERY] with P a fraction in "
            f"(0, 1] and EVERY a positive cycle count: {e}")
    if p <= 0:
        raise CliError(
            "--decimation P must be > 0 (omit the flag to disable)")
    return p, every


def _feature_result_fields(args, decim, bnb_flag) -> dict:
    """The ``decimation``/``bnb`` result fields, from the flags or
    their ``-p`` spellings — absent entirely (historical schema) when
    neither feature was requested."""
    from . import parse_algo_params

    given = parse_algo_params(args.algo_params)
    out = {}
    try:
        p = decim[0] if decim else \
            float(given.get("decimation_p", 0) or 0)
    except ValueError:
        p = 0.0  # malformed -p values die later in algo validation
    if p > 0:
        from ..algorithms.maxsum import normalize_decimation

        every = decim[1] if decim else \
            int(given.get("decimation_every", 0) or 0)
        p, _enabled, every = normalize_decimation(p, every)
        out["decimation"] = {"p": p, "every": every}
    from ..algorithms import param_bool

    if bnb_flag or param_bool(str(given.get("bnb", "")).strip()):
        out["bnb"] = True
    return out


#: algo family -> instance-array kind for the tuned-config lookup;
#: algorithms outside the tuned families skip the store entirely
_TUNABLE_FAMILY = {"maxsum": "factor", "amaxsum": "factor",
                   "dsa": "hyper", "mgm": "hyper"}


def _tuned_resolution(args, dcop, explicit_params: dict,
                      context: str, adoptable):
    """Resolve un-pinned knobs from the instance's home-rung sidecar
    (``pydcop autotune``), returning ``(adopted knob values, per-knob
    sources, rung label)``.  Explicit params always win
    (``tuning/store.resolve_knobs``); knobs outside ``adoptable`` —
    the set this dispatch surface can actually apply — are reported
    as ``default`` even if a sidecar carries them.  The store is only
    consulted when it exists AND holds sidecars, so solves on
    untuned machines never pay the extra array build the rung
    identity needs."""
    if getattr(args, "no_tuned", False):
        return {}, {}, None
    kind = _TUNABLE_FAMILY.get(args.algo)
    if kind is None:
        return {}, {}, None
    from ..tuning.store import SIDECAR_SUFFIX, default_store, \
        resolve_knobs

    store = default_store()
    try:
        empty = store.enabled and not any(
            n.endswith(SIDECAR_SUFFIX) for n in os.listdir(store.path))
    except OSError:
        empty = True
    if not store.enabled or empty:
        return {}, {}, None
    from ..dcop.dcop import filter_dcop
    from ..graphs.arrays import FactorGraphArrays, HypergraphArrays
    from ..parallel.bucketing import ShapeProfile, home_rung, \
        rung_label

    if kind == "factor":
        arrays = FactorGraphArrays.build(dcop, arity_sorted=True)
    else:
        arrays = HypergraphArrays.build(filter_dcop(dcop))
    sig = home_rung(ShapeProfile.of(arrays)).signature
    resolved, sources = resolve_knobs(
        args.algo, explicit_params, sig, store, context=context)
    adopted = {}
    for knob, src in list(sources.items()):
        if src != "tuned":
            continue
        if knob in adoptable:
            adopted[knob] = resolved[knob]
        else:
            sources[knob] = "default"
    return adopted, sources, rung_label(sig)


def _knob_param_str(knob: str, value) -> str:
    """One adopted knob as the ``-p name:value`` string the algo-param
    validator consumes (bools in the flag spelling the CLI already
    uses, e.g. ``bnb:1``)."""
    if isinstance(value, bool):
        value = int(value)
    return f"{knob}:{value}"


def _build_checkpointer(args, precision_name: Optional[str]):
    """The run's :class:`~pydcop_tpu.robustness.checkpoint.
    SolveCheckpointer` from ``--checkpoint DIR``, or None.  The
    snapshot name is the job identity (files × algo × params × seed ×
    budget), the fingerprint the program identity (precision, layout,
    backend, ...), so ``--resume`` can only ever restore THIS job's
    state into THIS program — anything else misses or refuses with a
    structured mismatch."""
    directory = getattr(args, "checkpoint", None)
    if not directory:
        if getattr(args, "resume", False):
            raise CliError(
                "--resume restores a --checkpoint snapshot: give "
                "the checkpoint directory too")
        return None
    if args.mode not in ("engine", "sharded"):
        raise CliError(
            "--checkpoint snapshots the compiled solver carry at "
            "chunk boundaries: mode engine or sharded, not "
            f"{args.mode!r}")
    if getattr(args, "scenario", None):
        raise CliError(
            "--checkpoint covers ONE long solve; a --scenario warm "
            "replay is protected by the session journal instead "
            "(checkpoint = base snapshot, journal = replayable "
            "delta tail — docs/dynamic_dcops.md)")
    every = getattr(args, "checkpoint_every", 256)
    if every < 1:
        raise CliError("--checkpoint-every must be >= 1 cycles")
    from . import parse_algo_params
    from ..robustness.checkpoint import (CheckpointStore,
                                         SolveCheckpointer,
                                         checkpoint_fingerprint,
                                         env_preempt_hook,
                                         solve_checkpoint_name)

    try:
        preempt_after, on_preempt = env_preempt_hook()
        store = CheckpointStore(directory)
    except (OSError, ValueError) as e:
        raise CliError(str(e))
    layout = parse_algo_params(args.algo_params).get("layout")
    return SolveCheckpointer(
        store,
        solve_checkpoint_name(args.dcop_files, args.algo, args.mode,
                              args.algo_params, args.seed,
                              precision_name),
        every=every,
        fingerprint=checkpoint_fingerprint(
            precision=precision_name or "f32", layout=layout,
            algo=args.algo),
        preempt_after=preempt_after, on_preempt=on_preempt)


def _resolved_precision_name(args) -> Optional[str]:
    """The precision to report in the result — only when one was
    actually requested (flag, -p param, or environment); a plain f32
    run keeps its historical result schema.  A malformed environment
    value dies as a clean CLI error, like every other misconfiguration
    (the argparse flag is already choice-validated)."""
    from . import parse_algo_params
    from ..ops.precision import ENV_VAR, resolve

    requested = (getattr(args, "precision", None)
                 or parse_algo_params(args.algo_params).get("precision")
                 or os.environ.get(ENV_VAR))
    if not requested:
        return None
    try:
        return resolve(requested).name
    except ValueError as e:
        raise CliError(str(e))


def run_cmd(args, timeout: Optional[float] = None):
    t0 = time.perf_counter()
    if getattr(args, "precision", None) and args.mode != "sharded":
        # the flag is sugar for the algorithm parameter; appending it
        # last makes the flag win over an explicit -p precision:.
        # Sharded mode skips the append: every sharded family takes
        # the policy as a constructor kwarg (injected below) even when
        # the algorithm's own engine params predate it (mgm2, dba, ...)
        # — validating it as an algo-param would reject those.
        args.algo_params = (args.algo_params or []) + [
            f"precision:{args.precision}"]
    decim = parse_decimation_flag(getattr(args, "decimation", None))
    bnb_flag = bool(getattr(args, "bnb", False))
    roi = getattr(args, "roi", False)
    if isinstance(roi, str) and roi != "auto":
        raise CliError(
            f"--roi takes no value (window every event) or 'auto' "
            f"(flip to full sweeps when the active fraction trends "
            f"toward 1), got {roi!r}")
    if roi and args.mode == "sharded":
        # ROADMAP: the activity-gated windowed sweep lives in the
        # compiled warm engine only; the sharded (reference-parity)
        # runtime has no window machinery.  Silently ignoring the
        # flag would report full-sweep costs as if they were
        # windowed, so the conflict is a loud startup rejection —
        # same rc-2 contract as every other CLI conflict
        raise CliError(
            "--roi needs the compiled warm engine (-m engine); "
            "sharded mode has no region-of-interest sweep — drop "
            "--roi or drop -m sharded")
    if getattr(args, "portfolio", None):
        return _run_portfolio(args, t0, timeout, decim, bnb_flag)
    if args.mode != "sharded":
        # same sugar rule as --precision: the flags become the
        # algorithm parameters, so algorithms without them (dsa, dpop,
        # ...) reject the request loudly through algo-param validation
        if decim:
            args.algo_params = (args.algo_params or []) + [
                f"decimation_p:{decim[0]}",
                f"decimation_every:{decim[1]}"]
        if bnb_flag:
            args.algo_params = (args.algo_params or []) + ["bnb:1"]
    elif (decim or bnb_flag) and args.algo not in ("maxsum", "amaxsum"):
        # the sharded decimation/bnb kwargs exist on the maxsum mesh
        # family only — fail fast instead of a constructor TypeError
        raise CliError(
            "--decimation/--bnb are maxsum-family options; "
            f"sharded {args.algo!r} supports neither")
    elif decim and args.algo == "amaxsum":
        # per-feature gate: ShardedAMaxSum takes bnb but rejects
        # decimation (stochastic activation re-admits pre-freeze
        # messages) — surface that as a clean CLI error, not a
        # constructor traceback
        raise CliError(
            "--decimation is not supported with amaxsum (stochastic "
            "edge activation undoes the freeze clamp); use maxsum "
            "for decimated runs")
    if getattr(args, "reserve_slots", None) \
            and not getattr(args, "scenario", None):
        # same die-at-startup rule as batch/serve: a typoed or
        # misplaced reservation must never be silently ignored
        raise CliError(
            "--reserve-slots provisions edit headroom for a dynamic "
            "replay: it requires --scenario on solve")
    precision_name = _resolved_precision_name(args)
    checkpointer = _build_checkpointer(args, precision_name)
    dcop = load_dcop_from_file(args.dcop_files)
    if getattr(args, "scenario", None):
        return _run_scenario(args, dcop, t0, timeout,
                             precision_name)
    algo_def = build_algo_def(args.algo, args.algo_params,
                              mode=dcop.objective)
    tuning_sources, tuned_rung = {}, None
    if args.mode == "engine":
        from . import parse_algo_params

        # consult the per-rung tuned-config store for every knob the
        # caller didn't pin; adopted knobs travel as ordinary -p
        # params, so algo-param validation covers them like any
        # explicit spelling and the rebuilt algo_def is identical to
        # the same config passed by hand (bit-exactness by
        # construction)
        adopted, tuning_sources, tuned_rung = _tuned_resolution(
            args, dcop, parse_algo_params(args.algo_params),
            "engine", adoptable=set(algo_def.params))
        if adopted:
            args.algo_params = (args.algo_params or []) + [
                _knob_param_str(k, v) for k, v in adopted.items()]
            algo_def = build_algo_def(args.algo, args.algo_params,
                                      mode=dcop.objective)
            if "precision" in adopted:
                precision_name = _resolved_precision_name(args)
                if checkpointer is not None:
                    # the snapshot fingerprint carries the precision
                    # the run really uses, tuned or not
                    checkpointer = _build_checkpointer(
                        args, precision_name)
    if precision_name and args.mode != "sharded" \
            and "precision" not in algo_def.params:
        # the algorithm never consults the policy (e.g. dpop): an
        # env-var default must not mislabel an f32 computation as
        # bf16 in the result.  Sharded mode is exempt — every sharded
        # family consumes the policy even when the algorithm's own
        # engine params predate it
        precision_name = None
    collector = None
    if args.run_metrics:
        # lossless stop contract: queue drained, file fsynced, any
        # discarded rows counted and warned (observability/collector)
        from ..observability.collector import CsvCollector

        collector = CsvCollector(args.run_metrics)
    telemetry_path = getattr(args, "telemetry", None)
    profile_dir = getattr(args, "profile", None)

    from ..observability.spans import profile_trace

    if args.mode == "sharded":
        from . import parse_algo_params
        from ..parallel import solve_sharded_result

        # only user-given params travel (validated/cast by algo_def);
        # defaults come from the sharded solvers themselves, and
        # engine-level knobs are not sharded-solver constructor args
        given = parse_algo_params(args.algo_params)
        params = {k: algo_def.params[k] for k in given}
        for engine_only in ("stop_cycle", "seed"):
            params.pop(engine_only, None)
        if getattr(args, "precision", None):
            # the flag wins over -p precision: (where declared); for
            # families whose engine params predate the policy this is
            # the only flag path — the kwarg exists on all of them
            params["precision"] = args.precision
        if decim:
            params["decimation_p"] = decim[0]
            params["decimation_every"] = decim[1]
        if bnb_flag:
            params["bnb"] = True
        # single-chip-only engine knob: reject loudly rather than let
        # the sharded solver constructor TypeError on it
        if params.pop("delta_on", "messages") != "messages":
            raise CliError(
                "delta_on:beliefs is a single-chip engine knob; "
                "sharded convergence keeps the message-delta semantics")
        # tuned-config consumption, sharded context: layout/precision/
        # bnb adopt from the home-rung sidecar when not pinned (the
        # space's validity rules keep e.g. fused off amaxsum)
        adopted, tuning_sources, tuned_rung = _tuned_resolution(
            args, dcop, params, "sharded",
            adoptable={"layout", "precision", "bnb"})
        params.update(adopted)
        if "precision" in adopted and not precision_name:
            from ..ops.precision import resolve as _resolve_precision

            precision_name = _resolve_precision(
                adopted["precision"]).name
        # same trace granularity rules as engine mode; the sharded
        # trace is recorded ON DEVICE by the mesh engine (zero extra
        # host round-trips), so asking for it never slows the sync path
        collect_every = None
        if args.period:
            collect_every = max(1, int(round(args.period)))
        elif args.run_metrics:
            collect_every = 16
        with profile_trace(profile_dir):
            res = solve_sharded_result(
                dcop, args.algo, n_cycles=args.max_cycles,
                batch=args.batch, seed=args.seed, timeout=timeout,
                collect_cost_every=collect_every,
                telemetry=bool(telemetry_path),
                checkpointer=checkpointer,
                resume=getattr(args, "resume", False), **params)
        cost, violations = dcop.solution_cost(
            res.assignment, infinity=args.infinity)
        if collector is not None:
            for cycle, c in res.cost_trace:
                collector.put(("", "global", "", c, cycle))
            collector.stop()
        # real message-plane traffic derived from the compiled layout
        # (edges x domain x store-dtype itemsize x cycles run x batch)
        # instead of the old hardcoded zeros
        msg_count = res.metrics.get("msg_per_cycle", 0) * res.cycles
        msg_size = res.metrics.get("bytes_per_cycle", 0) * res.cycles
        result = {
            # the runner reports whether its own termination fired
            # (SAME_COUNT stability, DBA zero violations) — even when
            # it fires exactly on the last budgeted cycle
            "status": res.status,
            "assignment": res.assignment,
            "cost": cost,
            "violation": violations,
            "cycle": res.cycles,
            "time": time.perf_counter() - t0,
            "msg_count": msg_count,
            "msg_size": msg_size,
        }
        if precision_name:
            result["precision"] = precision_name
        result.update(_feature_result_fields(args, decim, bnb_flag))
        if tuning_sources:
            result["tuning"] = tuning_sources
            result["tuned_rung"] = tuned_rung
        if checkpointer is not None:
            result.update(checkpointer.telemetry())
        if res.cost_trace:
            result["cost_trace"] = res.cost_trace
        if telemetry_path:
            _report_telemetry(telemetry_path, args, res, result,
                              dcop=dcop)
        if args.end_metrics:
            _append_end_metrics(args.end_metrics, result)
        output_json(result, args.output)
        return 0

    if args.mode == "engine":
        from ..infrastructure.run import solve_result

        collect_every = None
        if args.period:
            collect_every = max(1, int(round(args.period)))
        elif args.run_metrics:
            collect_every = 16  # default trace granularity (cycles)
        with profile_trace(profile_dir):
            try:
                res = solve_result(
                    dcop, algo_def, distribution=args.distribution,
                    timeout=timeout, max_cycles=args.max_cycles,
                    seed=args.seed,
                    collect_cost_every=collect_every,
                    telemetry=bool(telemetry_path),
                    checkpointer=checkpointer,
                    resume=getattr(args, "resume", False))
            except ValueError as e:
                from ..robustness.checkpoint import CheckpointError

                if checkpointer is not None and (
                        isinstance(e, CheckpointError)
                        or "--checkpoint" in str(e)):
                    # a structured refusal (fingerprint/state
                    # mismatch, or a solve_direct family with no
                    # chunk boundaries) is a clean CLI error, not a
                    # traceback
                    raise CliError(str(e))
                raise
        metrics = res.metrics
        if collector is not None:
            # engine mode has no per-computation value stream; feed the
            # global cost trace so --run_metrics is never silently empty
            for cycle, cost in res.cost_trace:
                collector.put(("", "global", "", cost, cycle))
    else:
        from ..infrastructure.run import run_dcop

        with profile_trace(profile_dir):
            res = run_dcop(
                dcop, algo_def, distribution=args.distribution,
                mode=args.mode, timeout=timeout,
                max_cycles=args.max_cycles,
                seed=args.seed, collector=collector,
                collect_moment=args.collect_on,
                collect_period=args.period, delay=args.delay,
                uiport=args.uiport)
        metrics = res.metrics

    if collector is not None:
        collector.stop()

    cost, violations = res.cost, res.violations
    if res.assignment and set(res.assignment) == set(dcop.variables):
        # violations are counted against args.infinity and excluded
        # from the soft cost; cost and violation come from the SAME
        # solution_cost call so they can never disagree (reference:
        # solve.py:448 + dcop.py:319-369)
        cost, violations = dcop.solution_cost(res.assignment,
                                              infinity=args.infinity)
    result = {
        "status": res.status,
        "assignment": res.assignment,
        "cost": cost,
        "violation": violations,
        "cycle": res.cycles,
        "time": time.perf_counter() - t0,
        "msg_count": metrics.get("msg_count", 0),
        "msg_size": metrics.get("msg_size", 0),
    }
    if precision_name and args.mode == "engine":
        # the orchestrated (thread/process) fabric computes in host
        # float64 — the policy applies to the compiled data plane only
        result["precision"] = precision_name
    if args.mode == "engine":
        result.update(_feature_result_fields(args, decim, bnb_flag))
        if tuning_sources:
            # per-knob resolution echo (explicit/tuned/default) plus
            # the rung whose sidecar was consulted
            result["tuning"] = tuning_sources
            result["tuned_rung"] = tuned_rung
    if checkpointer is not None:
        result.update(checkpointer.telemetry())
    if res.cost_trace:
        result["cost_trace"] = res.cost_trace
    if telemetry_path:
        _report_telemetry(telemetry_path, args, res, result, dcop=dcop)
    if args.end_metrics:
        _append_end_metrics(args.end_metrics, result)
    output_json(result, args.output)
    return 0


def _build_portfolio_checkpointer(args, race, precision_name):
    """The race's checkpointer from ``--checkpoint DIR``: named by
    instance × canonical arm grid × base seed, fingerprinted by the
    program identity PLUS the arm-grid hash and kill-rule knobs
    (``PortfolioRace.fingerprint_extra``) — a resume under a drifted
    grid or referee refuses with a structured mismatch."""
    directory = getattr(args, "checkpoint", None)
    if not directory:
        if getattr(args, "resume", False):
            raise CliError(
                "--resume restores a --checkpoint snapshot: give "
                "the checkpoint directory too")
        return None
    every = getattr(args, "checkpoint_every", 256)
    if every < 1:
        raise CliError("--checkpoint-every must be >= 1 cycles")
    from ..parallel.portfolio import canonical_spec
    from ..robustness.checkpoint import (CheckpointStore,
                                         SolveCheckpointer,
                                         checkpoint_fingerprint,
                                         env_preempt_hook,
                                         portfolio_checkpoint_name)

    try:
        preempt_after, on_preempt = env_preempt_hook()
        store = CheckpointStore(directory)
    except (OSError, ValueError) as e:
        raise CliError(str(e))
    fingerprint = checkpoint_fingerprint(
        precision=precision_name or "f32", algo="portfolio")
    fingerprint.update(race.fingerprint_extra())
    return SolveCheckpointer(
        store,
        portfolio_checkpoint_name(args.dcop_files,
                                  canonical_spec(race.arms),
                                  args.seed),
        every=every, fingerprint=fingerprint,
        preempt_after=preempt_after, on_preempt=on_preempt)


def _run_portfolio(args, t0: float, timeout, decim,
                   bnb_flag: bool) -> int:
    """``solve --portfolio``: race arm configurations over one
    instance as vmapped lanes, early-kill losers at chunk boundaries,
    keep the winner (``parallel/portfolio.py``)."""
    from . import parse_algo_params
    from ..parallel.portfolio import (PortfolioRace,
                                      PortfolioSpecError,
                                      parse_portfolio_spec)
    from ..robustness.checkpoint import CheckpointError

    if args.mode != "engine":
        raise CliError(
            "--portfolio races vmapped arm lanes through the "
            "compiled batch runners: mode engine only, not "
            f"{args.mode!r}")
    if getattr(args, "scenario", None):
        raise CliError(
            "--portfolio races ONE static instance; a --scenario "
            "warm replay keeps its single configured engine")
    if bnb_flag:
        raise CliError(
            "--portfolio arms run through batched runners, which "
            "reject bnb (per-instance pruning plans cannot ride a "
            "vmapped arm lane)")
    precision_name = _resolved_precision_name(args)
    base_params = parse_algo_params(args.algo_params)
    if decim:
        # the --decimation flag becomes the maxsum arms' baseline
        # schedule, same sugar rule as the plain solve path
        base_params.setdefault("decimation_p", str(decim[0]))
        base_params.setdefault("decimation_every", str(decim[1]))
    dcop = load_dcop_from_file(args.dcop_files)
    try:
        arms = parse_portfolio_spec(
            args.portfolio, base_algo=args.algo,
            base_params=base_params, base_seed=args.seed,
            mode=dcop.objective)
        race = PortfolioRace(
            dcop, arms, max_cycles=args.max_cycles,
            every=getattr(args, "portfolio_every", 32),
            margin=getattr(args, "portfolio_margin", 0.05),
            patience=getattr(args, "portfolio_patience", 3),
            plateau=getattr(args, "portfolio_plateau", 6),
            precision=precision_name)
    except (PortfolioSpecError, ValueError) as e:
        raise CliError(str(e))
    checkpointer = _build_portfolio_checkpointer(args, race,
                                                 precision_name)
    try:
        result = race.run(checkpointer=checkpointer,
                          resume=getattr(args, "resume", False),
                          timeout=timeout)
    except CheckpointError as e:
        raise CliError(str(e))
    if result["assignment"] and \
            set(result["assignment"]) == set(dcop.variables):
        # the headline cost/violation follow the CLI's --infinity
        # semantics exactly like the plain solve path; the per-arm
        # bests in the portfolio block stay the device evaluator's
        cost, violations = dcop.solution_cost(
            result["assignment"], infinity=args.infinity)
        result["cost"], result["violation"] = cost, violations
    result["time"] = time.perf_counter() - t0
    result["msg_count"] = 0
    result["msg_size"] = 0
    if precision_name:
        result["precision"] = precision_name
    if checkpointer is not None:
        result.update(checkpointer.telemetry())
    telemetry_path = getattr(args, "telemetry", None)
    if telemetry_path:
        from ..observability.report import RunReporter

        with RunReporter(telemetry_path, algo=args.algo,
                         mode="portfolio") as reporter:
            reporter.header(
                dcop=getattr(dcop, "name", None), seed=args.seed,
                max_cycles=args.max_cycles,
                precision=precision_name,
                portfolio=result["portfolio"]["spec"])
            summary = {k: result[k] for k in
                       ("status", "cost", "violation", "cycle",
                        "time", "msg_count", "msg_size",
                        "portfolio")}
            for k in ("checkpoint_s", "checkpoint_bytes",
                      "resumed_from_cycle"):
                if k in result:
                    summary[k] = result[k]
            reporter.summary(**summary)
    if args.end_metrics:
        _append_end_metrics(args.end_metrics, result)
    output_json(result, args.output)
    return 0


def _run_scenario(args, dcop, t0: float, timeout,
                  precision_name: Optional[str]) -> int:
    """``solve --scenario``: the warm dynamic-DCOP replay.  The
    initial solve compiles once; every event re-solve re-enters the
    same program (``dynamics/engine.py``) — the spans in the
    telemetry records prove it."""
    from . import output_json, parse_algo_params
    from ..dcop.scenario import ScenarioError
    from ..dcop.yamldcop import load_scenario_from_file
    from ..dynamics import DeltaError, DynamicEngine, replay_scenario

    if args.algo != "maxsum":
        raise CliError(
            "--scenario replays through the compiled scenario "
            f"engine, which speaks maxsum only (got {args.algo!r})")
    if args.mode not in ("engine", "sharded"):
        raise CliError(
            "--scenario needs the compiled data plane: mode engine "
            f"or sharded, not {args.mode!r} (the orchestrated "
            "runtime replays scenarios via the `run` command)")
    if getattr(args, "decimation", None) or getattr(args, "bnb",
                                                    False):
        raise CliError(
            "--scenario composes with neither --decimation nor "
            "--bnb (both bake per-instance state the edits would "
            "leave stale)")
    try:
        scenario = load_scenario_from_file(args.scenario)
    except ScenarioError as e:
        raise CliError(f"bad scenario {args.scenario}: {e}")
    given = parse_algo_params(args.algo_params)
    algo_def = build_algo_def(args.algo, args.algo_params,
                              mode=dcop.objective)
    # engine-only keys (stop_cycle/seed) are stripped by
    # DynamicEngine itself — ONE authority for the filter.  The
    # layout algo param is the warm engine's OWN kwarg (program
    # identity, not a solver parameter): lifted out here
    params = {k: algo_def.params[k] for k in given}
    layout = params.pop("layout", None) or "edge_major"
    if getattr(args, "precision", None):
        params["precision"] = args.precision
    try:
        engine = DynamicEngine(
            dcop, algo=args.algo, mode=args.mode,
            reserve=getattr(args, "reserve_slots", None),
            params=params, max_cycles=args.max_cycles,
            carry=getattr(args, "carry", "messages"),
            layout=layout,
            warm_budget=getattr(args, "warm_budget", "adaptive"),
            roi=getattr(args, "roi", False),
            roi_residual_threshold=getattr(
                args, "roi_residual_threshold", None))
    except ValueError as e:
        raise CliError(str(e))

    reporter = None
    telemetry_path = getattr(args, "telemetry", None)
    if telemetry_path:
        from ..observability.report import RunReporter

        reporter = RunReporter(telemetry_path, algo=args.algo,
                               mode=args.mode)
        reporter.header(
            dcop=getattr(dcop, "name", None), seed=args.seed,
            max_cycles=args.max_cycles,
            precision=precision_name,
            scenario=args.scenario,
            carry=engine.carry,
            layout=engine.layout,
            warm_budget=engine.warm_budget,
            reserve=getattr(args, "reserve_slots", None))
    try:
        replay = replay_scenario(
            engine, scenario, max_cycles=args.max_cycles,
            seed=args.seed, timeout=timeout, reporter=reporter)
    except DeltaError as e:
        raise CliError(
            f"scenario event rejected ({e.kind}): {e} "
            f"[{e.details}]")
    finally:
        if reporter is not None:
            reporter.close()
    solved = [e for e in replay["events"] if "assignment" in e]
    final = solved[-1] if solved else replay["initial"]
    result = {
        "status": final["status"],
        "assignment": final["assignment"],
        "cost": final["cost"],
        "violation": final["violation"],
        "cycle": final["cycle"],
        "time": time.perf_counter() - t0,
        "scenario": {
            "file": args.scenario,
            "events_applied": len(solved),
            "delays": sum(1 for e in replay["events"]
                          if "delay" in e),
            "carry": engine.carry,
            "layout": engine.layout,
            "warm_budget": engine.warm_budget,
            "roi": engine.roi,
            "roi_mode": engine.roi_mode,
            "reserve": getattr(args, "reserve_slots", None),
            "budget": replay["budget"],
            "initial": _scenario_event_summary(replay["initial"]),
            "events": [
                e if "status" not in e
                else _scenario_event_summary(e)
                for e in replay["events"]],
        },
    }
    if precision_name:
        result["precision"] = precision_name
    if args.end_metrics:
        # per-run summary semantics: the FINAL state's numbers
        result_row = dict(result, msg_count=0, msg_size=0)
        _append_end_metrics(args.end_metrics, result_row)
    output_json(result, args.output)
    return 0


def _scenario_event_summary(e: dict) -> dict:
    """Per-event result row of the scenario block: everything except
    the (potentially huge) per-event assignment — the top-level
    result carries the final one."""
    out = {k: e[k] for k in ("status", "cost", "violation", "cycle",
                             "warm_start", "spans", "upload_bytes",
                             "chunks_run", "settle_chunk",
                             "active_fraction",
                             "frontier_expansions",
                             "roi_mode", "roi_flipped")
           if k in e}
    for k in ("event", "edit"):
        if e.get(k) is not None:
            out[k] = e[k]
    return out


def _report_telemetry(path: str, args, res, result: dict, dcop=None):
    """Emit the run's JSONL telemetry: header (solver/layout/precision/
    mesh/compile stats), one record per executed cycle, and the final
    summary — one schema across solve/batch/sharded
    (observability/report.py).  Thread/process runs have no compiled
    chunk: they emit header + summary only."""
    from ..observability.report import RunReporter

    reporter = RunReporter(path, algo=args.algo, mode=args.mode)
    try:
        _report_telemetry_records(reporter, args, res, result, dcop)
    finally:
        reporter.close()


def _report_telemetry_records(reporter, args, res, result: dict,
                              dcop=None):
    from . import parse_algo_params

    header = {
        "dcop": getattr(dcop, "name", None),
        "seed": args.seed,
        "max_cycles": args.max_cycles,
        "precision": result.get("precision"),
        "layout": parse_algo_params(args.algo_params).get("layout"),
    }
    if args.mode == "sharded":
        import jax

        from ..parallel import make_mesh

        mesh = make_mesh()
        header["mesh"] = dict(mesh.shape)
        header["batch"] = args.batch or mesh.shape["dp"]
        header["devices"] = len(jax.devices())
    if res.compile_stats:
        header["compile_stats"] = res.compile_stats
    reporter.header(**header)
    reporter.cycles(res.cycle_metrics)
    spans = res.metrics.get("spans")
    summary = {
        "status": result["status"],
        "cost": result["cost"],
        "violation": result["violation"],
        "cycle": result["cycle"],
        "time": result["time"],
        "msg_count": result["msg_count"],
        "msg_size": result["msg_size"],
    }
    if spans:
        summary["spans"] = spans
    # the preemption-safety fields (schema minor 6) ride the summary
    # whenever the run checkpointed or resumed
    for k in ("checkpoint_s", "checkpoint_bytes",
              "resumed_from_cycle"):
        if k in result:
            summary[k] = result[k]
    # per-knob tuned-config resolution (schema minor 9) rides the
    # summary whenever the store was consulted
    for k in ("tuning", "tuned_rung"):
        if k in result:
            summary[k] = result[k]
    reporter.summary(**summary)


END_METRICS_COLUMNS = ["time", "status", "cost", "violation", "cycle",
                       "msg_count", "msg_size"]


def _append_end_metrics(path: str, result: dict):
    """Append one end-of-run summary row, writing the header when the
    file is new (reference: solve.py:411-443)."""
    new_file = not os.path.exists(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a", newline="") as f:
        writer = csv.writer(f)
        if new_file:
            writer.writerow(END_METRICS_COLUMNS)
        writer.writerow([result[c] for c in END_METRICS_COLUMNS])



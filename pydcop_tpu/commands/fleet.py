"""``pydcop fleet``: N serve workers behind one routing socket.

The horizontal half of solver-as-a-service (ISSUE 19): one
:class:`~pydcop_tpu.serving.fleet.FleetRouter` owns the client-facing
unix socket and speaks the same request schema as a solo ``pydcop
serve`` daemon, consistent-hashing delta targets (and the maxsum
solves that may become targets) across N worker daemons while
spilling other cold solves to the shallowest queue.  Workers share
one executable cache, tuned-config store, session-journal and
checkpoint directory under ``--fleet-dir``, and append (worker_id-
stamped, schema minor 10) to one ``--out`` file.

SIGTERM drains the fleet: each worker is rolling-drained (its queued
jobs requeue, its warm sessions keep their journals) and the router
exits once every in-flight job is answered or re-routed.

Examples::

    pydcop fleet --workers 4 --socket /tmp/fleet.sock \
        --fleet-dir /var/lib/pydcop/fleet
    pydcop fleet --workers 2 --oneshot jobs.jsonl --fleet-dir d/

``pydcop serve-status --socket /tmp/fleet.sock`` renders the
aggregated snapshot (repeat ``--socket`` to also interrogate worker
sockets directly).
"""

import json
import os
import signal
import sys
import threading

from . import CliError


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "fleet",
        help="run N serve workers behind one consistent-hash "
             "routing socket (shared exec cache / tuned store / "
             "session journals; live warm-session migration)")
    parser.add_argument("--workers", type=int, default=2,
                        metavar="N",
                        help="number of worker daemons to spawn "
                             "(default 2)")
    parser.add_argument("--socket", type=str, default=None,
                        metavar="PATH",
                        help="client-facing unix socket (same "
                             "schema as `pydcop serve --socket`); "
                             "default: read requests from stdin, "
                             "EOF drains")
    parser.add_argument("--oneshot", type=str, default=None,
                        metavar="JOBS.jsonl",
                        help="feed requests from this file, drain "
                             "the fleet, exit")
    parser.add_argument("--fleet-dir", dest="fleet_dir", type=str,
                        default="pydcop_fleet", metavar="DIR",
                        help="fleet state root: exec/ tuned/ "
                             "journal/ ckpt/ subdirs shared by all "
                             "workers, plus per-worker sockets and "
                             "stderr captures (default: "
                             "./pydcop_fleet)")
    parser.add_argument("--out", type=str, default=None,
                        metavar="out.jsonl",
                        help="shared JSONL telemetry file all "
                             "workers and the router append to, "
                             "each record stamped with its "
                             "worker_id (default: "
                             "FLEET_DIR/fleet_out.jsonl)")
    parser.add_argument("--max-batch", dest="max_batch", type=int,
                        default=8,
                        help="per-worker rung-fills dispatch "
                             "trigger (forwarded to every worker)")
    parser.add_argument("--max-delay-ms", dest="max_delay_ms",
                        type=float, default=25.0,
                        help="per-worker latency-deadline dispatch "
                             "trigger (forwarded)")
    parser.add_argument("--max-cycles", "--max_cycles",
                        dest="max_cycles", type=int, default=2000,
                        help="default cycle budget (forwarded)")
    parser.add_argument("--seed", type=int, default=0,
                        help="default engine seed (forwarded)")
    parser.add_argument("--metrics-port", dest="metrics_port",
                        type=int, default=None, metavar="PORT",
                        help="Prometheus endpoint for the ROUTER's "
                             "worker-labeled fleet metrics "
                             "(pydcop_fleet_*); /stats serves the "
                             "aggregated fleet snapshot")
    parser.add_argument("--slo", type=str, default=None,
                        metavar="FILE",
                        help="declarative service-level objectives "
                             "(YAML, observability/slo.py), "
                             "forwarded to every worker: each "
                             "evaluates locally at its heartbeat and "
                             "the router aggregates the rows (worst "
                             "worker wins) in its stats snapshot — "
                             "`pydcop serve-status` on the router "
                             "socket renders the fleet-wide table")
    parser.add_argument("--worker-arg", dest="worker_args",
                        action="append", default=None,
                        metavar="ARG",
                        help="extra flag forwarded verbatim to "
                             "every worker's `pydcop serve` command "
                             "line (repeatable), e.g. "
                             "--worker-arg=--roi")
    parser.add_argument("--connect-timeout-s",
                        dest="connect_timeout_s", type=float,
                        default=180.0, metavar="S",
                        help="how long to wait for each spawned "
                             "worker to bind its socket (workers "
                             "import jax on startup)")
    parser.set_defaults(func=run_cmd)
    return parser


def run_cmd(args, timeout=None):
    from ..observability.report import RunReporter
    from ..serving.fleet import (ROUTER_ID, FleetManager, FleetRouter,
                                 WorkerError)

    if args.workers < 1:
        raise CliError("--workers must be >= 1")
    if args.oneshot and args.socket:
        raise CliError("--oneshot and --socket are mutually exclusive")

    slo_file = getattr(args, "slo", None)
    if slo_file:
        from ..observability.slo import SLOError, load_objectives

        try:
            # validate at the router so a malformed objectives file
            # fails ONCE here, not N times in worker stderr captures
            load_objectives(slo_file)
        except SLOError as e:
            raise CliError(str(e))
        except OSError as e:
            raise CliError(f"--slo file unusable: {e}")

    manager = FleetManager(
        args.fleet_dir, out=args.out,
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
        max_cycles=args.max_cycles, seed=args.seed,
        worker_args=args.worker_args, slo=slo_file)

    registry = None
    from ..observability.registry import MetricsRegistry

    registry = MetricsRegistry()
    from ..observability.buildinfo import build_info_metric

    build_info_metric(registry)

    reporter = RunReporter(manager.out, algo="serve", mode="serve",
                           worker_id=ROUTER_ID)
    from ..observability.flightrec import (FlightRecorder,
                                           flightrec_path)

    flightrec = None
    try:
        flightrec = FlightRecorder(
            flightrec_path(os.path.dirname(manager.out) or ".",
                           ROUTER_ID),
            worker_id=ROUTER_ID)
    except OSError as e:
        print(f"[fleet] flight recorder disabled: {e}",
              file=sys.stderr)
    metrics_server = None
    stop = threading.Event()
    router = None
    try:
        reporter.header(
            fleet_workers=args.workers, fleet_dir=manager.fleet_dir,
            max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
            max_cycles=args.max_cycles, slo=slo_file,
            source=("oneshot" if args.oneshot
                    else "socket" if args.socket else "stdin"))
        router = FleetRouter(reporter=reporter, registry=registry,
                             checkpoint_dir=manager.ckpt_dir,
                             flightrec=flightrec)
        try:
            manager.start(router, args.workers,
                          connect_timeout=args.connect_timeout_s)
        except WorkerError as e:
            raise CliError(str(e))
        print(f"[fleet] {args.workers} worker(s) up under "
              f"{manager.fleet_dir}", file=sys.stderr)

        if args.metrics_port is not None:
            from ..observability.registry import MetricsHTTPServer

            metrics_server = MetricsHTTPServer(
                registry, port=args.metrics_port,
                snapshot_fn=router.stats_snapshot)
            print(f"[fleet] metrics on http://127.0.0.1:"
                  f"{metrics_server.port}/metrics", file=sys.stderr)

        prev_term = signal.signal(
            signal.SIGTERM, lambda _s, _f: stop.set())
        try:
            if args.oneshot:
                if not os.path.exists(args.oneshot):
                    raise CliError(
                        f"oneshot jobs file not found: "
                        f"{args.oneshot}")
                with open(args.oneshot) as f:
                    for line in f:
                        router.feed(line)
                router.drain()
            elif args.socket:
                from ..serving.sources import SocketServer

                server = SocketServer(router, args.socket)
                try:
                    while not stop.wait(0.2):
                        pass
                finally:
                    server.close()
                router.drain(timeout=60.0)
            else:
                for line in sys.stdin:
                    if stop.is_set():
                        break
                    router.feed(line)
                router.drain()
        finally:
            signal.signal(signal.SIGTERM, prev_term)
        snap = router.stats_snapshot()
        fl = snap["fleet"]["router"]
        print(f"[fleet] received={fl['received']} "
              f"routed={fl['routed']} spilled={fl['spilled']} "
              f"replies={fl['replies']} "
              f"failovers={fl['failovers']}", file=sys.stderr)
        reporter.serve(event="stats",
                       **{k: v for k, v in snap.items()
                          if k not in ("record", "algo", "mode",
                                       "event")})
    finally:
        if metrics_server is not None:
            metrics_server.close()
        if router is not None:
            manager.shutdown(router)
        if flightrec is not None:
            flightrec.dump("shutdown")
            flightrec.close()
        reporter.close()
    return 0

"""On-device solver portfolios: race arm configs, keep the winner.

The fused batch runners (``parallel/batch.py``) vmap many INSTANCES
through one solver config.  A portfolio flips that axis: ONE instance
rides every lane, and the lanes differ by solver *arm* — seed, family
(maxsum / dsa / mgm), damping, decimation schedule, DSA variant...
No single config dominates (the DSA vs decimated-MaxSum conflict-rate
gaps in bench_decimation are the motivating measurement), so the
principled answer is to race them and keep the winner.

Mechanics, all reused from machinery already proven bit-exact:

* Arms sharing a trace signature (family + every non-seed hyperparam)
  become ONE vmapped broadcast-batched runner — the instance cubes are
  broadcast across the lanes, per-arm RNG comes from per-lane PRNG
  keys (``_batch_keys``; dsa/mgm per-variable draws are pad-stable via
  ``ops.kernels.prefix_uniform``).  Arm hyperparameters that differ
  only by SEED are program arguments, so an arm set never retraces;
  arms with different hyperparams group into separate programs
  (hyperparams are trace constants of the compiled step — damping
  folds into the message recurrence, decimation changes the carry).
* The race advances in compiled chunks through the checkpointed drive
  triple (``_ckpt_programs``: init / chunk-to-traced-limit / decode).
  At each chunk boundary — the existing two-scalar host sync, zero
  extra round-trips — every arm is scored by the vmapped
  ``assignment_cost_violations`` evaluator and the host referee
  (``ops/arm_race.py``) kills losing arms: trailing the leader beyond
  a margin for ``patience`` consecutive boundaries, or a best-cost
  plateau for ``plateau`` boundaries.
* A killed arm's lanes become masked no-op lanes inside the compiled
  chunk (``finished |= dead`` — the while-loop cond already skips
  finished lanes, the decimation freeze-plane trick applied to whole
  lanes), and when the live count halves the survivors REBATCH down
  the pow2 rung ladder (``runner_for_arm_group``): state sliced by
  ``tree_map``, a fresh smaller runner whose compile is that rung's
  first dispatch.
* The survivor set rides the PR 15 checkpoint: at every boundary the
  group states + referee state + per-arm best selections snapshot
  through :class:`~pydcop_tpu.robustness.checkpoint.SolveCheckpointer`
  (atomic write, fingerprint manifest carrying the ARM-GRID hash so a
  drifted resume refuses), and a ``kill -9`` + ``--resume`` reproduces
  the uninterrupted race bit-exactly — scoring and kills are pure
  functions of the restored state.

Spec grammar (``solve --portfolio``, ``batch --portfolio``, the serve
``portfolio`` job field)::

    auto                                  # the built-in 8-arm preset
    "maxsum;maxsum,damping:0.9;dsa,variant:A,seeds:2"

Arms are ``;``-separated; each arm is ``family[,name:value...]`` with
two special keys: ``seed:N`` pins the arm's engine seed and
``seeds:N`` expands the arm into N replicas seeded ``base..base+N-1``.
``layout`` and ``bnb`` are rejected loudly (layouts are warm-engine
program identity, bnb plans are per-instance trace constants — neither
can ride a vmapped arm lane).
"""

import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.arm_race import (new_race, race_from_host, race_summary,
                            race_to_host, race_update)

#: families with a vmapped batched solver — the only legal arm families
#: (mirrors serving/schema.SERVABLE_ALGOS; asserted equal in tests)
PORTFOLIO_FAMILIES = ("maxsum", "dsa", "mgm")

#: the ``auto`` preset: one spread across the family x schedule space
#: the decimation/DSA benches showed no single point of dominating —
#: two damping points, a decimated arm, the DSA variants, MGM, and a
#: second seed on the default maxsum arm
AUTO_SPEC = ("maxsum;"
             "maxsum,seed:1;"
             "maxsum,damping:0.9;"
             "maxsum,decimation_p:0.05,decimation_every:8;"
             "dsa,variant:A;"
             "dsa,variant:B;"
             "dsa,variant:C;"
             "mgm")

#: arm-parameter keys that can never ride a vmapped lane, with the
#: reason given on rejection (never a silent downgrade)
_REJECTED_ARM_PARAMS = {
    "layout": "layouts are warm-engine program identity, not a "
              "batched-arm parameter (every arm lane runs the "
              "canonical edge-major step)",
    "bnb": "bnb pruned-reduction plans are build-time constants of "
           "one instance's cubes and cannot ride a vmapped arm lane",
    "stop_cycle": "stop_cycle is an engine-level knob; give the race "
                  "one budget via max_cycles",
}

_PORTFOLIO_DEFAULTS = {"every": 32, "margin": 0.05, "patience": 3,
                       "plateau": 6}


class PortfolioSpecError(ValueError):
    """A malformed ``--portfolio`` spec; raised at parse time (CLI
    startup / serve admission), never mid-race."""


@dataclass(frozen=True)
class Arm:
    """One racing configuration: a solver family, an engine seed and
    the family's (typed, validated) hyperparameters."""

    algo: str
    seed: int
    params: Tuple[Tuple[str, Any], ...] = ()

    @property
    def params_dict(self) -> Dict[str, Any]:
        return dict(self.params)

    @property
    def label(self) -> str:
        """Stable human-readable arm name used in telemetry and the
        result block: ``maxsum[damping:0.9,s3]``."""
        inner = ",".join(f"{k}:{v}" for k, v in self.params)
        inner = f"{inner},s{self.seed}" if inner else f"s{self.seed}"
        return f"{self.algo}[{inner}]"

    @property
    def group_key(self) -> Tuple:
        """The trace-signature part of the arm: everything but the
        seed.  Arms sharing it run as lanes of ONE vmapped program."""
        return (self.algo, self.params)


def parse_portfolio_spec(spec: str,
                         base_algo: Optional[str] = None,
                         base_params: Optional[Dict[str, Any]] = None,
                         base_seed: int = 0,
                         mode: str = "min") -> List[Arm]:
    """Spec string -> validated arm list (see the module docstring for
    the grammar).  ``base_params`` seed the params of arms whose family
    matches ``base_algo`` (the solve CLI's ``-a``/``-p`` become the
    baseline every same-family arm inherits); an arm's own ``k:v``
    wins.  Values are cast and validated through the family's own
    ``AlgoParameterDef`` table, so a typoed arm parameter dies here
    with the algorithm's error message, never inside a compiled race.
    """
    from ..algorithms import AlgoParameterException, AlgorithmDef

    text = (spec or "").strip()
    if not text:
        raise PortfolioSpecError("empty --portfolio spec")
    if text == "auto":
        text = AUTO_SPEC
    arms: List[Arm] = []
    for ai, chunk in enumerate(text.split(";")):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = [p.strip() for p in chunk.split(",") if p.strip()]
        algo = parts[0]
        if algo not in PORTFOLIO_FAMILIES:
            raise PortfolioSpecError(
                f"arm {ai} ({chunk!r}): family {algo!r} has no "
                f"vmapped batch solver; portfolio families: "
                f"{', '.join(PORTFOLIO_FAMILIES)}")
        raw: Dict[str, str] = {}
        if base_params and algo == base_algo:
            for k, v in base_params.items():
                if k == "seed":
                    continue  # the race owns per-arm seeding
                if k in _REJECTED_ARM_PARAMS:
                    raise PortfolioSpecError(
                        f"base -p param {k}: "
                        f"{_REJECTED_ARM_PARAMS[k]}")
                raw[k] = str(v)
        seed: Optional[int] = None
        replicas = 1
        for p in parts[1:]:
            k, sep, v = p.partition(":")
            k, v = k.strip(), v.strip()
            if not sep or not k or not v:
                raise PortfolioSpecError(
                    f"arm {ai} ({chunk!r}): parameter {p!r} is not "
                    f"'name:value'")
            if k in _REJECTED_ARM_PARAMS:
                raise PortfolioSpecError(
                    f"arm {ai} ({chunk!r}): {k}: "
                    f"{_REJECTED_ARM_PARAMS[k]}")
            if k == "seed":
                seed = _spec_int(ai, chunk, k, v)
            elif k == "seeds":
                replicas = _spec_int(ai, chunk, k, v)
                if replicas < 1:
                    raise PortfolioSpecError(
                        f"arm {ai} ({chunk!r}): seeds wants a "
                        f"positive replica count, got {v!r}")
            else:
                raw[k] = v
        try:
            algo_def = AlgorithmDef.build_with_default_param(
                algo, params=dict(raw), mode=mode)
        except AlgoParameterException as e:
            raise PortfolioSpecError(
                f"arm {ai} ({chunk!r}): {e}")
        params = tuple(sorted(
            (k, algo_def.params[k]) for k in raw))
        if seed is not None and replicas > 1:
            raise PortfolioSpecError(
                f"arm {ai} ({chunk!r}): seed: and seeds: are "
                f"mutually exclusive (seeds expands replicas from "
                f"the base seed)")
        if seed is not None:
            arms.append(Arm(algo, int(seed), params))
        else:
            for r in range(replicas):
                arms.append(Arm(algo, int(base_seed) + r, params))
    if not arms:
        raise PortfolioSpecError(f"spec {spec!r} defines no arms")
    labels = [a.label for a in arms]
    dupes = sorted({x for x in labels if labels.count(x) > 1})
    if dupes:
        raise PortfolioSpecError(
            f"duplicate arm(s) {', '.join(dupes)}: identical "
            f"family+params+seed lanes would race byte-identical "
            f"programs")
    return arms


def _spec_int(ai, chunk, k, v) -> int:
    try:
        return int(v)
    except ValueError:
        raise PortfolioSpecError(
            f"arm {ai} ({chunk!r}): {k} wants an integer, got {v!r}")


def canonical_spec(arms: Sequence[Arm]) -> str:
    """The normalized spec string: arm labels joined by ``;`` — the
    form that feeds serve group keys and checkpoint fingerprints, so
    two spellings of the same grid share identity."""
    return ";".join(a.label for a in arms)


def spec_fingerprint(arms: Sequence[Arm]) -> str:
    """Short stable hash of the arm grid for checkpoint manifests."""
    return hashlib.sha256(
        canonical_spec(arms).encode()).hexdigest()[:16]


# ----------------------------------------------------------- the race


@dataclass
class _Group:
    """One arm group's racing machinery: the broadcast-batched runner,
    its compiled drive triple, the vmapped carry, and the lane -> arm
    map (``-1`` marks pow2 padding lanes, finished from birth)."""

    algo: str
    params: Dict[str, Any]
    arm_idx: List[int]
    runner: Any = None
    programs: Tuple = ()
    state: Any = None
    lane_arms: List[int] = field(default_factory=list)
    rebatches: int = 0

    @property
    def batch(self) -> int:
        return len(self.lane_arms)


class PortfolioRace:
    """Race ``arms`` over one DCOP instance; :meth:`run` returns a
    solve-shaped result dict plus the ``portfolio`` telemetry block.

    ``every`` is the scoring/kill cadence in cycles (each boundary is
    one compiled chunk per group), ``margin``/``patience``/``plateau``
    parameterize the kill rule (``ops/arm_race.py``).  ``precision``
    is the race-level default policy; an arm's own ``precision:``
    param wins.  ``exec_cache`` + ``instance_key`` (a stable identity
    of the instance file) let repeated races over the same instance —
    the serve admission shape — reuse runners and serialized
    evaluators across dispatches."""

    def __init__(self, dcop, arms: Sequence[Arm],
                 max_cycles: int = 2000,
                 every: int = _PORTFOLIO_DEFAULTS["every"],
                 margin: float = _PORTFOLIO_DEFAULTS["margin"],
                 patience: int = _PORTFOLIO_DEFAULTS["patience"],
                 plateau: int = _PORTFOLIO_DEFAULTS["plateau"],
                 precision: Optional[str] = None,
                 exec_cache=None,
                 instance_key: Optional[Tuple] = None):
        if not arms:
            raise PortfolioSpecError("a portfolio needs >= 1 arm")
        if every < 1:
            raise ValueError(f"--portfolio-every must be >= 1, "
                             f"got {every}")
        if patience < 1 or plateau < 1:
            raise ValueError("portfolio patience/plateau must be "
                             ">= 1")
        if margin < 0:
            raise ValueError(f"portfolio margin must be >= 0, "
                             f"got {margin}")
        self.dcop = dcop
        self.arms = list(arms)
        self.max_cycles = int(max_cycles)
        self.every = int(every)
        self.margin = float(margin)
        self.patience = int(patience)
        self.plateau = int(plateau)
        self.precision = precision
        self.exec_cache = exec_cache
        self.instance_key = instance_key
        self.minimize = getattr(dcop, "objective", "min") != "max"
        #: per-family template arrays, built once per (family,
        #: precision) the grid actually uses
        self._templates: Dict[Tuple, Any] = {}
        #: filled by run(): the boundary-by-boundary race event log
        #: (kills, rebatches) for observability consumers
        self.events: List[Dict[str, Any]] = []
        self.last_spans: Dict[str, float] = {}

    # ------------------------------------------------------ templates

    def _template_for(self, algo: str,
                      params: Dict[str, Any]):
        from ..dcop.dcop import filter_dcop
        from ..graphs.arrays import (FactorGraphArrays,
                                     HypergraphArrays)

        precision = params.get("precision") or self.precision
        family = "factor" if algo == "maxsum" else "hyper"
        key = (family, precision)
        arrays = self._templates.get(key)
        if arrays is None:
            if family == "factor":
                arrays = FactorGraphArrays.build(
                    self.dcop, arity_sorted=True,
                    precision=precision)
            else:
                arrays = HypergraphArrays.build(
                    filter_dcop(self.dcop), precision=precision)
            self._templates[key] = arrays
        return arrays

    # --------------------------------------------------------- groups

    def _build_groups(self) -> List[_Group]:
        """Arms grouped by trace signature, in first-appearance order
        (deterministic: the group list and lane order are part of the
        race's replayable identity)."""
        order: List[Tuple] = []
        by_key: Dict[Tuple, List[int]] = {}
        for i, arm in enumerate(self.arms):
            k = arm.group_key
            if k not in by_key:
                by_key[k] = []
                order.append(k)
            by_key[k].append(i)
        groups = []
        for k in order:
            algo, params_t = k
            params = dict(params_t)
            if self.precision and "precision" not in params:
                params["precision"] = self.precision
            groups.append(_Group(algo=algo, params=params,
                                 arm_idx=list(by_key[k])))
        return groups

    def _group_signature(self, group: _Group) -> Optional[Tuple]:
        """Cross-race runner/executable cache identity for one group:
        instance identity x family x params x arm-grid-free.  None
        without an ``instance_key`` (the compiled programs close over
        this instance's index tables, so caching without a stable
        instance identity would serve another instance's program)."""
        if self.instance_key is None:
            return None
        return (("portfolio",) + tuple(self.instance_key),
                group.algo, tuple(sorted(
                    (k, str(v)) for k, v in group.params.items())))

    def _open_group(self, group: _Group, lane_arms: List[int],
                    init_keys=None):
        """(Re)build one group's runner at ``len(lane_arms)`` lanes
        (already pow2-padded; ``-1`` = padding) and compile/fetch its
        drive triple.  ``init_keys`` seeds fresh lanes; omit it when
        the caller will install a restored/sliced state instead."""
        from .batch import runner_for_arm_group

        template = self._template_for(group.algo, group.params)
        runner = runner_for_arm_group(
            group.algo, template, len(lane_arms), group.params,
            group_signature=self._group_signature(group),
            exec_cache=self.exec_cache)
        group.runner = runner
        group.programs = runner._ckpt_programs()
        group.lane_arms = list(lane_arms)
        if init_keys is not None:
            init_all = group.programs[0]
            group.state = init_all(runner._instance_args, init_keys)
            pad = np.asarray([a < 0 for a in lane_arms], dtype=bool)
            if pad.any():
                group.state = self._mask_finished(group.state, pad)

    @staticmethod
    def _mask_finished(state, mask: np.ndarray):
        """Freeze lanes: ``finished |= mask`` makes them no-op lanes
        of the compiled chunk (its while-loop cond already skips
        finished lanes) — the decimation freeze-plane mechanics
        applied to whole lanes."""
        import jax.numpy as jnp

        fin = jnp.logical_or(state["finished"],
                             jnp.asarray(mask))
        return dict(state, finished=fin)

    # ----------------------------------------------------------- run

    def run(self, checkpointer=None, resume: bool = False,
            timeout: Optional[float] = None) -> Dict[str, Any]:
        """Run the race to a winner.  With ``checkpointer`` the
        survivor set snapshots at chunk boundaries and ``resume``
        restores the newest snapshot (arm-grid fingerprint checked by
        the manifest) and continues — reproducing the uninterrupted
        race bit-exactly."""
        import jax.numpy as jnp

        from .batch import _batch_keys
        from .bucketing import next_pow2

        t0 = time.perf_counter()
        race = new_race(len(self.arms), minimize=self.minimize)
        best_sel: List[Optional[np.ndarray]] = \
            [None] * len(self.arms)
        groups = self._build_groups()
        self.events = []
        boundary = 0

        restored = None
        if resume and checkpointer is not None:
            restored = checkpointer.load(template=None)
        if restored is not None:
            boundary, race, best_sel = self._restore(
                groups, restored)
        else:
            for g in groups:
                b = next_pow2(len(g.arm_idx))
                lane_arms = list(g.arm_idx) + [-1] * (b - len(
                    g.arm_idx))
                seeds = [self.arms[a].seed if a >= 0
                         else self.arms[lane_arms[0]].seed
                         for a in lane_arms]
                self._open_group(g, lane_arms,
                                 init_keys=_batch_keys(0, seeds, b))

        status = None
        while boundary < self.max_cycles and race["alive"].any():
            if timeout is not None and \
                    time.perf_counter() - t0 > timeout:
                status = "TIMEOUT"
                break
            limit = min(boundary + self.every, self.max_cycles)
            for g in groups:
                if not any(self._arm_live(race, a)
                           for a in g.lane_arms):
                    continue
                chunk_all = g.programs[1]
                g.state = chunk_all(g.runner._instance_args, g.state,
                                    jnp.int32(limit))
            boundary = limit
            self._score_boundary(groups, race, best_sel, boundary)
            if checkpointer is not None:
                done = (boundary >= self.max_cycles
                        or not race["alive"].any())
                payload = self._snapshot(groups, race, best_sel,
                                         boundary)
                checkpointer.maybe_save(boundary, lambda: payload,
                                        final=done)
            self._rebatch(groups, race, next_pow2)

        summary = race_summary(race,
                               labels=[a.label for a in self.arms])
        win = summary["winner_index"]
        if status is None:
            status = ("FINISHED" if race["finished"][win]
                      else "MAX_CYCLES")
        result = self._result(win, best_sel, race, summary, status,
                              time.perf_counter() - t0, groups)
        return result

    @staticmethod
    def _arm_live(race, arm: int) -> bool:
        return arm >= 0 and bool(race["alive"][arm])

    def _score_boundary(self, groups: List[_Group], race,
                        best_sel: List, boundary: int) -> None:
        """One boundary's scoring + kill pass: decode and evaluate
        every group's lanes (ONE vmapped evaluator call per group),
        fold the per-arm scores into the referee, then freeze the
        lanes of arms it killed."""
        n = len(self.arms)
        costs = np.full(n, np.nan)
        viols = np.zeros(n, dtype=np.int64)
        cycles = np.zeros(n, dtype=np.int64)
        finished = np.zeros(n, dtype=bool)
        sels: List[Optional[np.ndarray]] = [None] * n
        for g in groups:
            if not any(a >= 0 for a in g.lane_arms):
                continue
            decode_all = g.programs[2]
            sel = np.asarray(decode_all(g.runner._instance_args,
                                        g.state))
            cost_g, viol_g = g.runner.evaluate(sel)
            cyc = np.asarray(g.state["cycle"])
            fin = np.asarray(g.state["finished"])
            for lane, arm in enumerate(g.lane_arms):
                if arm < 0 or not race["alive"][arm]:
                    continue
                costs[arm] = cost_g[lane]
                viols[arm] = viol_g[lane]
                cycles[arm] = cyc[lane]
                finished[arm] = fin[lane]
                sels[arm] = sel[lane]
        scored = ~np.isnan(costs)
        costs = np.where(scored, costs, np.inf)
        prev_best_viol = race["best_viol"].copy()
        prev_best_cost = race["best_cost"].copy()
        update = race_update(race, costs, viols, cycles, finished,
                             margin=self.margin,
                             patience=self.patience,
                             plateau=self.plateau)
        improved = scored & (
            (race["best_viol"] != prev_best_viol)
            | (race["best_cost"] != prev_best_cost)
            | np.isinf(prev_best_cost))
        for a in np.flatnonzero(improved):
            if sels[a] is not None:
                best_sel[a] = sels[a].copy()
        if update["killed"]:
            self.events.append({
                "event": "kill", "boundary_cycle": int(boundary),
                "arms": [self.arms[a].label
                         for a in update["killed"]],
                "reasons": [str(race["kill_reason"][a])
                            for a in update["killed"]],
                "leader": self.arms[update["leader"]].label,
                "live": update["live"]})
            for g in groups:
                dead = np.asarray(
                    [a in update["killed"] for a in g.lane_arms],
                    dtype=bool)
                if dead.any():
                    g.state = self._mask_finished(g.state, dead)

    def _rebatch(self, groups: List[_Group], race,
                 next_pow2) -> None:
        """Survivor rebatch down the pow2 rung ladder: when a group's
        live lane count has halved, slice the survivors' carry rows
        out (``tree_map``) and continue on a fresh smaller runner —
        its compile is that rung's first dispatch, every later chunk
        of the rung reuses it."""
        import jax
        import jax.numpy as jnp

        for g in groups:
            live = [i for i, a in enumerate(g.lane_arms)
                    if self._arm_live(race, a)]
            if not live or g.batch <= 1:
                continue
            new_b = next_pow2(len(live))
            if new_b > g.batch // 2:
                continue
            keep = live + [live[-1]] * (new_b - len(live))
            idx = jnp.asarray(np.asarray(keep, dtype=np.int32))
            state = jax.tree_util.tree_map(lambda x: x[idx], g.state)
            lane_arms = [g.lane_arms[i] for i in live] \
                + [-1] * (new_b - len(live))
            old_b = g.batch
            self._open_group(g, lane_arms)
            g.state = state
            pad = np.asarray([a < 0 for a in lane_arms], dtype=bool)
            if pad.any():
                g.state = self._mask_finished(g.state, pad)
            g.rebatches += 1
            self.events.append({
                "event": "rebatch", "algo": g.algo,
                "from_batch": old_b, "to_batch": new_b,
                "arms": [self.arms[a].label for a in lane_arms
                         if a >= 0]})

    # ----------------------------------------------------- checkpoint

    def fingerprint_extra(self) -> Dict[str, Any]:
        """Manifest fields beyond the standard program fingerprint:
        the arm-grid hash and the kill-rule knobs — a resume under a
        different grid or referee must refuse, not silently diverge.
        """
        return {"portfolio_arms": spec_fingerprint(self.arms),
                "portfolio_every": self.every,
                "portfolio_margin": self.margin,
                "portfolio_patience": self.patience,
                "portfolio_plateau": self.plateau}

    def _snapshot(self, groups: List[_Group], race, best_sel,
                  boundary: int) -> Dict[str, Any]:
        from ..robustness.checkpoint import tree_to_host

        return {
            "kind": "portfolio",
            "boundary": int(boundary),
            "race": race_to_host(race),
            "best_sel": [None if s is None else
                         np.asarray(s).tolist() for s in best_sel],
            "groups": [{
                "algo": g.algo,
                "lane_arms": list(g.lane_arms),
                "rebatches": int(g.rebatches),
                "state": tree_to_host(g.state),
            } for g in groups],
        }

    def _restore(self, groups: List[_Group],
                 payload: Dict[str, Any]):
        """Install a snapshot: rebuild each group's runner at the
        SNAPSHOT's lane count (rebatches that already happened stay
        happened) and put the carries back on device.  The referee
        state restores with exact dtypes, so every later kill decision
        replays identically."""
        from ..robustness.checkpoint import (CheckpointError,
                                             tree_to_device)

        if payload.get("kind") != "portfolio":
            raise CheckpointError(
                "snapshot is not a portfolio survivor set",
                kind="state")
        saved = payload.get("groups", [])
        if len(saved) != len(groups):
            raise CheckpointError(
                f"snapshot has {len(saved)} arm group(s), this race "
                f"builds {len(groups)} — the arm grid drifted",
                kind="state")
        for g, s in zip(groups, saved):
            if s["algo"] != g.algo:
                raise CheckpointError(
                    f"snapshot group order drifted: {s['algo']} vs "
                    f"{g.algo}", kind="state")
            self._open_group(g, [int(a) for a in s["lane_arms"]])
            g.state = tree_to_device(s["state"])
            g.rebatches = int(s.get("rebatches", 0))
        race = race_from_host(payload["race"])
        best_sel = [None if s is None
                    else np.asarray(s, dtype=np.int64)
                    for s in payload["best_sel"]]
        return int(payload["boundary"]), race, best_sel

    # -------------------------------------------------------- results

    def _result(self, win: int, best_sel, race, summary,
                status: str, elapsed: float,
                groups: List[_Group]) -> Dict[str, Any]:
        arm = self.arms[win]
        template = self._template_for(
            arm.algo, dict(arm.params))
        sel = best_sel[win]
        assignment = {}
        if sel is not None:
            n_true = getattr(template, "n_vars_true", None) \
                or template.n_vars
            names = list(template.var_names)[:n_true]
            assignment = {
                name: self.dcop.variable(name).domain.values[int(v)]
                for name, v in zip(names, sel[:n_true])}
        cost = race["best_cost"][win]
        block = {
            "spec": canonical_spec(self.arms),
            "every": self.every,
            "margin": self.margin,
            "patience": self.patience,
            "plateau": self.plateau,
            "groups": len(groups),
            "rebatches": sum(g.rebatches for g in groups),
            **{k: v for k, v in summary.items()
               if k != "winner_index"},
        }
        return {
            "status": status,
            "assignment": assignment,
            "cost": float(cost) if np.isfinite(cost) else None,
            "violation": (int(race["best_viol"][win])
                          if np.isfinite(cost) else None),
            "cycle": int(race["cycles"][win]),
            "algo": arm.algo,
            "time": elapsed,
            "portfolio": block,
        }

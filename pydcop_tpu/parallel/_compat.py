"""jax version compatibility for the mesh layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` into the
top-level ``jax`` namespace (and its replication-check kwarg was
renamed ``check_rep`` -> ``check_vma``) across jax releases; the mesh
solvers run on both spellings through this resolver so a jax downgrade
never takes the whole multi-chip layer down with an AttributeError.
"""

import jax

_new_style = hasattr(jax, "shard_map")
if _new_style:
    _shard_map = jax.shard_map
else:  # older jax: the experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, **kwargs):
    if not _new_style and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)

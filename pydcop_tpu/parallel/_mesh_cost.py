"""On-device assignment cost for the sharded solvers' anytime trace.

One shard_map'ed evaluator shared by every mesh family: each tp shard
sums its own constraint cubes at the current assignment (the same
round-robin partition the solver steps over), one ``psum`` assembles
the total, and the replicated unary costs are added once — so the mesh
engine's per-cycle cost trace needs zero host round-trips and no
replicated copy of the cube stacks.

Dummy padding rows are handled per family: the local-search partitions
pad with all-zero cubes (contribute nothing), the MaxSum factor
partition pads with BIG-filled cubes and needs the explicit validity
mask.
"""

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from ..ops.kernels import bucket_cost


def build_mesh_cost(mesh, n_vars: int,
                    buckets: List[Tuple[np.ndarray, np.ndarray,
                                        Optional[np.ndarray]]],
                    var_costs: np.ndarray, x_has_sink: bool,
                    with_violations: bool = False):
    """Compile ``cost(x) -> (B,)`` over the (dp, tp) mesh.

    ``buckets``: per arity bucket ``(cubes (TP, F, D, ..., D),
    var_ids (TP, F, a), valid (TP, F) or None)`` — ``valid`` masks
    padded rows whose cube values are not inert (MaxSum's BIG fill);
    ``None`` means padding contributes zero by construction.
    ``var_costs``: the ORIGINAL (V, D) unary costs (no sink row).
    ``x_has_sink``: whether the assignment carries the sink column
    already (local-search state) or needs it appended (selections).

    ``with_violations`` compiles the telemetry variant ``fn(x) ->
    conflicts (B,)`` INSTEAD: the count of constraints whose cost at
    ``x`` exceeds their own optimum (``> min + 1e-6`` — the same test
    the sharded DSA-B plateau rule runs), with per-constraint optima
    hoisted to build time and the cost sum elided entirely (the
    evaluator runs every telemetry cycle; the int32 psum is its only
    collective).  Padded rows are inert either way: a masked row is
    excluded explicitly, an all-zero dummy row sits exactly at its
    optimum.
    """
    nb = len(buckets)
    V = n_vars
    tp_sh = NamedSharding(mesh, P("tp"))
    cubes_d = [jax.device_put(c, tp_sh) for c, _v, _m in buckets]
    vids_d = [jax.device_put(np.asarray(v, dtype=np.int32), tp_sh)
              for _c, v, _m in buckets]
    valid_d = [None if m is None else jax.device_put(
        np.asarray(m, dtype=bool), tp_sh) for _c, _v, m in buckets]
    has_mask = [m is not None for _c, _v, m in buckets]
    mask_args = [m for m in valid_d if m is not None]
    vc_d = jax.device_put(
        jnp.asarray(np.asarray(var_costs[:V], dtype=np.float32)),
        NamedSharding(mesh, P()))
    # per-constraint optima hoisted to build time: the conflict test
    # runs every telemetry cycle — a min over every cube cell inside
    # the loop body would dominate the evaluator
    optima_d = [jax.device_put(
        np.asarray(c, dtype=np.float32)
        .reshape(c.shape[0], c.shape[1], -1).min(axis=-1), tp_sh)
        for c, _v, _m in buckets] if with_violations else []

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P("dp"), [P("tp")] * nb, [P("tp")] * nb,
                  [P("tp")] * sum(has_mask),
                  [P("tp")] * len(optima_d), P()),
        out_specs=P("dp"),
    )
    def cost_fn(x, cubes, var_ids, masks, optima, vc):
        cubes_l = [c[0] for c in cubes]
        vids_l = [v[0] for v in var_ids]
        masks_l = iter([m[0] for m in masks])
        mask_of = [next(masks_l) if hm else None for hm in has_mask]
        opt_l = [o[0] for o in optima]

        def one(x1):
            x1 = x1.astype(jnp.int32)
            x_ext = x1 if x_has_sink else jnp.concatenate(
                [x1, jnp.zeros((1,), dtype=jnp.int32)])
            tot = jnp.float32(0)
            conflicts = jnp.int32(0)
            for bi, (cu, vi, m) in enumerate(
                    zip(cubes_l, vids_l, mask_of)):
                if cu.shape[0] == 0:
                    continue
                # upcast at the reduction boundary: cubes may be
                # bf16-stored (ops/precision.py), the trace sums in f32
                c_raw = bucket_cost(cu, vi, x_ext).astype(jnp.float32)
                if with_violations:
                    conf = c_raw > opt_l[bi] + 1e-6
                    if m is not None:
                        conf = jnp.logical_and(conf, m)
                    conflicts = conflicts + jnp.sum(
                        conf.astype(jnp.int32))
                else:
                    c = c_raw if m is None else \
                        jnp.where(m, c_raw, 0.0)
                    tot = tot + jnp.sum(c)
            if with_violations:
                return jax.lax.psum(conflicts, "tp")
            tot = jax.lax.psum(tot, "tp")
            return tot + jnp.sum(vc[jnp.arange(V), x_ext[:V]])

        return jax.vmap(one)(x)

    def cost(x):
        return cost_fn(x, cubes_d, vids_d, mask_args, optima_d, vc_d)

    return cost

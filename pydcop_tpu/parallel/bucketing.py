"""Shape-bucketed padding planner for heterogeneous batch fusion.

The fused campaign path (commands/batch.py -> parallel/batch.py) turns
N same-topology jobs into ONE vmapped program; a *mixed* campaign used
to degrade to one subprocess per job — full CLI startup + XLA retrace
each (the round-6 measured tooling cost).  This planner is the
sequence-length-bucketing pattern from inference serving applied to
DCOP instances: group jobs into a small geometric ladder of shared
padded shapes (next power-of-two rungs on variable count and per-arity
bucket slot counts), pad every instance of a rung to the rung's shape
with phantom variables/factors (``graphs.arrays.*.pad_to``), and a
whole mixed campaign becomes ≤ #rungs compiled programs.

Padding waste is capped and reported: the pure power-of-two ladder
bounds each instance's padded/true cell ratio at 2x by construction,
and the rung-consolidation pass (which merges a small rung into a
covering bigger one to cut program count further) only fires while
every merged member stays under ``max_waste``.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (0 stays 0: an absent bucket)."""
    if n <= 0:
        return 0
    return 1 << (int(n) - 1).bit_length()


def parse_reserve(spec) -> Tuple[int, Dict[int, int]]:
    """Parse the ``--reserve-slots`` grammar into ``(extra phantom
    variable rows, {arity: extra factor slots})``.

    Grammar: comma-separated ``vars:N`` / ``ARITY:N`` entries, e.g.
    ``"vars:8,2:16,3:4"`` = 8 spare variable rows, 16 spare binary
    slots, 4 spare ternary slots.  Dict input (``{"vars": 8, 2: 16}``)
    passes through with the same validation; ``None``/empty means no
    reservation.  The ladder sizes phantom capacity purely from the
    power-of-two rung otherwise — this is the explicit headroom knob
    dynamic workloads use to provision edit capacity
    (``dynamics/``)."""
    if spec is None:
        return 0, {}
    if isinstance(spec, tuple) and len(spec) == 2 \
            and isinstance(spec[1], dict):
        # already-parsed form: idempotent, so hot loops can parse
        # once and pass the result through
        return int(spec[0]), {int(a): int(n)
                              for a, n in spec[1].items()}
    if isinstance(spec, str):
        items = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition(":")
            if not sep:
                raise ValueError(
                    f"--reserve-slots wants 'vars:N' / 'ARITY:N' "
                    f"entries, got {part!r}")
            items[key.strip()] = val.strip()
        spec = items
    if not isinstance(spec, dict):
        raise ValueError(
            f"reserve spec must be a 'vars:N,ARITY:N' string or a "
            f"dict, got {type(spec).__name__}")
    extra_vars = 0
    slots: Dict[int, int] = {}
    for key, val in spec.items():
        try:
            n = int(val)
        except (TypeError, ValueError):
            raise ValueError(
                f"reserve count for {key!r} must be an int, "
                f"got {val!r}")
        if n < 0:
            raise ValueError(
                f"reserve count for {key!r} must be >= 0, got {n}")
        if str(key).strip().lower() == "vars":
            extra_vars = n
            continue
        try:
            arity = int(key)
        except (TypeError, ValueError):
            raise ValueError(
                f"reserve key must be 'vars' or an arity int, "
                f"got {key!r}")
        if arity < 1:
            raise ValueError(
                f"reserve arity must be >= 1, got {arity}")
        slots[arity] = slots.get(arity, 0) + n
    return extra_vars, slots


@dataclass(frozen=True)
class ShapeProfile:
    """The padding-relevant shape of one compiled instance."""

    kind: str                                  # "factor" | "hyper"
    max_domain: int
    n_vars: int
    bucket_counts: Tuple[Tuple[int, int], ...]  # sorted (arity, count)
    n_pairs: int = 0                           # hyper: neighbor pairs

    @classmethod
    def of(cls, arrays) -> "ShapeProfile":
        counts = tuple(sorted(
            (b.cubes.ndim - 1, int(b.cubes.shape[0]))
            for b in arrays.buckets))
        if hasattr(arrays, "nbr_src"):       # HypergraphArrays
            return cls("hyper", int(arrays.max_domain),
                       int(arrays.n_vars), counts,
                       int(len(arrays.nbr_src)))
        return cls("factor", int(arrays.max_domain),
                   int(arrays.n_vars), counts)

    @property
    def cells(self) -> int:
        """Table cells the instance really occupies (variable plane +
        cost cubes) — the denominator of the waste ratio."""
        D = self.max_domain
        return self.n_vars * D + sum(
            c * D ** a for a, c in self.bucket_counts)


@dataclass
class Rung:
    """One shared padded shape and the jobs assigned to it."""

    kind: str
    max_domain: int
    n_vars: int                      # padded V (includes the sink row)
    bucket_slots: Dict[int, int]     # arity -> padded factor count
    n_pairs: int                     # hyper: padded neighbor pairs
    members: List[int] = field(default_factory=list)

    @property
    def signature(self) -> Tuple:
        """Hashable rung identity — the in-process trace-cache key:
        every instance padded to the same signature reuses one
        compiled program."""
        return (self.kind, self.max_domain, self.n_vars,
                tuple(sorted(self.bucket_slots.items())), self.n_pairs)

    @property
    def cells(self) -> int:
        D = self.max_domain
        return self.n_vars * D + sum(
            c * D ** a for a, c in self.bucket_slots.items())

    def waste_for(self, profile: ShapeProfile) -> float:
        return self.cells / max(profile.cells, 1)

    def covers(self, profile: ShapeProfile) -> bool:
        return (self.kind == profile.kind
                and self.max_domain == profile.max_domain
                # the sink row: phantom factors need an anchor
                and self.n_vars > profile.n_vars
                and self.n_pairs >= profile.n_pairs
                and all(self.bucket_slots.get(a, 0) >= c
                        for a, c in profile.bucket_counts))

    def pad(self, arrays):
        """Pad one member's arrays to this rung's shape."""
        if self.kind == "hyper":
            return arrays.pad_to(self.n_vars, dict(self.bucket_slots),
                                 n_pairs=self.n_pairs)
        return arrays.pad_to(self.n_vars, dict(self.bucket_slots))


def rung_label(signature: Tuple) -> str:
    """A rung signature compacted into one metric-label-safe token,
    e.g. ``factor:d3:v17:a2x32`` — the ``rung`` label of the serve
    registry's dispatch counters, stage histograms and memory gauges
    (the raw tuple would make every Prometheus label an eyesore and
    every grouping query a substring hunt).  ``runner_for_rung``
    accepts ANY hashable as a rung signature (library callers key
    however they like), so a tuple that is not :attr:`Rung.signature`
    shaped falls back to a generic flattening instead of failing a
    telemetry read."""
    try:
        kind, max_domain, n_vars, slots, n_pairs = signature
        parts = [str(kind), f"d{max_domain}", f"v{n_vars}"]
        parts.extend(f"a{a}x{c}" for a, c in slots)
        if n_pairs:
            parts.append(f"p{n_pairs}")
        return ":".join(parts)
    except (TypeError, ValueError):
        flat = "_".join(
            str(x) for x in (signature if isinstance(
                signature, (tuple, list)) else (signature,)))
        return flat.replace(" ", "")[:64] or "unkeyed"


def _base_rung(profile: ShapeProfile, reserve=None) -> Rung:
    """The profile's home rung: next power of two per dimension, plus
    one sink variable row anchoring phantom factors.  ``reserve``
    (anything :func:`parse_reserve` accepts) adds explicit headroom on
    top: extra variable rows and per-arity slots — part of the rung
    SIGNATURE, so two jobs batch only when they were provisioned
    alike."""
    extra_vars, extra_slots = parse_reserve(reserve)
    slots = {a: next_pow2(c)
             for a, c in profile.bucket_counts if c}
    for a, n in extra_slots.items():
        slots[a] = slots.get(a, 0) + n
    return Rung(
        kind=profile.kind, max_domain=profile.max_domain,
        n_vars=next_pow2(profile.n_vars) + 1 + extra_vars,
        bucket_slots=slots,
        n_pairs=next_pow2(profile.n_pairs),
    )


def home_rung(profile: ShapeProfile, reserve=None) -> Rung:
    """The profile's power-of-two home rung, public: the serving
    admission path (``serving/queue.py``) assigns each ARRIVING job its
    rung directly — no campaign-wide consolidation pass exists when
    jobs trickle in one at a time, so two jobs batch exactly when their
    home-rung signatures (and solver options) match.  ``reserve``
    provisions explicit edit headroom (see :func:`parse_reserve`)."""
    return _base_rung(profile, reserve=reserve)


def plan_rungs(profiles: List[ShapeProfile],
               max_waste: float = 2.0,
               max_rung_bytes: Optional[int] = None,
               bytes_per_cell: int = 4,
               reserve=None) -> List["Rung"]:
    """Group instance profiles into a padding ladder.

    Pass 1 assigns each profile its power-of-two home rung (identical
    home rungs share one entry).  Pass 2 consolidates: smaller rungs
    merge into the cheapest covering bigger rung while every merged
    member's padded/true cell ratio stays <= ``max_waste`` — fewer
    rungs means fewer compiled programs, the quantity the
    ``bench_hetero_batch`` contract asserts.  Members lists index into
    ``profiles``.

    ``max_rung_bytes`` (optional) caps the padded PER-INSTANCE memory
    a consolidation target may reach, priced at ``bytes_per_cell`` —
    the precision policy's store itemsize (``Policy.store_itemsize``).
    This is where mixed precision buys program count: a campaign run
    at bf16 advertises 2-byte cells, so the same byte budget admits
    rungs twice as large and more small topologies merge into them.
    ``None`` keeps the historical cells-only behavior.

    ``reserve`` (see :func:`parse_reserve`) adds explicit per-arity
    slot and variable-row headroom to EVERY rung — the ``batch
    --reserve-slots`` knob, provisioning edit capacity a dynamic
    campaign activates in place.  The reservation rides the rung
    signatures, so it costs compiled-program identity only when it
    changes shapes (which is its entire point)."""
    reserve = parse_reserve(reserve)   # once, not per profile
    by_sig: Dict[Tuple, Rung] = {}
    for i, p in enumerate(profiles):
        rung = _base_rung(p, reserve=reserve)
        rung = by_sig.setdefault(rung.signature, rung)
        rung.members.append(i)

    def fits_budget(rung: "Rung") -> bool:
        if max_rung_bytes is None:
            return True
        return rung.cells * bytes_per_cell <= max_rung_bytes

    rungs = sorted(by_sig.values(), key=lambda r: r.cells,
                   reverse=True)
    kept: List[Rung] = []
    for rung in rungs:
        target = None
        for big in kept:
            if fits_budget(big) and all(
                    big.covers(profiles[i]) and
                    big.waste_for(profiles[i]) <= max_waste
                    for i in rung.members):
                if target is None or big.cells < target.cells:
                    target = big
        if target is not None:
            target.members.extend(rung.members)
        else:
            kept.append(rung)
    for rung in kept:
        rung.members.sort()
        if not fits_budget(rung):
            # the budget can veto merges, but a single instance's own
            # power-of-two home rung may already exceed it — that rung
            # cannot be shrunk, so say so instead of silently planning
            # an over-budget program (repo policy: no silent caps)
            import warnings

            warnings.warn(
                f"fuse-hetero rung {rung.signature} needs "
                f"{rung.cells * bytes_per_cell} bytes per instance, "
                f"over the {max_rung_bytes}-byte budget; the budget "
                "only bounds consolidation merges — this instance "
                "shape alone exceeds it", RuntimeWarning)
    return kept


def plan_stats(rungs: List[Rung],
               profiles: List[ShapeProfile],
               bytes_per_cell: int = 4) -> Dict[str, object]:
    """Aggregate ladder stats for campaign results and the bench
    contract: compiled-program count, total-cell padding waste, and
    the padded memory priced at the precision policy's store itemsize
    (``bytes_per_cell``: 4 for f32, 2 for bf16)."""
    true_cells = padded_cells = 0
    for rung in rungs:
        for i in rung.members:
            true_cells += profiles[i].cells
            padded_cells += rung.cells
    return {
        "programs": len(rungs),
        "jobs": sum(len(r.members) for r in rungs),
        "true_cells": true_cells,
        "padded_cells": padded_cells,
        "padded_bytes": padded_cells * bytes_per_cell,
        "padding_waste": round(padded_cells / max(true_cells, 1), 3),
    }

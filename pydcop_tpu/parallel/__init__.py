from .batch import BatchedDsa, BatchedMaxSum, BatchedMgm
from .sharded_maxsum import ShardedAMaxSum, ShardedMaxSum


def make_mesh(n_devices: int = None, tp: int = None):
    """Build a (dp, tp) mesh over the available devices.

    Default: tp = 2 when at least 4 devices are available (factor-parallel
    pairs), the rest data-parallel.
    """
    import jax

    if n_devices is None:
        n_devices = len(jax.devices())
    if tp is None:
        tp = 2 if n_devices >= 4 and n_devices % 2 == 0 else 1
    dp = n_devices // tp
    return jax.make_mesh((dp, tp), ("dp", "tp"))


def _build_sharded_solver(dcop, algo: str, mesh, batch: int, params):
    """Construct the sharded solver + its compiled arrays for one
    algorithm name (shared by :func:`solve_sharded` and
    :func:`solve_sharded_result`)."""
    from ..dcop.dcop import filter_dcop
    from ..graphs.arrays import FactorGraphArrays, HypergraphArrays

    if algo in ("maxsum", "amaxsum"):
        from .sharded_maxsum import (ShardedAMaxSum, ShardedFusedMaxSum,
                                     ShardedMaxSum)

        layout = params.pop("layout", None)
        # arity-sorted build gives mixed-arity models the canonical
        # factor-major edge layout the fast mesh layouts need;
        # edge_major keeps the model's own order (the generic oracle)
        arrays = FactorGraphArrays.build(
            dcop, arity_sorted=layout != "edge_major")
        if algo == "amaxsum":
            if layout == "fused":
                # loud rejection, never a silent downgrade (the repo
                # policy ShardedMaxSum itself enforces for layouts)
                raise ValueError(
                    "amaxsum has no fused mesh layout: -p layout:fused "
                    "is only supported for maxsum "
                    "(ShardedFusedMaxSum); use layout edge_major/"
                    "lane_major or drop the param")
            cls = ShardedAMaxSum
        elif layout == "fused":
            # the fused var-sorted layout has its own mesh class (one
            # local gather + one psum per cycle)
            cls = ShardedFusedMaxSum
        else:
            cls = ShardedMaxSum
        if layout is not None and layout != "fused":
            # pass every other value through so ShardedMaxSum keeps
            # honoring explicit layouts and loudly rejecting bad ones
            params["layout"] = layout
        solver = cls(arrays, mesh, batch=batch, **params)
    elif algo == "dsa":
        arrays = HypergraphArrays.build(filter_dcop(dcop))
        from .sharded_localsearch import ShardedDsa

        solver = ShardedDsa(arrays, mesh, batch=batch, **params)
    elif algo == "mgm":
        arrays = HypergraphArrays.build(filter_dcop(dcop))
        from .sharded_localsearch import ShardedMgm

        solver = ShardedMgm(arrays, mesh, batch=batch, **params)
    elif algo == "mgm2":
        arrays = HypergraphArrays.build(filter_dcop(dcop))
        from .sharded_mgm2 import ShardedMgm2

        solver = ShardedMgm2(arrays, mesh, batch=batch, **params)
    elif algo in ("mixeddsa", "dba", "gdba", "adsa", "dsatuto"):
        from .sharded_breakout import (ShardedAdsa, ShardedDba,
                                       ShardedDsatuto, ShardedGdba,
                                       ShardedMixedDsa)

        cls = {"mixeddsa": ShardedMixedDsa, "dba": ShardedDba,
               "gdba": ShardedGdba, "adsa": ShardedAdsa,
               "dsatuto": ShardedDsatuto}[algo]
        arrays = HypergraphArrays.build(filter_dcop(dcop))
        solver = cls(arrays, mesh, batch=batch, **params)
    else:
        raise ValueError(
            f"solve_sharded supports every iterative algorithm "
            f"(maxsum/amaxsum/dsa/adsa/dsatuto/mgm/mgm2/mixeddsa/"
            f"dba/gdba), not {algo!r}")
    return solver, arrays


def solve_sharded_result(dcop, algo: str, n_cycles: int = 100,
                         mesh=None, batch: int = None, seed: int = 0,
                         collect_cost_every: int = None,
                         telemetry: bool = False,
                         chunk_size: int = None, timeout: float = None,
                         checkpointer=None, resume: bool = False,
                         **params):
    """Like :func:`solve_sharded` but returns the full
    :class:`~pydcop_tpu.engine.solver.RunResult` — including the
    anytime ``cost_trace`` recorded ON DEVICE by the mesh engine
    (``collect_cost_every`` cycles between kept samples; traces cost
    nothing in host round-trips), and the engine's dispatch/host-sync
    counters in ``metrics``.

    ``telemetry`` additionally records the per-cycle metric planes
    (``RunResult.cycle_metrics``: residual / flips / conflicted
    constraints, drained at chunk boundaries only), splits
    trace/lower/compile/execute spans (``metrics["spans"]``) and fills
    ``RunResult.compile_stats`` with the HLO census of the compiled
    chunk.  Telemetry-off runs execute the identical compiled step —
    the guard suite asserts selections AND convergence cycles are
    unchanged.  Message-plane stats (``metrics["msg_per_cycle"]`` /
    ``metrics["bytes_per_cycle"]``) are always reported.
    """
    import time as _time

    import numpy as np

    from ..engine.solver import RunResult

    t0 = _time.perf_counter()
    if mesh is None:
        mesh = make_mesh()
    if batch is None:
        batch = mesh.shape["dp"]
    solver, arrays = _build_sharded_solver(dcop, algo, mesh, batch,
                                           params)
    if checkpointer is not None:
        # the mesh shape is part of the snapshot's identity: the
        # sharded carry's array shapes bake (dp, tp) in, so resume
        # onto a different mesh must refuse, not crash mid-device_put
        if not checkpointer.fingerprint.get("mesh"):
            checkpointer.fingerprint["mesh"] = dict(mesh.shape)
        solver.checkpointer = checkpointer
        solver.checkpoint_resume = bool(resume)
    sel, cycles = solver.run(
        n_cycles, seed=seed, collect_cost_every=collect_cost_every,
        collect_metrics=telemetry, spans=telemetry,
        chunk_size=chunk_size, timeout=timeout)

    variables = [dcop.variable(n) for n in arrays.var_names]
    best_key, best = None, None
    for row in np.asarray(sel):
        assignment = {
            v.name: v.domain.values[int(i)]
            for v, i in zip(variables, row)
        }
        cost, violations = dcop.solution_cost(assignment)
        # rank restarts lexicographically by (violations, cost): the
        # soft cost excludes violated constraints, so cost alone cannot
        # rank a feasible restart above an infeasible one
        key = (violations,
               cost if dcop.objective == "min" else -cost)
        if best_key is None or key < best_key:
            best_key, best = key, (assignment, cost, violations)
    stats = dict(getattr(solver, "last_run_stats", {}))
    stats.update(solver.message_plane_stats())
    if checkpointer is not None:
        stats["checkpoint"] = checkpointer.telemetry()
    if telemetry and getattr(solver, "last_spans", None):
        stats["spans"] = dict(solver.last_spans)
    finished = bool(solver.finished)
    return RunResult(
        assignment=best[0],
        cycles=cycles,
        finished=finished,
        cost=best[1],
        violations=best[2],
        duration=_time.perf_counter() - t0,
        status="FINISHED" if finished
        else stats.get("status", "MAX_CYCLES"),
        cost_trace=list(getattr(solver, "last_cost_trace", [])),
        metrics=stats,
        cycle_metrics=list(getattr(solver, "last_cycle_metrics", []))
        if telemetry else [],
        compile_stats=dict(getattr(solver, "last_compile_stats", {}))
        if telemetry else {},
    )


def solve_sharded(dcop, algo: str, n_cycles: int = 100,
                  mesh=None, batch: int = None, seed: int = 0,
                  **params):
    """Solve a DCOP on a (dp, tp) device mesh — the multi-chip
    counterpart of ``infrastructure.run.solve``.

    ``algo``: maxsum / amaxsum (edge- or lane-major), dsa, mgm or
    mgm2.  ``batch`` independent restarts ride the dp axis (default:
    one per dp row); the best-cost restart is returned.  Returns
    (assignment dict, cost, cycles, finished) — ``finished`` is True
    iff the algorithm's own termination rule fired (possibly exactly
    on the final cycle), so callers never infer status from
    ``cycles < n_cycles``.  For the anytime cost trace and engine
    metrics, use :func:`solve_sharded_result`.
    """
    res = solve_sharded_result(dcop, algo, n_cycles=n_cycles,
                               mesh=mesh, batch=batch, seed=seed,
                               **params)
    return res.assignment, res.cost, res.cycles, res.finished


from .sharded_breakout import (ShardedDba, ShardedGdba,  # noqa: E402
                               ShardedMixedDsa)
from .sharded_mgm2 import ShardedMgm2  # noqa: E402
from .portfolio import (Arm, PortfolioRace,  # noqa: E402
                        PortfolioSpecError, parse_portfolio_spec)

__all__ = ["Arm", "BatchedDsa", "BatchedMaxSum", "BatchedMgm",
           "PortfolioRace", "PortfolioSpecError", "ShardedAMaxSum",
           "ShardedDba", "ShardedGdba", "ShardedMaxSum",
           "ShardedMgm2", "ShardedMixedDsa", "make_mesh",
           "parse_portfolio_spec", "solve_sharded",
           "solve_sharded_result"]

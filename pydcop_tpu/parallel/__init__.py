from .batch import BatchedMaxSum
from .sharded_maxsum import ShardedMaxSum


def make_mesh(n_devices: int = None, tp: int = None):
    """Build a (dp, tp) mesh over the available devices.

    Default: tp = 2 when at least 4 devices are available (factor-parallel
    pairs), the rest data-parallel.
    """
    import jax

    if n_devices is None:
        n_devices = len(jax.devices())
    if tp is None:
        tp = 2 if n_devices >= 4 and n_devices % 2 == 0 else 1
    dp = n_devices // tp
    return jax.make_mesh((dp, tp), ("dp", "tp"))


__all__ = ["BatchedMaxSum", "ShardedMaxSum", "make_mesh"]

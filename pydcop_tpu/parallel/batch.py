"""Batched solving: many independent instances in one compiled program.

reference parity: ``pydcop batch`` runs jobs *sequentially* (the reference
acknowledges "run in parallel" as a TODO, commands/batch.py:68).  Here a
batch of instances is one vmapped solver whose batch axis can
additionally be sharded over the mesh's dp axis.  Two fusion regimes:

* **same topology** (BASELINE config 5: 1024 random coloring / Ising
  draws of one graph): only the cost cubes ride the batch axis, all
  index tables come from the shared template;
* **heterogeneous, shape-bucketed** (``instances=[...]``): instances
  padded to one rung shape by ``graphs.arrays.*.pad_to`` batch their
  whole topology — cubes AND the edge/var index tables, variable
  planes and neighbor-pair lists — so a mixed campaign runs in
  ≤ #rungs compiled programs (``parallel/bucketing.py`` plans the
  rungs).  Selections stay bit-exact with each instance's unpadded
  solve (phantom rows are inert by construction; dsa/mgm draw
  pad-stable per-variable randomness, see ``ops.kernels.prefix_uniform``),
  and :meth:`decode` masks phantom variables out of the result.
"""

import os
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graphs.arrays import FactorGraphArrays, HypergraphArrays
from ..algorithms.maxsum import MaxSumSolver
from ..ops.kernels import assignment_cost_violations


def _batch_keys(seed, seeds, b):
    if seeds is None:
        return jax.random.split(jax.random.PRNGKey(seed), b)
    if len(seeds) != b:
        raise ValueError(f"need {b} seeds, got {len(seeds)}")
    return jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])


def _stacked(instances, pick) -> jnp.ndarray:
    return jnp.asarray(np.stack([np.asarray(pick(a))
                                 for a in instances]))


def _check_same_shape(instances):
    shapes = {
        (a.n_vars, a.max_domain,
         tuple((b.cubes.ndim - 1, b.cubes.shape[0])
               for b in a.buckets),
         len(a.nbr_src) if hasattr(a, "nbr_src") else 0)
        for a in instances}
    if len(shapes) != 1:
        raise ValueError(
            "heterogeneous instances must be padded to ONE shared "
            f"shape first (graphs.arrays pad_to); got {len(shapes)} "
            "distinct shapes")


class _BatchedRunnerBase:
    """Shared runner body for every batched family: the per-max_cycles
    compiled-program cache, the ``lax.while_loop`` drive, seed/key
    handling and the masked decode.  Subclasses set ``self._one``
    (instance args + key -> (selection, cycle, finished)),
    ``self._instance_args``, ``self.B`` and ``self.n_vars_true``."""

    def __init__(self):
        self.max_cycles = 200
        self._jitted: Dict[Tuple[int, bool], object] = {}
        self._eval_jit = None
        self.n_vars_true: Optional[List[int]] = None
        #: trace-time flag: the metrics variant of the compiled
        #: program carries per-cycle metric planes (set by run();
        #: part of the trace-cache key, so both variants coexist)
        self._collect_metrics = False
        #: per-instance telemetry of the last run(collect_metrics=
        #: True): one record list per instance (observability/metrics)
        self.last_cycle_metrics: List[List[Dict]] = []
        #: optional disk executable cache (engine/_cache.ExecutableCache)
        #: + the logical identity prefix its keys carry: when both are
        #: set (runner_for_rung attaches them for serving callers),
        #: run() AOT-compiles via jax.stages instead of jit dispatch —
        #: a restarted process's cold start for a known rung becomes a
        #: deserialize, not a retrace+compile.  ``last_spans`` reports
        #: where the last run() spent its wall time
        #: (trace_lower_s/compile_s on a cache miss, deserialize_s on a
        #: hit, execute_s always).
        self.exec_cache = None
        self.exec_cache_key: Optional[Tuple] = None
        #: optional fault-injection gate (serving/faults.py): the
        #: serve dispatcher points this at its FaultPlan for the
        #: duration of one dispatch and clears it after.  Called with
        #: the site name ("compile" at program build, "execute" at
        #: dispatch) and raises FaultInjected when the plan fires;
        #: None (always, outside chaos runs) is dead code
        self.fault_hook = None
        #: per-knob resolution of the params this runner was built
        #: with (explicit/tuned/default), set by ``runner_for_rung``
        #: when a tuned-config store was consulted; None when tuning
        #: was not in play (direct construction, no store)
        self.tuning_sources: Optional[Dict[str, str]] = None
        self.last_spans: Dict[str, float] = {}
        #: trace ids of the jobs the last run() executed for, in batch
        #: order (serve dispatches thread them through so a shared
        #: runner's spans stay attributable to the jobs that rode it)
        self.last_trace_ids: List[str] = []

    def _drive(self, base, state):
        """The shared convergence loop: step until the solver reports
        finished or the cycle budget runs out.  ``max_cycles`` is baked
        into the trace via the closure, hence the per-value cache.

        With ``_collect_metrics`` the carry becomes ``(state,
        planes)``: the body additionally writes the residual / flips /
        conflicts planes each cycle (solver arithmetic untouched, so
        telemetry-on selections stay bit-exact) and the planes are
        returned alongside the final state."""
        def cond(s):
            return jnp.logical_and(
                jnp.logical_not(s["finished"]),
                s["cycle"] < self.max_cycles)

        if not self._collect_metrics:
            return jax.lax.while_loop(cond, base.step, state)

        from ..observability.metrics import (alloc_metric_planes,
                                             conflict_count,
                                             feature_metrics,
                                             normalize_buckets,
                                             residual_from_q,
                                             write_metric_planes)

        # the (possibly vmapped-argument-swapped) instance buckets at
        # trace time: per-instance conflict counts ride the same
        # arrays the step reads.  Optima are hoisted OUTSIDE the loop
        # body (local-search solvers carry them; MaxSum derives them
        # here once) — an in-body min over every cube cell is most of
        # the conflict evaluator's cost (PERF_NOTES round 10)
        buckets = normalize_buckets(base.buckets)
        optima = getattr(base, "bucket_optima", None)
        if optima is None:
            optima = [
                jnp.min(jnp.asarray(c).reshape(c.shape[0], -1),
                        axis=-1) if c.shape[0] else
                jnp.zeros((0,), dtype=jnp.float32)
                for c, _vi in buckets]

        def body(carry):
            s, planes = carry
            s2 = base.step(s)
            with jax.named_scope("engine/telemetry"):
                i = s["cycle"]
                resid = residual_from_q(s, s2)
                x2 = base.assignment_indices(s2)
                flips = jnp.sum(
                    (x2 != base.assignment_indices(s))
                    .astype(jnp.int32))
                viol = conflict_count(buckets, x2, optima=optima) \
                    .astype(jnp.int32)
                freezes, pruned = feature_metrics(s2)
                planes = write_metric_planes(planes, i, resid, flips,
                                             viol, freezes=freezes,
                                             pruned=pruned)
            return s2, planes

        final, planes = jax.lax.while_loop(
            lambda c: cond(c[0]), body,
            (state, alloc_metric_planes(self.max_cycles)))
        return final, planes

    def set_instances(self, instances) -> None:
        """Re-point the runner at a new instance set of the SAME
        padded shape: the instance arrays are program *arguments*, so
        the compiled vmapped programs in the trace cache are reused
        as-is (this is what makes the rung-signature runner cache pay
        for in-process callers that revisit a rung)."""
        if len(instances) != self.B:
            raise ValueError(
                f"runner compiled for batch {self.B}, "
                f"got {len(instances)} instances")
        _check_same_shape([self._template] + list(instances))
        self._instance_args = self._build_args(instances)
        self.n_vars_true = [a.n_vars_true or a.n_vars
                            for a in instances]

    # ----------------------------------------- checkpointed chunks

    def _one_start(self, args, key):
        """One instance's fresh state (the checkpoint path's init
        program)."""
        with self._swapped(args) as base:
            return base.init_state(key)

    def _one_chunk(self, args, state, limit):
        """One instance driven to the TRACED ``limit`` — unlike
        :meth:`_drive`, the budget is a program argument, so the
        whole chunk schedule reuses ONE compiled program regardless
        of where a resume lands."""
        with self._swapped(args) as base:
            def cond(s):
                return jnp.logical_and(
                    jnp.logical_not(s["finished"]),
                    s["cycle"] < limit)

            return jax.lax.while_loop(cond, base.step, state)

    def _one_finish(self, args, state):
        with self._swapped(args) as base:
            return base.assignment_indices(state)

    def _ckpt_programs(self):
        """The three compiled programs of the checkpointed drive —
        built ONLY when a checkpointer is attached, so checkpoint-off
        runs keep their historical byte-identical program set."""
        progs = self._jitted.get("ckpt")
        if progs is None:
            if self.fault_hook is not None:
                self.fault_hook("compile")
            progs = (
                jax.jit(jax.vmap(self._one_start, in_axes=(0, 0))),
                jax.jit(jax.vmap(self._one_chunk,
                                 in_axes=(0, 0, None))),
                jax.jit(jax.vmap(self._one_finish,
                                 in_axes=(0, 0))),
            )
            self._jitted["ckpt"] = progs
        return progs

    def _run_checkpointed(self, seed, max_cycles, seeds,
                          checkpointer, resume, trace_ids):
        """The preemption-safe drive (``robustness/checkpoint.py``):
        the vmapped solve runs as compiled chunks of the
        checkpointer's cadence, snapshotting the whole batched carry
        at each chunk boundary — atomic write, fingerprint manifest —
        and, on ``resume``, restoring it (signature-checked against a
        freshly initialized carry) so a killed campaign rung
        continues mid-job.  Selections AND per-instance convergence
        cycles are bit-exact with the single-program run: the chunked
        step arithmetic is boundary-invariant (the PR 2 guard, here
        asserted by the ckpt test matrix)."""
        from ..observability.spans import SpanClock
        from ..robustness.checkpoint import (tree_to_device,
                                             tree_to_host)

        self.max_cycles = max_cycles
        self._collect_metrics = False
        self.last_cycle_metrics = []
        self.last_trace_ids = [str(t) for t in (trace_ids or [])]
        keys = _batch_keys(seed, seeds, self.B)
        spans = SpanClock()
        init_all, chunk_all, decode_all = self._ckpt_programs()
        args = self._instance_args
        with spans.span("execute_s"):
            if self.fault_hook is not None:
                self.fault_hook("execute")
            state = init_all(args, keys)
            if resume:
                restored = checkpointer.load(
                    template=tree_to_host(state))
                if restored is not None:
                    state = tree_to_device(restored)
            every = checkpointer.every or max_cycles
            while True:
                cycles = np.asarray(state["cycle"])
                fin = np.asarray(state["finished"])
                live = ~fin & (cycles < max_cycles)
                frontier = int(cycles[live].min()) if live.any() \
                    else int(cycles.min())
                if frontier:
                    checkpointer.maybe_save(
                        frontier, lambda: tree_to_host(state),
                        final=not live.any())
                if not live.any():
                    break
                limit = min(
                    ((frontier // every) + 1) * every, max_cycles)
                state = chunk_all(args, state, jnp.int32(limit))
            sel = decode_all(args, state)
            out = (np.asarray(sel), np.asarray(state["cycle"]),
                   np.asarray(state["finished"]))
        self.last_spans = spans.as_dict()
        return out

    def run(self, seed: int = 0, max_cycles: int = 200, seeds=None,
            collect_metrics: bool = False, trace_ids=None,
            checkpointer=None, resume: bool = False):
        """Returns (selections (B, V), cycles (B,), finished (B,)).
        ``seeds`` gives each instance its own engine seed (fused batch
        campaigns: row i carries job i's declared seed); default is the
        split-key stream of ``seed``.  ``collect_metrics`` fills
        ``self.last_cycle_metrics`` with one per-cycle record list per
        instance (telemetry planes ride the vmapped carry; the
        telemetry-off program is untouched and cached separately).
        ``trace_ids`` (serve dispatches) lands in
        ``self.last_trace_ids`` so the per-dispatch spans stay joined
        to the jobs that produced them.  ``checkpointer``
        (robustness/checkpoint.SolveCheckpointer) switches to the
        chunked preemption-safe drive — snapshots at chunk
        boundaries, ``resume`` restores — with bit-exact selections
        and cycles; without one this path compiles nothing new."""
        from ..observability.metrics import metric_records

        from ..observability.spans import SpanClock

        if checkpointer is not None:
            if collect_metrics:
                raise ValueError(
                    "checkpointed campaign runs do not collect the "
                    "per-cycle telemetry planes (the metric-plane "
                    "carry is not part of the batched snapshot); "
                    "run telemetry and checkpointing separately")
            return self._run_checkpointed(seed, max_cycles, seeds,
                                          checkpointer, resume,
                                          trace_ids)
        self.max_cycles = max_cycles
        self._collect_metrics = bool(collect_metrics)
        if trace_ids is not None and len(trace_ids) > self.B:
            # fewer is fine (pow2-padded batches carry inert rows with
            # no job behind them); more means the caller mis-batched
            raise ValueError(
                f"got {len(trace_ids)} trace ids for batch {self.B}")
        self.last_trace_ids = [str(t) for t in (trace_ids or [])]
        keys = _batch_keys(seed, seeds, self.B)
        cache_key = (max_cycles, self._collect_metrics)
        spans = SpanClock()
        run_all = self._jitted.get(cache_key)
        if run_all is None:
            run_all = self._compile_run(cache_key, keys, spans)
            self._jitted[cache_key] = run_all
        with spans.span("execute_s"):
            if self.fault_hook is not None:
                self.fault_hook("execute")
            if collect_metrics:
                sel, cycles, finished, planes = run_all(
                    self._instance_args, keys)
                planes = {k: np.asarray(v) for k, v in planes.items()}
                cycles = np.asarray(cycles)
                self.last_cycle_metrics = [
                    metric_records(
                        {k: v[i] for k, v in planes.items()},
                        int(cycles[i]))
                    for i in range(self.B)]
            else:
                sel, cycles, finished = run_all(
                    self._instance_args, keys)
                self.last_cycle_metrics = []
            out = (np.asarray(sel), np.asarray(cycles),
                   np.asarray(finished))
        self.last_spans = spans.as_dict()
        return out

    def _compile_run(self, cache_key: Tuple, keys,
                     spans) -> object:
        """The compiled whole-batch program for ``cache_key``.  Without
        an attached executable cache this is the historical jit wrapper
        (compiles lazily on first dispatch).  With one, the program is
        AOT-compiled through ``jax.stages`` so the compiled executable
        can be serialized to disk — and a later process's cold start
        for the same logical key (rung signature × algo × precision ×
        batch, plus the argument aval signature and this runner's
        ``cache_key``) deserializes it instead of retracing: the spans
        then show ``deserialize_s`` and NO ``compile_s``, the warm-start
        evidence the serve telemetry asserts on."""
        if self.fault_hook is not None:
            self.fault_hook("compile")
        jitted = jax.jit(jax.vmap(self._one, in_axes=(0, 0)))
        if self.exec_cache is None or self.exec_cache_key is None:
            return jitted
        return self._aot_via_cache(jitted, (self._instance_args, keys),
                                   cache_key, spans)

    def _aot_via_cache(self, jitted, args, extra_key, spans,
                       prefix: str = ""):
        """Load-or-compile-and-store through the attached executable
        cache, shared by the run program and the evaluator (``prefix``
        names their spans apart).  The deserialize span is recorded
        ONLY on a hit: telemetry consumers classify cold vs warm
        dispatches by its presence."""
        from ..observability.spans import aot_compile, aval_signature

        full_key = (self.exec_cache_key, extra_key,
                    aval_signature(args))
        t0 = time.perf_counter()
        compiled = self.exec_cache.load(full_key)
        if compiled is not None:
            spans.add(prefix + "deserialize_s",
                      time.perf_counter() - t0)
            return compiled
        _lowered, compiled = aot_compile(jitted, args, spans,
                                         prefix=prefix)
        self.exec_cache.store(full_key, compiled)
        return compiled

    def decode(self, sel: np.ndarray) -> List[np.ndarray]:
        """Masked decode: each row sliced to its instance's true
        variable count, so phantom variables never leak into
        selections."""
        if self.n_vars_true is None:
            return [sel[i] for i in range(self.B)]
        return [sel[i, :n] for i, n in enumerate(self.n_vars_true)]

    def _eval_one(self, args, x):
        """One instance's (cost, violations) for :meth:`evaluate` —
        buckets and unary costs from the vmapped args on the hetero
        path, from the shared template otherwise.  Works for both
        bucket flavors (FactorBucket / ConstraintBucket): only
        ``var_ids`` and the stacked cubes are read."""
        if self._hetero:
            buckets = list(zip(args["cubes"], args["var_ids"]))
            var_costs = args["var_costs"]
        else:
            buckets = [
                (c, jnp.asarray(b.var_ids))
                for c, b in zip(args["cubes"], self._template.buckets)]
            var_costs = jnp.asarray(self._template.var_costs)
        return assignment_cost_violations(buckets, var_costs, x)

    def evaluate(self, sel: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Device-side cost/violation re-evaluation of the (B, V)
        selections: ONE jitted vmapped call over the same stacked
        instance arrays the solve ran on, replacing the per-job host
        Python re-walk of every constraint (PERF_NOTES round 8 named
        it the fused leg's remaining cost).  Phantom rows contribute
        exactly zero (their only valid slot costs 0), so padded and
        unpadded evaluations agree.  Returns (model-space costs (B,),
        hard-violation counts (B,)) — the compiled ``±HARD`` clip is
        the violation marker, mirroring ``DCOP.solution_cost`` with
        the default infinity threshold
        (``ops.kernels.assignment_cost_violations``)."""
        x = jnp.asarray(np.asarray(sel, dtype=np.int32))
        fn = self._eval_jit
        if fn is None:
            fn = self._eval_jit = self._compile_eval(x)
        cost, viol = fn(self._instance_args, x)
        # device costs are signed (min-compiled); undo for max models
        return (self._sign * np.asarray(cost, dtype=np.float64),
                np.asarray(viol))

    def _compile_eval(self, x):
        """The vmapped cost/violation evaluator — exec-cached like the
        run program when a cache is attached (a warm serve restart
        must pay ZERO compiles, and the evaluator's was measurably the
        larger of the two leftovers), plain jit otherwise.  Its spans
        (``eval_*``) MERGE into ``last_spans`` so the dispatch record
        shows the whole compile story of one dispatch."""
        jitted = jax.jit(jax.vmap(self._eval_one, in_axes=(0, 0)))
        if self.exec_cache is None or self.exec_cache_key is None:
            return jitted
        from ..observability.spans import SpanClock

        spans = SpanClock()
        compiled = self._aot_via_cache(
            jitted, (self._instance_args, x), "evaluate", spans,
            prefix="eval_")
        # merge ROUNDED, like run()'s spans — a dispatch record must
        # not mix 6-digit and raw-float precisions
        for k, v in spans.as_dict().items():
            self.last_spans[k] = self.last_spans.get(k, 0.0) + v
        return compiled


_MISSING = object()


def _swap_dev(base, updates):
    """Swap device-constant cache entries of a lazy-constants solver
    (MaxSumSolver) for one vmapped instance's arrays; returns what to
    restore."""
    saved = {k: base._dev_cache.get(k, _MISSING) for k in updates}
    base._dev_cache.update(updates)
    return saved


def _restore_dev(base, saved):
    for k, v in saved.items():
        if v is _MISSING:
            base._dev_cache.pop(k, None)
        else:
            base._dev_cache[k] = v


class BatchedMaxSum(_BatchedRunnerBase):
    """vmap MaxSum over stacked per-instance arrays: cost cubes only
    (same-topology fusion) or the full padded topology
    (``instances=[...]``, shape-bucketed hetero fusion)."""

    def __init__(self, template: FactorGraphArrays,
                 cubes_batches: Optional[List[np.ndarray]] = None,
                 batch: int = 1,
                 instances: Optional[List[FactorGraphArrays]] = None,
                 **params):
        super().__init__()
        if params.get("bnb"):
            # loud rejection, never a silent downgrade: bnb plans are
            # build-time constants of the cube CONTENTS (sorted cell
            # order + suffix bounds), but a batched runner's cubes are
            # vmapped program ARGUMENTS swapped per instance — the
            # template's plan would silently misprune every other
            # instance.  Decimation composes fine (the freeze plane is
            # per-instance state under the vmap).
            raise ValueError(
                "batched runners do not support bnb: pruned-reduction "
                "plans are build-time constants of one instance's "
                "cubes, but batched cubes are per-instance vmapped "
                "arguments; run bnb through the engine or sharded "
                "paths")
        self.solver = MaxSumSolver(template, **params)
        self._template = template
        self._sign = float(template.sign)
        self._hetero = instances is not None
        if self._hetero:
            if self.solver._canonical is None:
                raise ValueError(
                    "hetero batching needs the canonical factor-major "
                    "edge layout (pad_to emits it; build source arrays "
                    "with arity_sorted=True)")
            batch = len(instances)
            self._instance_args = self._build_args(instances)
            self.n_vars_true = [a.n_vars_true or a.n_vars
                                for a in instances]
        elif cubes_batches is not None:
            batch = cubes_batches[0].shape[0]
            self._instance_args = {
                "cubes": [jnp.asarray(
                    cb, dtype=self.solver.policy.store_dtype)
                    for cb in cubes_batches]}
        else:
            self._instance_args = {"cubes": [
                jnp.broadcast_to(cubes[None], (batch,) + cubes.shape)
                for cubes, _, _ in self.solver.buckets
            ]}
        self.B = batch

        def one_instance(args, key):
            # swap the template solver's device constants for this
            # instance's; the per-instance arrays are vmapped ARGUMENTS,
            # so one compiled program serves any instance set of the
            # same shape
            with self._swapped(args) as base:
                out = self._drive(base, base.init_state(key))
                final, planes = out if self._collect_metrics \
                    else (out, None)
                # decode through assignment_indices, NOT the raw
                # selection field: with stability:0 the step elides the
                # per-cycle argmin and carries the INIT-state selection
                # — the live assignment must be rebuilt from the final
                # messages, the same decode the sync engine uses
                sel = base.assignment_indices(final)
            if planes is not None:
                return sel, final["cycle"], final["finished"], planes
            return sel, final["cycle"], final["finished"]

        self._one = one_instance

    def _swap_updates(self, args):
        """This instance's device-constant overrides (the cube stacks
        plus, on the hetero path, the whole batched topology)."""
        updates = {"buckets": [
            (c, ei, args["var_ids"][bi] if self._hetero else vi)
            for bi, (c, (_, ei, vi))
            in enumerate(zip(args["cubes"], self.solver.buckets))
        ]}
        if self._hetero:
            updates.update(
                var_costs=args["var_costs"],
                domain_mask=args["domain_mask"],
                domain_size=args["domain_size"],
                edge_var=args["edge_var"],
            )
        return updates

    @contextmanager
    def _swapped(self, args):
        """The template solver with one vmapped instance's arrays
        swapped into its device-constant cache — the shared body of
        the single-program run AND the chunked checkpoint programs,
        so the swap logic cannot drift between them."""
        saved = _swap_dev(self.solver, self._swap_updates(args))
        try:
            yield self.solver
        finally:
            _restore_dev(self.solver, saved)

    def _build_args(self, instances):
        _check_same_shape(instances)
        nb = len(instances[0].buckets)
        store = self.solver.policy.store_dtype
        return {
            # cost planes ride the policy's store dtype (bf16 halves
            # the per-rung cell bytes, letting bucketing.py admit
            # larger rungs under the same byte budget)
            "cubes": [jnp.asarray(
                _stacked(instances, lambda a, i=i: a.buckets[i].cubes),
                dtype=store) for i in range(nb)],
            "var_ids": [_stacked(instances, lambda a, i=i:
                                 a.buckets[i].var_ids)
                        for i in range(nb)],
            "edge_var": _stacked(instances, lambda a: a.edge_var),
            "var_costs": jnp.asarray(
                _stacked(instances, lambda a: a.var_costs),
                dtype=store),
            "domain_mask": _stacked(instances, lambda a: a.domain_mask),
            "domain_size": _stacked(instances, lambda a: a.domain_size),
        }

    @property
    def solver_buckets_batched(self):
        """The batched per-bucket cube stacks (callers re-shard them
        onto a device mesh before run, e.g. __graft_entry__)."""
        return self._instance_args["cubes"]

    @solver_buckets_batched.setter
    def solver_buckets_batched(self, value):
        self._instance_args = dict(self._instance_args,
                                   cubes=list(value))


class _BatchedLocalSearch(_BatchedRunnerBase):
    """vmap a local-search solver over stacked per-instance constraint
    cubes sharing one topology — or, with ``instances=[...]``, over
    whole shape-padded topologies — the campaign workload of BASELINE
    config 5 for the DSA/MGM family, companion of
    :class:`BatchedMaxSum`."""

    solver_cls = None  # set by subclasses

    #: plain solver attributes swapped per instance on the hetero path
    _swap_attrs = ("var_costs", "domain_mask", "domain_size",
                   "initial_idx", "has_initial", "nbr_src", "nbr_dst")

    def __init__(self, template: HypergraphArrays,
                 cubes_batches: Optional[List[np.ndarray]] = None,
                 batch: int = 1,
                 instances: Optional[List[HypergraphArrays]] = None,
                 **params):
        super().__init__()
        self.solver = self.solver_cls(template, **params)
        self._template = template
        self._sign = float(template.sign)
        self._hetero = instances is not None
        # p_mode=arity derives a per-variable probability vector from
        # the topology: on the hetero path each instance batches its
        # own (phantom rows land on 1.0, which is inert — they never
        # satisfy `want`)
        self._swap_probability = self._hetero and \
            getattr(self.solver, "p_mode", "fixed") == "arity"
        if self._hetero:
            batch = len(instances)
            self._instance_args = self._build_args(instances)
            self.n_vars_true = [a.n_vars_true or a.n_vars
                                for a in instances]
        elif cubes_batches is not None:
            batch = cubes_batches[0].shape[0]
            self._instance_args = {
                "cubes": [jnp.asarray(
                    cb, dtype=self.solver.policy.store_dtype)
                    for cb in cubes_batches]}
        else:
            self._instance_args = {"cubes": [
                jnp.broadcast_to(cubes[None], (batch,) + cubes.shape)
                for cubes, _ in self.solver.buckets
            ]}
        self.B = batch

        def one_instance(args, key):
            with self._swapped(args) as base:
                out = self._drive(base, base.init_state(key))
                final, planes = out if self._collect_metrics \
                    else (out, None)
            if planes is not None:
                return (final["x"], final["cycle"],
                        final["finished"], planes)
            return final["x"], final["cycle"], final["finished"]

        self._one = one_instance

    @contextmanager
    def _swapped(self, args):
        """The template solver with one vmapped instance's cubes (and,
        on the hetero path, its whole topology) swapped in; the
        per-constraint optima (DSA-B's violation test) are re-derived
        from the swapped cubes.  Shared by the single-program run and
        the chunked checkpoint programs."""
        base = self.solver
        saved = {a: getattr(base, a) for a in self._swap_attrs}
        saved["buckets"] = base.buckets
        saved["bucket_optima"] = base.bucket_optima
        if self._swap_probability:
            saved["probability"] = base.probability
        try:
            base.buckets = [
                (c, args["var_ids"][bi] if self._hetero else vi)
                for bi, (c, (_, vi))
                in enumerate(zip(args["cubes"], saved["buckets"]))
            ]
            base.bucket_optima = [
                jnp.min(c.reshape(c.shape[0], -1), axis=-1)
                if c.shape[0] else jnp.zeros((0,), dtype=c.dtype)
                for c in args["cubes"]
            ]
            if self._hetero:
                for a in self._swap_attrs:
                    setattr(base, a, args[a])
            if self._swap_probability:
                base.probability = args["probability"]
            yield base
        finally:
            for a, v in saved.items():
                setattr(base, a, v)

    def _build_args(self, instances):
        _check_same_shape(instances)
        nb = len(instances[0].buckets)
        store = self.solver.policy.store_dtype
        args = {
            "cubes": [jnp.asarray(
                _stacked(instances, lambda a, i=i: a.buckets[i].cubes),
                dtype=store) for i in range(nb)],
            "var_ids": [_stacked(instances, lambda a, i=i:
                                 a.buckets[i].var_ids)
                        for i in range(nb)],
        }
        for name in self._swap_attrs:
            args[name] = _stacked(instances,
                                  lambda a, n=name: getattr(a, n))
        args["var_costs"] = jnp.asarray(args["var_costs"],
                                        dtype=store)
        if self._swap_probability:
            from ..algorithms.dsa import arity_probability

            args["probability"] = _stacked(instances,
                                           arity_probability)
        return args

    @property
    def cubes_batched(self):
        """The batched per-bucket cube stacks (callers re-shard them
        onto a device mesh before run, e.g. __graft_entry__)."""
        return self._instance_args["cubes"]

    @cubes_batched.setter
    def cubes_batched(self, value):
        self._instance_args = dict(self._instance_args,
                                   cubes=list(value))


class BatchedDsa(_BatchedLocalSearch):
    """vmap DSA (A/B/C variants) over per-instance cost cubes."""

    from ..algorithms.dsa import DsaSolver as solver_cls


class BatchedMgm(_BatchedLocalSearch):
    """vmap MGM over per-instance cost cubes."""

    from ..algorithms.mgm import MgmSolver as solver_cls


# ------------------------------------------------------- runner cache

BATCHED_CLASSES = {"maxsum": BatchedMaxSum, "dsa": BatchedDsa,
                   "mgm": BatchedMgm}

#: (algo, rung signature, batch, params) -> runner.  The instance
#: arrays are call ARGUMENTS of the compiled vmapped program, so a
#: cached runner serves any instance set padded to its rung signature
#: without retracing.  Scope, stated honestly: the cache is
#: per-PROCESS — within one fused campaign group a rung costs one
#: compilation by construction, and IN-PROCESS callers (library use,
#: repeated `_run_fused_group` calls, the `serve` dispatcher, benches)
#: amortize across groups sharing a rung; the CLI's one-child-per-group
#: isolation does not carry it across groups.  Bounded: oldest runners
#: (and their padded device arrays) are evicted past the cap
#: (``PYDCOP_TPU_RUNNER_CACHE``, default 32); hits/misses/evictions
#: are counted and surfaced in serve telemetry summaries.
_RUNNER_CACHE: Dict[Tuple, object] = {}
_RUNNER_CACHE_CAP = 32
_RUNNER_CACHE_STATS = {"hits": 0, "misses": 0, "evictions": 0}
RUNNER_CACHE_ENV = "PYDCOP_TPU_RUNNER_CACHE"


def runner_cache_cap() -> int:
    """The bound, read per call so tests and long-lived daemons can
    retune it; a malformed env value dies loudly instead of silently
    keeping the default."""
    raw = os.environ.get(RUNNER_CACHE_ENV)
    if raw is None:
        return _RUNNER_CACHE_CAP
    try:
        cap = int(raw)
        if cap < 1:
            raise ValueError(raw)
    except ValueError:
        raise ValueError(
            f"{RUNNER_CACHE_ENV} wants a positive integer runner "
            f"count, got {raw!r}")
    return cap


def runner_cache_stats() -> Dict[str, int]:
    """Point-in-time cache counters (plus current size and bound) for
    telemetry summaries."""
    return dict(_RUNNER_CACHE_STATS, size=len(_RUNNER_CACHE),
                cap=runner_cache_cap())


def runner_cache_bytes() -> Dict[str, int]:
    """Approximate resident array bytes per cached runner, keyed by a
    compact ``algo/rung/batch`` label — the live-buffer census leg the
    serve memory snapshot attributes to rungs (each runner pins its
    padded instance arguments on device for as long as it is
    cached)."""
    from ..observability.memory import approx_object_bytes
    from .bucketing import rung_label

    out: Dict[str, int] = {}
    for key, runner in list(_RUNNER_CACHE.items()):
        algo, sig, b = key[0], key[1], key[2]
        label = f"{algo}/{rung_label(sig)}/b{b}"
        out[label] = out.get(label, 0) + approx_object_bytes(
            getattr(runner, "_instance_args", None))
    return out


def evict_runner(algo: str, rung_signature: Tuple, batch: int,
                 params: dict) -> bool:
    """Drop one cached runner by its exact identity.  The serve
    dispatcher calls this after a watchdog timeout: the abandoned
    worker thread may still be executing the timed-out runner, so the
    retry/bisection attempts must build a FRESH runner instead of
    calling ``set_instances`` on (and racing against) the one in
    flight.  Returns whether an entry was dropped."""
    key = (algo, rung_signature, int(batch),
           tuple(sorted(params.items())))
    if _RUNNER_CACHE.pop(key, None) is not None:
        _RUNNER_CACHE_STATS["evictions"] += 1
        return True
    return False


def runner_for_rung(algo: str, instances, params: dict,
                    rung_signature: Optional[Tuple] = None,
                    exec_cache=None, tuned_store=None):
    """Build — or fetch and re-point — the batched runner for ``algo``
    over instances padded to one rung shape.  ``exec_cache`` (an
    :class:`~pydcop_tpu.engine._cache.ExecutableCache`) additionally
    persists the compiled program across PROCESSES, keyed by this
    rung-signature identity — the serve daemon's warm restart.

    ``tuned_store`` (a :class:`~pydcop_tpu.tuning.store
    .TunedConfigStore`) folds the rung's measured-fastest knobs into
    ``params`` BEFORE the cache key is computed — a caller pinning
    the winning config explicitly and a caller resolving it from the
    store land on the SAME cached runner and the SAME compiled
    program, which is what makes tuned selections bit-exact with the
    explicit spelling by construction.  Explicit params always win;
    the per-knob resolution (``explicit``/``tuned``/``default``)
    lands on ``runner.tuning_sources`` for result blocks and
    telemetry."""
    cls = BATCHED_CLASSES[algo]
    tuning_sources = None
    if tuned_store is not None and rung_signature is not None:
        from ..tuning.store import resolve_knobs

        params, tuning_sources = resolve_knobs(
            algo, params, rung_signature, tuned_store,
            context="batched")
    key = None
    if rung_signature is not None:
        key = (algo, rung_signature, len(instances),
               tuple(sorted(params.items())))
        runner = _RUNNER_CACHE.get(key)
        if runner is not None:
            _RUNNER_CACHE_STATS["hits"] += 1
            if exec_cache is not None:
                runner.exec_cache = exec_cache
                runner.exec_cache_key = key
            runner.tuning_sources = tuning_sources
            runner.set_instances(instances)
            return runner
        _RUNNER_CACHE_STATS["misses"] += 1
    runner = cls(instances[0], instances=list(instances), **params)
    runner.tuning_sources = tuning_sources
    if exec_cache is not None:
        runner.exec_cache = exec_cache
        runner.exec_cache_key = key if key is not None else (
            algo, len(instances), tuple(sorted(params.items())))
    if key is not None:
        cap = runner_cache_cap()
        while len(_RUNNER_CACHE) >= cap:
            _RUNNER_CACHE.pop(next(iter(_RUNNER_CACHE)))
            _RUNNER_CACHE_STATS["evictions"] += 1
        _RUNNER_CACHE[key] = runner
    return runner


def runner_for_arm_group(algo: str, template, batch: int,
                         params: dict,
                         group_signature: Optional[Tuple] = None,
                         exec_cache=None):
    """The portfolio flip of :func:`runner_for_rung`: ONE instance
    broadcast across ``batch`` arm lanes (same family + hyperparams,
    per-lane seeds).  The broadcast constructor path makes the cubes
    views of one buffer, so an arm group costs one instance's device
    memory regardless of lane count, and the vmapped chunk programs
    trace once per (group, batch) — the rebatch ladder's rungs.

    ``group_signature`` must carry a stable INSTANCE identity (the
    serve queue passes its ``(path, mtime_ns, size)`` key): unlike the
    rung-padded hetero path, the broadcast cubes bake this instance's
    contents into the cached runner, so caching without that identity
    would hand another instance's program to the caller.  Without a
    signature the runner is built fresh and never cached."""
    cls = BATCHED_CLASSES[algo]
    key = None
    if group_signature is not None:
        key = (algo, ("arm",) + tuple(group_signature), int(batch),
               tuple(sorted(params.items())))
        runner = _RUNNER_CACHE.get(key)
        if runner is not None:
            _RUNNER_CACHE_STATS["hits"] += 1
            if exec_cache is not None:
                runner.exec_cache = exec_cache
                runner.exec_cache_key = key
            return runner
        _RUNNER_CACHE_STATS["misses"] += 1
    runner = cls(template, batch=int(batch), **params)
    # the broadcast path leaves per-lane true sizes unset (it serves
    # one instance); every lane decodes to the template's true width
    runner.n_vars_true = [getattr(template, "n_vars_true", None)
                          or template.n_vars] * int(batch)
    if exec_cache is not None:
        runner.exec_cache = exec_cache
        runner.exec_cache_key = key if key is not None else (
            algo, "arm", int(batch), tuple(sorted(params.items())))
    if key is not None:
        cap = runner_cache_cap()
        while len(_RUNNER_CACHE) >= cap:
            _RUNNER_CACHE.pop(next(iter(_RUNNER_CACHE)))
            _RUNNER_CACHE_STATS["evictions"] += 1
        _RUNNER_CACHE[key] = runner
    return runner

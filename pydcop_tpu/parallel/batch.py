"""Batched solving: many independent instances in one compiled program.

reference parity: ``pydcop batch`` runs jobs *sequentially* (the reference
acknowledges "run in parallel" as a TODO, commands/batch.py:68).  Here a
batch of instances sharing a topology (e.g. 1024 random graph-coloring /
Ising draws — BASELINE config 5) is one vmapped solver whose batch axis
can additionally be sharded over the mesh's dp axis.
"""

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..graphs.arrays import FactorGraphArrays, HypergraphArrays
from ..algorithms.maxsum import MaxSumSolver


def _batch_keys(seed, seeds, b):
    if seeds is None:
        return jax.random.split(jax.random.PRNGKey(seed), b)
    if len(seeds) != b:
        raise ValueError(f"need {b} seeds, got {len(seeds)}")
    return jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])


class BatchedMaxSum:
    """vmap MaxSum over stacked per-instance cost cubes (same topology)."""

    def __init__(self, template: FactorGraphArrays,
                 cubes_batches: Optional[List[np.ndarray]] = None,
                 batch: int = 1, **params):
        self.solver = MaxSumSolver(template, **params)
        if cubes_batches is not None:
            batch = cubes_batches[0].shape[0]
            self.solver_buckets_batched = [
                jnp.asarray(cb) for cb in cubes_batches
            ]
        else:
            self.solver_buckets_batched = [
                jnp.broadcast_to(cubes[None],
                                 (batch,) + cubes.shape)
                for cubes, _, _ in self.solver.buckets
            ]
        self.B = batch

        base = self.solver

        def one_instance(cubes_list, key):
            # swap the solver's cubes for this instance's
            orig = base.buckets
            base.buckets = [
                (c, ei, vi)
                for c, (_, ei, vi) in zip(cubes_list, orig)
            ]
            state = base.init_state(key)
            try:
                def body(s):
                    return base.step(s)

                def cond(s):
                    return jnp.logical_and(
                        jnp.logical_not(s["finished"]),
                        s["cycle"] < self.max_cycles)

                final = jax.lax.while_loop(cond, body, state)
            finally:
                base.buckets = orig
            # decode through assignment_indices, NOT the raw selection
            # field: with stability:0 the step elides the per-cycle
            # argmin and carries the INIT-state selection — the live
            # assignment must be rebuilt from the final messages, the
            # same decode the sync engine uses
            return (base.assignment_indices(final), final["cycle"],
                    final["finished"])

        self._one = one_instance
        self.max_cycles = 200
        self._jitted = {}  # max_cycles -> compiled vmapped runner

    def run(self, seed: int = 0, max_cycles: int = 200, seeds=None):
        """Returns (selections (B, V), cycles (B,), finished (B,)).
        ``seeds`` gives each instance its own engine seed (fused batch
        campaigns: row i carries job i's declared seed); default is the
        split-key stream of ``seed``."""
        self.max_cycles = max_cycles
        keys = _batch_keys(seed, seeds, self.B)
        # max_cycles is baked into the traced while-loop via the closure,
        # so the compiled runner is cached per max_cycles value
        run_all = self._jitted.get(max_cycles)
        if run_all is None:
            run_all = jax.jit(jax.vmap(self._one, in_axes=(0, 0)))
            self._jitted[max_cycles] = run_all
        sel, cycles, finished = run_all(self.solver_buckets_batched, keys)
        return (np.asarray(sel), np.asarray(cycles), np.asarray(finished))


class _BatchedLocalSearch:
    """vmap a local-search solver over stacked per-instance constraint
    cubes sharing one topology — the campaign workload of BASELINE
    config 5 (1024 random Ising / coloring draws) for the DSA/MGM
    family, companion of :class:`BatchedMaxSum`."""

    solver_cls = None  # set by subclasses

    def __init__(self, template: HypergraphArrays,
                 cubes_batches: Optional[List[np.ndarray]] = None,
                 batch: int = 1, **params):
        self.solver = self.solver_cls(template, **params)
        if cubes_batches is not None:
            batch = cubes_batches[0].shape[0]
            self.cubes_batched = [jnp.asarray(cb)
                                  for cb in cubes_batches]
        else:
            self.cubes_batched = [
                jnp.broadcast_to(cubes[None], (batch,) + cubes.shape)
                for cubes, _ in self.solver.buckets
            ]
        self.B = batch
        self.max_cycles = 200
        self._jitted = {}

        base = self.solver

        def one_instance(cubes_list, key):
            # swap in this instance's cubes; the per-constraint optima
            # (DSA-B's violation test) must be re-derived from them
            orig, orig_opt = base.buckets, base.bucket_optima
            base.buckets = [
                (c, vi) for c, (_, vi) in zip(cubes_list, orig)
            ]
            base.bucket_optima = [
                jnp.min(c.reshape(c.shape[0], -1), axis=-1)
                if c.shape[0] else jnp.zeros((0,), dtype=c.dtype)
                for c in cubes_list
            ]
            state = base.init_state(key)
            try:
                def body(s):
                    return base.step(s)

                def cond(s):
                    return jnp.logical_and(
                        jnp.logical_not(s["finished"]),
                        s["cycle"] < self.max_cycles)

                final = jax.lax.while_loop(cond, body, state)
            finally:
                base.buckets, base.bucket_optima = orig, orig_opt
            return final["x"], final["cycle"], final["finished"]

        self._one = one_instance

    def run(self, seed: int = 0, max_cycles: int = 200, seeds=None):
        """Returns (selections (B, V), cycles (B,), finished (B,));
        ``seeds`` optionally fixes one engine seed per instance."""
        self.max_cycles = max_cycles
        keys = _batch_keys(seed, seeds, self.B)
        run_all = self._jitted.get(max_cycles)
        if run_all is None:
            run_all = jax.jit(jax.vmap(self._one, in_axes=(0, 0)))
            self._jitted[max_cycles] = run_all
        sel, cycles, finished = run_all(self.cubes_batched, keys)
        return (np.asarray(sel), np.asarray(cycles),
                np.asarray(finished))


class BatchedDsa(_BatchedLocalSearch):
    """vmap DSA (A/B/C variants) over per-instance cost cubes."""

    from ..algorithms.dsa import DsaSolver as solver_cls


class BatchedMgm(_BatchedLocalSearch):
    """vmap MGM over per-instance cost cubes."""

    from ..algorithms.mgm import MgmSolver as solver_cls

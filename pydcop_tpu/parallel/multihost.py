"""Multi-host bootstrap: the DCN side of the distributed backend.

reference parity: the reference scales to machines with
``pydcop orchestrator`` + ``pydcop agent`` over HTTP
(SURVEY.md §2.8 #3).  This framework keeps that control plane (it works
across hosts unchanged — agents POST JSON to the orchestrator's
address) and adds the *data plane* story: multi-controller JAX over
DCN, where every host runs the same program and the global device mesh
spans all hosts' chips.

Typical pod usage::

    from pydcop_tpu.parallel.multihost import initialize_multihost, \
        global_mesh

    initialize_multihost()            # jax.distributed.initialize()
    mesh = global_mesh(dp=..., tp=...)
    solver = ShardedMaxSum(arrays, mesh, batch=...)

On TPU pods ``jax.distributed.initialize`` picks up the coordinator
from the environment; on CPU/GPU clusters pass coordinator_address /
num_processes / process_id explicitly.
"""

from typing import Optional, Tuple

import numpy as np


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Initialize multi-controller JAX; returns True when running
    multi-process (False for a single-process run, which needs no
    initialization)."""
    import jax

    if num_processes in (None, 1) and coordinator_address is None:
        try:
            jax.distributed.initialize()
        except Exception:
            # single-host run (no coordinator in the environment)
            return False
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    return jax.process_count() > 1


def global_mesh(dp: Optional[int] = None, tp: Optional[int] = None,
                axis_names: Tuple[str, str] = ("dp", "tp")):
    """A (dp, tp) mesh over ALL hosts' devices.

    Defaults: tp = devices per host (so tensor-parallel collectives ride
    ICI within a host/slice), dp = the rest (instance parallelism over
    DCN, which only synchronizes at chunk boundaries).
    """
    import jax

    devices = np.array(jax.devices())
    n = devices.size
    if tp is None:
        tp = max(1, jax.local_device_count())
    if dp is None:
        dp = n // tp
    if dp * tp != n:
        raise ValueError(
            f"dp*tp = {dp}*{tp} != {n} global devices")
    return jax.sharding.Mesh(devices.reshape(dp, tp), axis_names)

"""Multi-chip MaxSum: dp x tp sharded step over a jax.sharding.Mesh.

This is the framework's "distributed communication backend" for the data
plane (SURVEY.md §2.8): where the reference scales out by placing agent
processes on machines and POSTing JSON messages over HTTP
(pydcop/infrastructure/communication.py:313-441), the TPU framework
shards the *stacked message arrays* over a device mesh:

* ``dp`` (data-parallel) axis — independent problem instances (the batch
  dimension of BASELINE config 5),
* ``tp`` (tensor-parallel) axis — factors of one instance, partitioned
  across devices; the variable update's segment-sum over incoming
  messages becomes a per-device partial sum + ``psum`` over ``tp`` — the
  XLA collective rides ICI, replacing the reference's network plane.

The factor partition is computed host-side (round-robin per arity bucket,
padded with inert dummy factors so every shard has identical static
shapes); dummy edges point at a sink variable row which every reduction
masks out.  The per-shard edge layout is canonical factor-major by
construction, so the shard-local update supports the same two layouts as
the single-chip solver: edge-major ``(E, D)`` and lane-major ``(D, E)``
(edges riding the 128-wide lane dimension, reusing the lane factor
kernel).

Message semantics mirror the single-chip :class:`MaxSumSolver` exactly:
``damping_nodes`` (vars / factors / both / none), solver noise, mean
normalization over valid slots, and SAME_COUNT-stable convergence with
the same damping-scaled stability threshold — so a sharded run and a
single-chip run of the same instance select the same values.
"""

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from ._mesh_cost import build_mesh_cost
from ..engine._cache import enable_persistent_cache
from ..engine.mesh_engine import MeshSolverMixin
from ..graphs.arrays import BIG, SENTINEL, FactorGraphArrays
from ..ops.kernels import (PrunedPlan, belief_margins,
                           build_pruned_plan, decimation_select,
                           factor_messages, factor_messages_pruned)
from ..ops.precision import resolve as resolve_precision

SAME_COUNT = 4


@dataclass
class _ShardedBucket:
    arity: int
    cubes: np.ndarray      # (TP, F, D, ..., D)
    offset: int            # first local edge id of this bucket's block
    var_ids: np.ndarray    # (TP, F, arity) — global var ids (V = sink)


def _round_robin(n: int, tp: int) -> np.ndarray:
    """(tp, ceil(n/tp)) indices, -1 marking padded dummy slots."""
    fmax = (n + tp - 1) // tp if n else 0
    idx = np.full((tp, fmax), -1, dtype=np.int64)
    for g in range(tp):
        ids = np.arange(g, n, tp)
        idx[g, : len(ids)] = ids
    return idx


def _partition(arrays: FactorGraphArrays, tp: int):
    """Split factors across tp shards (vectorized gather per bucket; the
    only Python loop is over the tp shards for the index table)."""
    D = arrays.max_domain
    V = arrays.n_vars
    shard_buckets: List[_ShardedBucket] = []
    offset = 0
    blocks = []  # per bucket: (TP, fmax*arity) var ids for edge_var
    for b in arrays.buckets:
        a = b.arity
        idx = _round_robin(b.cubes.shape[0], tp)
        fmax = idx.shape[1]
        valid = idx >= 0
        cubes = np.full((tp, fmax) + (D,) * a, BIG, dtype=np.float32)
        var_ids = np.full((tp, fmax, a), V, dtype=np.int32)
        cubes[valid] = b.cubes[idx[valid]]
        var_ids[valid] = b.var_ids[idx[valid]]
        shard_buckets.append(_ShardedBucket(a, cubes, offset, var_ids))
        blocks.append(var_ids.reshape(tp, fmax * a))
        offset += fmax * a
    e_loc = offset
    edge_var = (np.concatenate(blocks, axis=1) if blocks
                else np.full((tp, 0), V, dtype=np.int32)).astype(np.int32)
    return shard_buckets, edge_var, e_loc


class ShardedMaxSum(MeshSolverMixin):
    """MaxSum over a (dp, tp) mesh.

    Parameters mirror the single-chip solver
    (``algorithms/maxsum.py``): ``damping`` / ``damping_nodes`` /
    ``stability`` / ``noise``; ``layout`` picks the shard-local state
    layout (``edge_major`` or ``lane_major``; ``auto`` = lane-major when
    all factor arities are <= 2, like ``build_solver``).

    ``batch`` independent instances ride the dp axis (must be a multiple
    of the mesh's dp size).
    """

    #: whether the algorithm's own termination rule fired on the
    #: last completed run() (False before/without a completed run)
    finished = False

    def _init_params(self, arrays, mesh, damping, damping_nodes,
                     stability, noise, batch, precision=None,
                     decimation_p=0.0, decimation_every=0):
        """The parameter block every mesh layout shares — ONE copy of
        the damping-invariant convergence-threshold rule
        (algorithms/maxsum.py:64-70) and the batch/dp check, so the
        fused mesh class can never diverge from the lane mesh on
        convergence semantics."""
        from ..algorithms.maxsum import normalize_decimation

        (self.decimation_p, self.decimation,
         self.decimation_every) = normalize_decimation(
            decimation_p, decimation_every)
        # subclasses without plans (fused mesh, which rejects bnb)
        # inherit the inert defaults
        self.bnb = False
        self._bnb_plans_np = []
        self._bnb_active = False
        self._bnb_cells_total = 0
        # mesh runs re-traced from cold every process before the mesh
        # engine: turn the persistent XLA cache on for every sharded
        # construction path, like SyncEngine does for single-chip
        enable_persistent_cache()
        # mixed-precision policy: cost planes (cubes, unary costs) are
        # device-placed in store_dtype; message planes, psums and the
        # on-device cost trace stay in accum f32 (ops/precision.py)
        self.policy = resolve_precision(precision)
        self.mesh = mesh
        self.tp = mesh.shape["tp"]
        self.dp = mesh.shape["dp"]
        self.damping = float(damping)
        self.damping_nodes = damping_nodes
        self.stability = float(stability)
        if damping_nodes in ("vars", "both") and 0 < damping < 1:
            self.stability *= (1 - float(damping))
        self.noise = float(noise)
        self.V = arrays.n_vars
        self.D = arrays.max_domain
        if batch % self.dp != 0:
            raise ValueError(
                f"batch {batch} must be a multiple of dp={self.dp}")
        self.B = batch

    def __init__(self, arrays: FactorGraphArrays, mesh,
                 damping: float = 0.5, damping_nodes: str = "vars",
                 stability: float = 0.1, noise: float = 0.0,
                 layout: str = "auto", batch: int = 1,
                 use_pallas: Optional[bool] = None,
                 precision=None, decimation_p: float = 0.0,
                 decimation_every: int = 0, bnb: bool = False):
        self._init_params(arrays, mesh, damping, damping_nodes,
                          stability, noise, batch, precision=precision,
                          decimation_p=decimation_p,
                          decimation_every=decimation_every)

        # validate BEFORE the host-side factor partition: a bad layout
        # must fail fast, not after padding every bucket across shards
        if layout not in ("auto", "edge_major", "lane_major"):
            raise ValueError(
                f"ShardedMaxSum supports layouts auto/edge_major/"
                f"lane_major, not {layout!r} (the fused var-sorted "
                f"layout lives in ShardedFusedMaxSum; solve_sharded "
                f"dispatches -p layout:fused there)")
        shard_buckets, edge_var, e_loc = _partition(arrays, self.tp)
        self.E_loc = e_loc
        self.buckets = shard_buckets
        self.edge_var = edge_var                        # (TP, E_loc)
        self._build_bnb_plans(bnb, shard_buckets)
        from ..ops.pallas_kernels import (NARY_FALLBACK_TEXT,
                                          nary_fast_eligible)

        def _lane_ok(bi, sb):
            # the shared (env-overridable) fast-path gate; a bnb plan
            # replaces the unrolled sweep for its bucket, so planned
            # buckets pass regardless of cell count
            return nary_fast_eligible(self.D, sb.arity) or (
                self._bnb_active
                and self._bnb_plans_np[bi] is not None)
        if layout == "auto":
            layout = "lane_major" if all(
                _lane_ok(bi, sb)
                for bi, sb in enumerate(shard_buckets)) \
                else "edge_major"
        if layout == "lane_major" and not all(
                _lane_ok(bi, sb)
                for bi, sb in enumerate(shard_buckets)):
            raise ValueError(
                f"lane_major needs {NARY_FALLBACK_TEXT}; use "
                "edge_major for bigger factors")
        self.layout = layout
        if use_pallas is None:
            # same measured default as the single-chip lane solver
            # (algorithms/maxsum.py:266-272): the fused kernel wins in
            # isolation but blocks XLA's elementwise fusion around it,
            # so the all-jnp step is faster on the benched chip; the
            # kernel stays available for larger domains / other chips
            use_pallas = False
        self.use_pallas = bool(use_pallas)
        # off-TPU the fused kernel runs in pallas interpret mode so the
        # kernel path itself is testable on the virtual CPU mesh
        self._pallas_interpret = jax.default_backend() != "tpu"

        vc = np.concatenate(
            [np.asarray(arrays.var_costs, dtype=np.float32),
             np.full((1, self.D), BIG, dtype=np.float32)])
        self.var_costs = vc                             # (V+1, D)
        dm = np.concatenate(
            [arrays.domain_mask, np.zeros((1, self.D), dtype=bool)])
        self.domain_mask = dm
        ds = np.concatenate(
            [arrays.domain_size, np.ones((1,), dtype=np.int32)])
        self.domain_size = ds

        self._build_step()

    # -------------------------------------------------- bnb plumbing

    def _build_bnb_plans(self, bnb, shard_buckets):
        """Per-shard branch-and-bound plans, stacked along a leading
        TP axis (every shard's bucket has identical padded shape, so
        block counts agree; cell ORDER is per-shard).  Buckets too
        small to pay for bound checks stay None — full scan."""
        self.bnb = bool(bnb)
        self._bnb_plans_np = []
        if self.bnb:
            for sb in shard_buckets:
                per_shard = [build_pruned_plan(sb.cubes[g])
                             for g in range(self.tp)]
                if not per_shard or per_shard[0] is None:
                    self._bnb_plans_np.append(None)
                    continue
                self._bnb_plans_np.append(PrunedPlan(
                    digits=np.stack([p.digits for p in per_shard]),
                    cube_cells=np.stack(
                        [p.cube_cells for p in per_shard]),
                    suffix_min=np.stack(
                        [p.suffix_min for p in per_shard]),
                    block=per_shard[0].block,
                    n_blocks=per_shard[0].n_blocks,
                    n_cells=per_shard[0].n_cells))
        self._bnb_active = any(p is not None
                               for p in self._bnb_plans_np)
        self._bnb_cells_total = sum(
            pl.n_blocks * pl.block * sb.cubes.shape[1]
            for pl, sb in zip(self._bnb_plans_np, shard_buckets)
            if pl is not None)

    def _features_on(self) -> bool:
        """Whether the extended (decimation/bnb) step signature is in
        force; off means the compiled program is byte-identical to the
        pre-feature solver."""
        return self.decimation or self._bnb_active

    # ------------------------------------------------------------ state

    def _init_state(self):
        """Fresh per-run message state, sharded onto the mesh."""
        B, TP, E, D = self.B, self.tp, self.E_loc, self.D
        mask_e = self.domain_mask[self.edge_var]        # (TP, E, D)
        q0 = np.where(mask_e, 0.0, BIG).astype(np.float32)
        r0 = np.zeros_like(q0)
        q0 = np.broadcast_to(q0[None], (B, TP, E, D)).copy()
        r0 = np.broadcast_to(r0[None], (B, TP, E, D)).copy()
        sh = NamedSharding(self.mesh, P("dp", "tp"))
        return {"q": jax.device_put(q0, sh),
                "r": jax.device_put(r0, sh)}

    def _make_consts(self):
        mesh = self.mesh
        store = self.policy.store_dtype
        consts = {
            "edge_var": jax.device_put(
                self.edge_var, NamedSharding(mesh, P("tp"))),
            # cost planes ride the store dtype (half the HBM bytes per
            # cycle under bf16); everything integer/bool is untouched
            "cubes": [
                jax.device_put(np.asarray(sb.cubes, dtype=store),
                               NamedSharding(mesh, P("tp")))
                for sb in self.buckets
            ],
            "var_costs": jax.device_put(
                jnp.asarray(self.var_costs, dtype=store),
                NamedSharding(mesh, P())),
            "domain_mask": jax.device_put(
                jnp.asarray(self.domain_mask), NamedSharding(mesh, P())),
            "domain_size": jax.device_put(
                jnp.asarray(self.domain_size), NamedSharding(mesh, P())),
        }
        if self._bnb_active:
            from ..ops.kernels import pruned_suffix_min

            tp_sh = NamedSharding(mesh, P("tp"))

            def _place_plan(pl):
                # bounds recomputed from the STORE-ROUNDED values the
                # sweep reads, never the f32 build values (bf16 rounds
                # down: an f32 bound above the stored floor could
                # early-out past a winning cell)
                stored = np.asarray(pl.cube_cells, dtype=store)
                return PrunedPlan(
                    digits=jax.device_put(pl.digits, tp_sh),
                    cube_cells=jax.device_put(stored, tp_sh),
                    suffix_min=jax.device_put(pruned_suffix_min(
                        stored, pl.block, pl.n_blocks), tp_sh),
                    block=pl.block, n_blocks=pl.n_blocks,
                    n_cells=pl.n_cells)

            consts["bnb_plans"] = [
                None if pl is None else _place_plan(pl)
                for pl in self._bnb_plans_np
            ]
        return consts

    def _device_put(self):
        """Shard the state and constants onto the mesh (constants come
        from the per-instance cache; the dict is a shallow copy so a
        session may swap entries without touching the cache)."""
        return self._init_state(), dict(self._consts())

    # ------------------------------------------------------------- step

    def _factor_update_edge_major(self, q, cubes, plans=None):
        """(E, D) layout: per-bucket factor_messages, canonical
        slices; a branch-and-bound ``plans`` entry reroutes its bucket
        through the pruned sweep (bit-exact).  Returns ``(new_r,
        pruned_runs)``."""
        E, D = self.E_loc, self.D
        blocks = []
        runs = []
        for bi, (sb, cu) in enumerate(zip(self.buckets, cubes)):
            a = sb.arity
            if a == 0:
                continue
            f = cu.shape[0]
            q_blk = q[sb.offset:sb.offset + f * a].reshape(f, a, D)
            q_in = [q_blk[:, p] for p in range(a)]
            plan = plans[bi] if plans is not None else None
            if plan is not None:
                msgs, br = factor_messages_pruned(plan, q_in)
                runs.append((br, plan.block * f))
            else:
                msgs = factor_messages(cu, q_in)
            blocks.append(jnp.stack(msgs, axis=1).reshape(f * a, D))
        if not blocks:
            return jnp.zeros((E, D), dtype=q.dtype), runs
        return (blocks[0] if len(blocks) == 1 else
                jnp.concatenate(blocks, axis=0)), runs

    def _factor_update_lane_major(self, qT, cubes, plans=None):
        """(D, E) layout: lane kernels, same math as MaxSumLaneSolver —
        per-arity-bucket dispatch identical to the single-chip solver
        (binary and small-n-ary buckets each one fused kernel on the
        pallas path, jnp fallbacks elsewhere; branch-and-bound plans
        reroute to the pruned sweep).  Returns ``(new_r,
        pruned_runs)``."""
        D, E = self.D, self.E_loc
        blocks = []
        runs = []
        for bi, (sb, cu) in enumerate(zip(self.buckets, cubes)):
            a = sb.arity
            if a == 0:
                continue
            f = cu.shape[0]
            if a == 1:
                # unary msg = the cost row, upcast to the message
                # (accum) dtype before mixed-arity concatenation
                blocks.append(jnp.transpose(cu).astype(qT.dtype))
                continue
            cubesT = jnp.moveaxis(cu, 0, -1)            # (D, ..., D, F)
            q_blk = qT[:, sb.offset:sb.offset + a * f]
            q_in = [q_blk[:, p::a] for p in range(a)]
            from ..ops.pallas_kernels import factor_messages_lane_major

            plan = plans[bi] if plans is not None else None
            out = factor_messages_lane_major(
                cubesT, q_in, a, use_pallas=self.use_pallas,
                interpret=self._pallas_interpret, plan=plan)
            if plan is not None:
                msgs, br = out
                runs.append((br, plan.block * f))
            else:
                msgs = out
            blocks.append(jnp.stack(msgs, axis=2)
                          .reshape(D, a * f))
        if not blocks:
            return jnp.zeros((D, E), dtype=qT.dtype), runs
        return (blocks[0] if len(blocks) == 1 else
                jnp.concatenate(blocks, axis=1)), runs

    def _build_step(self):
        if self._features_on():
            # decimation/bnb runs compile the EXTENDED step; with both
            # off this builder stays byte-for-byte the historical one
            # (the off == today bit-exactness contract)
            self._build_step_features()
            return
        V, D, E = self.V, self.D, self.E_loc
        damping, damping_nodes = self.damping, self.damping_nodes
        noise = self.noise
        lane = self.layout == "lane_major"

        def local_step(q, r, key, edge_var, cubes, var_costs,
                       domain_mask, domain_size):
            # q, r: (B_loc, E, D); edge_var: (E,)
            def one(q1, r1, k1):
                with jax.named_scope("maxsum/factor_update"):
                    new_r = self._factor_update_edge_major(
                        q1, cubes)[0] \
                        if not lane else jnp.transpose(
                            self._factor_update_lane_major(
                                jnp.transpose(q1), cubes)[0])
                if damping_nodes in ("factors", "both") and damping > 0:
                    new_r = damping * r1 + (1 - damping) * new_r
                with jax.named_scope("maxsum/var_update"):
                    partial_sum = jax.ops.segment_sum(
                        new_r, edge_var, num_segments=V + 1)
                    sum_r = jax.lax.psum(partial_sum, "tp")
                    belief = var_costs + sum_r
                q_new = belief[edge_var] - new_r
                mask_e = domain_mask[edge_var]
                mean = (jnp.sum(jnp.where(mask_e, q_new, 0.0), axis=1)
                        / domain_size[edge_var])
                q_new = q_new - mean[:, None]
                if noise > 0:
                    # per-(shard, instance) streams: edges are split
                    # across devices so one global stream cannot exist
                    tp_idx = jax.lax.axis_index("tp")
                    sub = jax.random.fold_in(k1, tp_idx)
                    q_new = q_new + noise * jax.random.uniform(
                        sub, q_new.shape)
                if damping_nodes in ("vars", "both") and damping > 0:
                    q_new = damping * q1 + (1 - damping) * q_new
                q_new = jnp.where(mask_e, q_new, BIG)
                sel = jnp.argmin(
                    jnp.where(domain_mask[:V], belief[:V],
                              jnp.asarray(SENTINEL, belief.dtype)),
                    axis=-1)
                # stability <= 0 disables delta convergence (same dead-
                # compute elision as the single-chip solvers): skip the
                # full-array reduce AND its cross-shard pmax collective.
                # Telemetry re-enables it as the residual plane: the
                # IN-step reduce fuses over q planes already live here,
                # where an engine-side |Δq| pass would pin the old q
                # buffer across the step and break donation
                if E and (self.stability > 0 or self._telemetry_delta):
                    delta_local = jnp.max(
                        jnp.where(mask_e, jnp.abs(q_new - q1), 0.0))
                    delta = jax.lax.pmax(delta_local, "tp")
                else:
                    delta = jnp.float32(0)
                return q_new, new_r, sel, delta

            dp_idx = jax.lax.axis_index("dp")
            keys = jax.vmap(
                lambda i: jax.random.fold_in(
                    jax.random.fold_in(key, dp_idx), i))(
                jnp.arange(q.shape[0]))
            return jax.vmap(one)(q, r, keys)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(
                P("dp", "tp"), P("dp", "tp"), P(), P("tp"),
                [P("tp") for _ in self.buckets],
                P(), P(), P(),
            ),
            out_specs=(P("dp", "tp"), P("dp", "tp"), P("dp"), P("dp")),
            # pallas_call cannot declare vma on its outputs yet, so the
            # varying-mesh-axis check is off ONLY for the kernel path;
            # the jnp paths keep the trace-time spec verification
            check_vma=not (self.layout == "lane_major"
                           and self.use_pallas),
        )
        def sharded(q, r, key, edge_var, cubes, var_costs,
                    domain_mask, domain_size):
            q2, r2, sel, delta = local_step(
                q[:, 0], r[:, 0], key, edge_var[0],
                [c[0] for c in cubes],
                var_costs, domain_mask, domain_size)
            return q2[:, None], r2[:, None], sel, delta

        self._step = jax.jit(sharded)

    def _build_step_features(self):
        """The decimation/bnb-extended sharded step: same per-cycle
        math as ``_build_step``'s, plus the frozen-variable clamp and
        chunk-aligned freeze events (decimation) and/or the pruned
        factor reductions (bnb).  Signature grows by ``(frozen, pin,
        cycle)`` state-side and the plan constants; outputs add
        ``(frozen, pin, pruned)``.  The freeze selection runs in a
        ``lax.cond`` OUTSIDE the per-instance vmap, so non-event
        cycles skip the margin sort entirely."""
        V, D, E = self.V, self.D, self.E_loc
        damping, damping_nodes = self.damping, self.damping_nodes
        noise = self.noise
        lane = self.layout == "lane_major"
        decim = self.decimation
        bnb = self._bnb_active
        p_frac = self.decimation_p
        every = self.decimation_every
        cells_total = self._bnb_cells_total

        def local_step(q, r, key, frozen, pin, cycle, edge_var, cubes,
                       var_costs, domain_mask, domain_size, plans):
            # q, r: (B_loc, E, D); frozen/pin: (B_loc, V); edge_var:
            # (E,) with V marking dummy (sink) edges.  A decimation-
            # only run carries NO plans (empty list) — full scans for
            # every bucket
            if not plans:
                plans = None

            def one(q1, r1, k1):
                with jax.named_scope("maxsum/factor_update"):
                    if lane:
                        new_rT, runs = self._factor_update_lane_major(
                            jnp.transpose(q1), cubes, plans)
                        new_r = jnp.transpose(new_rT)
                    else:
                        new_r, runs = self._factor_update_edge_major(
                            q1, cubes, plans)
                if damping_nodes in ("factors", "both") and damping > 0:
                    new_r = damping * r1 + (1 - damping) * new_r
                with jax.named_scope("maxsum/var_update"):
                    partial_sum = jax.ops.segment_sum(
                        new_r, edge_var, num_segments=V + 1)
                    sum_r = jax.lax.psum(partial_sum, "tp")
                    belief = var_costs + sum_r
                q_new = belief[edge_var] - new_r
                mask_e = domain_mask[edge_var]
                mean = (jnp.sum(jnp.where(mask_e, q_new, 0.0), axis=1)
                        / domain_size[edge_var])
                q_new = q_new - mean[:, None]
                if noise > 0:
                    tp_idx = jax.lax.axis_index("tp")
                    sub = jax.random.fold_in(k1, tp_idx)
                    q_new = q_new + noise * jax.random.uniform(
                        sub, q_new.shape)
                if damping_nodes in ("vars", "both") and damping > 0:
                    q_new = damping * q1 + (1 - damping) * q_new
                q_new = jnp.where(mask_e, q_new, BIG)
                sel = jnp.argmin(
                    jnp.where(domain_mask[:V], belief[:V],
                              jnp.asarray(SENTINEL, belief.dtype)),
                    axis=-1)
                if bnb and cells_total:
                    executed = jnp.float32(0)
                    for br, w in runs:
                        executed = executed + \
                            br.astype(jnp.float32) * jnp.float32(w)
                    frac = 1.0 - executed / jnp.float32(cells_total)
                else:
                    frac = jnp.float32(0)
                return q_new, new_r, sel, belief, frac

            dp_idx = jax.lax.axis_index("dp")
            keys = jax.vmap(
                lambda i: jax.random.fold_in(
                    jax.random.fold_in(key, dp_idx), i))(
                jnp.arange(q.shape[0]))
            q2, r2, sel, beliefs, frac = jax.vmap(one)(q, r, keys)
            # per-instance pruned-cell fraction, tp-averaged so the
            # out spec stays tp-invariant (shards prune independently)
            pruned = jax.lax.pmean(frac, "tp") if bnb else frac

            if decim:
                do = ((cycle + 1) % every) == 0
                elig = domain_size[:V] > 1

                def _on(_):
                    with jax.named_scope("maxsum/decimation"):
                        margins = jax.vmap(
                            lambda b: belief_margins(
                                b[:V], domain_mask[:V]))(beliefs)
                        return jax.vmap(
                            lambda m, f: decimation_select(
                                m, f, elig, p_frac))(margins, frozen)

                newly = jax.lax.cond(
                    do, _on, lambda _: jnp.zeros_like(frozen), None)
                frozen2 = jnp.logical_or(frozen, newly)
                pin2 = jnp.where(newly, sel, pin)
                b_loc = frozen2.shape[0]
                froz_full = jnp.concatenate(
                    [frozen2, jnp.zeros((b_loc, 1), bool)], axis=1)
                pin_full = jnp.concatenate(
                    [pin2, jnp.zeros((b_loc, 1), jnp.int32)], axis=1)
                froz_e = froz_full[:, edge_var]         # (B, E)
                pin_e = pin_full[:, edge_var]
                clamp = jnp.where(
                    jnp.arange(D)[None, None, :] == pin_e[..., None],
                    0.0, BIG)
                q2 = jnp.where(froz_e[..., None],
                               clamp.astype(q2.dtype), q2)
                sel = jnp.where(frozen2, pin2, sel)
            else:
                frozen2, pin2 = frozen, pin
            # convergence delta AFTER the clamp (single-chip order)
            mask_e = domain_mask[edge_var]
            if E and (self.stability > 0 or self._telemetry_delta):
                delta_b = jnp.max(jnp.where(
                    mask_e[None], jnp.abs(q2 - q), 0.0), axis=(1, 2))
                delta = jax.lax.pmax(delta_b, "tp")
            else:
                delta = jnp.zeros((q.shape[0],), jnp.float32)
            return q2, r2, sel, delta, frozen2, pin2, pruned

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(
                P("dp", "tp"), P("dp", "tp"), P(),
                P("dp"), P("dp"), P(),
                P("tp"),
                [P("tp") for _ in self.buckets],
                P(), P(), P(),
                P("tp"),  # bnb plan leaves (empty list without bnb)
            ),
            out_specs=(P("dp", "tp"), P("dp", "tp"), P("dp"), P("dp"),
                       P("dp"), P("dp"), P("dp")),
            # the replication checker has no rule for pallas calls or
            # for the pruned sweep's lax.while_loop — disable it for
            # those programs (the specs above still shard correctly)
            check_vma=not (self._bnb_active
                           or (self.layout == "lane_major"
                               and self.use_pallas)),
        )
        def sharded(q, r, key, frozen, pin, cycle, edge_var, cubes,
                    var_costs, domain_mask, domain_size, plans):
            local_plans = [
                None if pl is None else PrunedPlan(
                    pl.digits[0], pl.cube_cells[0], pl.suffix_min[0],
                    pl.block, pl.n_blocks, pl.n_cells)
                for pl in plans]
            q2, r2, sel, delta, frozen2, pin2, pruned = local_step(
                q[:, 0], r[:, 0], key, frozen, pin, cycle,
                edge_var[0], [c[0] for c in cubes],
                var_costs, domain_mask, domain_size, local_plans)
            return (q2[:, None], r2[:, None], sel, delta,
                    frozen2, pin2, pruned)

        self._step = jax.jit(sharded)

    # -------------------------------------------------------------- run

    def _step_args(self, consts):
        """The constant tail of a ``_step`` call — layout subclasses
        carry different constants through the same run loop."""
        args = (consts["edge_var"], consts["cubes"],
                consts["var_costs"], consts["domain_mask"],
                consts["domain_size"])
        if self._features_on():
            args = args + (consts.get("bnb_plans", []),)
        return args

    def _dummy_feature_state(self):
        """Placeholder ``(frozen, pin)`` planes for bnb-only runs: the
        extended step signature carries them uniformly, the decimation
        branch never reads them."""
        return (jnp.zeros((self.B, self.V), dtype=bool),
                jnp.zeros((self.B, self.V), dtype=jnp.int32))

    def _decode_sel(self, sel_np: np.ndarray) -> np.ndarray:
        """Map the step's selection output to ORIGINAL variable order
        (identity here; the fused layout solves in degree-sorted
        order)."""
        return sel_np

    # ---------------------------------------------- mesh engine protocol

    #: telemetry flag: compute the in-step message delta even when
    #: stability convergence is off, so the residual plane reads it
    #: from the carry instead of re-walking the q planes
    _telemetry_delta = False
    #: per-flag compiled steps (stability<=0 only): toggling telemetry
    #: must hand back the EXACT prior program, not a rebuild
    _step_variants = None

    def _set_telemetry_delta(self, on: bool):
        """Pick the step variant for this run (called by the mixin
        before EVERY drive, both directions): with the stability rule
        active the step already computes the delta and both flags
        share one program; with ``stability<=0`` the two variants are
        built once each and cached, so a telemetry-off run after a
        telemetry-on run executes the original untouched program (the
        bit-exactness contract is about the program, not just the
        selections).  The delta reduce itself changes no
        message/selection arithmetic either way."""
        on = bool(on)
        if self.stability > 0:
            self._telemetry_delta = on
            return
        if self._step_variants is None:
            # the step built at __init__ is the flag-off variant
            self._step_variants = {self._telemetry_delta: self._step}
        if on not in self._step_variants:
            self._telemetry_delta = on
            self._build_step()
            self._step_variants[on] = self._step
        else:
            self._telemetry_delta = on
            self._step = self._step_variants[on]

    def enable_telemetry_delta(self):
        """Arm the in-step |Δq| reduce for a telemetry run (public
        alias of ``_set_telemetry_delta(True)``)."""
        self._set_telemetry_delta(True)

    def mesh_init(self, seed: int):
        """The engine carry: message state + on-device convergence
        bookkeeping (prev selection, SAME_COUNT streak)."""
        state = self._init_state()
        state.update({
            "key": jax.random.PRNGKey(seed),
            # -1 never equals an argmin index: the first cycle can
            # never count as stable, like the eager loop's prev_sel
            # = None warm-up
            "sel": jax.device_put(
                np.full((self.B, self.V), -1, dtype=np.int32),
                NamedSharding(self.mesh, P("dp"))),
            "same": jnp.int32(0),
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
        })
        if self._telemetry_delta:
            state["delta"] = jnp.float32(0)
        if self.decimation:
            dp_sh = NamedSharding(self.mesh, P("dp"))
            state["frozen"] = jax.device_put(
                np.zeros((self.B, self.V), dtype=bool), dp_sh)
            state["pin"] = jax.device_put(
                np.zeros((self.B, self.V), dtype=np.int32), dp_sh)
        if self._bnb_active:
            state["pruned"] = jnp.float32(0)
        return state

    def mesh_step(self, s):
        """One cycle, pure: the sharded step plus the SAME_COUNT-
        stability rule (selection unchanged across the WHOLE batch AND
        message delta below the stability threshold) evaluated on
        device — the exact arithmetic of the eager host loop."""
        key, sub = jax.random.split(s["key"])
        if self._features_on():
            frozen, pin = (s["frozen"], s["pin"]) if self.decimation \
                else self._dummy_feature_state()
            q, r, sel, delta, frozen2, pin2, pruned = self._step(
                s["q"], s["r"], sub, frozen, pin, s["cycle"],
                *self._step_args(self._consts()))
        else:
            q, r, sel, delta = self._step(
                s["q"], s["r"], sub, *self._step_args(self._consts()))
        stable = jnp.logical_and(
            jnp.all(sel == s["sel"]),
            jnp.max(delta) < jnp.float32(self.stability))
        same = jnp.where(stable, s["same"] + 1, jnp.int32(0))
        out = dict(s)
        out.update(q=q, r=r, key=key, sel=sel, same=same,
                   cycle=s["cycle"] + 1,
                   finished=same >= SAME_COUNT)
        if "delta" in s:
            out["delta"] = jnp.max(delta)
        if self.decimation:
            out["frozen"] = frozen2
            out["pin"] = pin2
        if self._bnb_active:
            out["pruned"] = jnp.mean(pruned)
        return out

    def mesh_residual(self, s_prev, s_next):
        """The telemetry residual plane: the step's own max|Δq|
        (carried as ``delta``), NaN before ``enable_telemetry_delta``
        armed it."""
        if "delta" not in s_next:
            return jnp.float32(jnp.nan)
        return s_next["delta"]

    def _cost_buckets(self):
        """(cubes, var_ids, valid) triples for the on-device cost: the
        MaxSum partition pads with BIG-filled cubes, so padded rows
        need the explicit mask.  Cubes ride the store dtype (the cost
        evaluator upcasts to f32 at its sums)."""
        store = self.policy.store_dtype
        return [(np.asarray(sb.cubes, dtype=store), sb.var_ids,
                 sb.var_ids[:, :, 0] < self.V)
                for sb in self.buckets]

    def _mesh_sel_device(self, state):
        """The selection in ORIGINAL variable order, on device (layout
        subclasses override to undo their solve-order permutation)."""
        return state["sel"]

    def _build_cost_fn(self, with_violations: bool = False):
        """On-device cost matching the single-chip solver's ``cost``
        (cubes at selection + unary costs); ``with_violations`` adds
        the telemetry conflict count (parallel/_mesh_cost.py)."""
        return build_mesh_cost(self.mesh, self.V, self._cost_buckets(),
                               self.var_costs, x_has_sink=False,
                               with_violations=with_violations)

    def _mesh_cost_input(self, state):
        return self._mesh_sel_device(state)

    def message_plane_stats(self):
        """MaxSum message traffic per cycle: every real edge carries a
        q (variable->factor) and an r (factor->variable) plane row of
        D values in the policy's store dtype, per restart instance —
        the layout-derived counts ``solve -m sharded`` reports instead
        of the old hardcoded zeros."""
        e_real = int(sum(
            int((sb.var_ids[:, :, 0] < self.V).sum()) * sb.arity
            for sb in self.buckets if sb.arity >= 1))
        msgs = 2 * e_real * self.B
        return {"msg_per_cycle": msgs,
                "bytes_per_cycle":
                    msgs * self.D * self.policy.store_itemsize}

    # ------------------------------------------------------------- runs

    def run(self, n_cycles: int, seed: int = 0,
            collect_cost_every: Optional[int] = None,
            collect_metrics: bool = False, spans: bool = False,
            chunk_size: Optional[int] = None,
            timeout: Optional[float] = None
            ) -> Tuple[np.ndarray, int]:
        """Run until SAME_COUNT-stable (same convergence rule as the
        single-chip solver: selection unchanged AND message delta below
        the stability threshold) or ``n_cycles``, in compiled chunks on
        device (one host sync per chunk, see
        ``engine/mesh_engine.py``).  ``collect_cost_every`` fills
        ``self.last_cost_trace`` from the on-device anytime buffer;
        ``collect_metrics`` fills ``self.last_cycle_metrics`` the same
        way (residual/flips/conflicts planes, zero extra host syncs)
        and ``spans`` records compile/execute spans + the HLO census.

        Returns ((B, V) selections, cycles run)."""
        return self._drive_mesh(
            self.mesh_init(seed), n_cycles,
            collect_cost_every=collect_cost_every,
            collect_metrics=collect_metrics, spans=spans,
            chunk_size=chunk_size, timeout=timeout)

    def run_eager(self, n_cycles: int, seed: int = 0
                  ) -> Tuple[np.ndarray, int]:
        """The pre-engine loop — one dispatch and one sel+delta
        device->host transfer per cycle.  Kept as the equivalence
        oracle for the chunked engine (bit-exactness tests) and the
        A/B leg of ``suite.py bench_mesh_dispatch``."""
        import time as _time

        t0 = _time.perf_counter()
        state, consts = self._device_put()
        q, r = state["q"], state["r"]
        args = self._step_args(consts)
        key = jax.random.PRNGKey(seed)
        prev_sel = None
        same = 0
        cycle = 0
        sel = None
        self.finished = False
        features = self._features_on()
        if features:
            dp_sh = NamedSharding(self.mesh, P("dp"))
            frozen = jax.device_put(
                np.zeros((self.B, self.V), dtype=bool), dp_sh)
            pin = jax.device_put(
                np.zeros((self.B, self.V), dtype=np.int32), dp_sh)
        while cycle < n_cycles:
            key, sub = jax.random.split(key)
            if features:
                q, r, sel, delta, frozen, pin, _pruned = self._step(
                    q, r, sub, frozen, pin, jnp.int32(cycle), *args)
            else:
                q, r, sel, delta = self._step(q, r, sub, *args)
            cycle += 1
            sel_h = np.asarray(jax.device_get(sel))
            delta_h = float(np.max(np.asarray(jax.device_get(delta))))
            if prev_sel is not None and \
                    np.array_equal(sel_h, prev_sel) and \
                    delta_h < self.stability:
                same += 1
                if same >= SAME_COUNT:
                    # may fire on the final cycle: still "finished"
                    self.finished = True
                    break
            else:
                same = 0
            prev_sel = sel_h
        self.last_run_stats = self._eager_stats(
            cycle, "FINISHED" if self.finished else "MAX_CYCLES", t0)
        return self._decode_sel(np.asarray(jax.device_get(sel))), cycle

    def step_once(self, seed: int = 0):
        """One sharded step (for compile-checking the multi-chip path)."""
        state, consts = self._device_put()
        args = self._step_args(consts)
        if self._features_on():
            frozen, pin = self._dummy_feature_state()
            out = self._step(state["q"], state["r"],
                             jax.random.PRNGKey(seed), frozen, pin,
                             jnp.int32(0), *args)
        else:
            out = self._step(state["q"], state["r"],
                             jax.random.PRNGKey(seed), *args)
        sel = out[2]
        jax.block_until_ready(sel)
        return self._decode_sel(np.asarray(jax.device_get(sel)))


class ShardedFusedMaxSum(ShardedMaxSum):
    """The fused var-sorted layout on the (dp, tp) mesh: per shard, ONE
    irregular op per cycle (the partner gather) plus the belief psum.

    The mesh form of :class:`~pydcop_tpu.algorithms.maxsum.\
MaxSumFusedSolver`: a factor's two endpoint slots always live on the
    factor's own shard (factors are partitioned, edges follow), so the
    partner permutation stays shard-LOCAL; every shard's slot table
    shares ONE global variable ordering bucketed by the max-over-shards
    local degree, so shapes are identical across shards and the
    per-variable partial sums are static reshape+reduce — assembled
    with a single ``psum`` over tp, exactly where the lane layout psums
    its scatter partials.

    N-ary graphs (PEAV/SECP shapes) use the same arity-bucketed slot
    tables as the single-chip fused solver: per (arity, position)
    bucket one shard-local static gather pulls that position's
    incoming messages out of slot space, the bucket's lane-major
    hypercube sweep emits all its messages, and one static assembly
    permutation lays them back into slots — zero scatters, and the
    partner traffic stays shard-local because a factor's slots always
    live on its own shard.  Requires factor arities >= 2 under the
    unroll threshold, like the single-chip fused solver.
    """

    def __init__(self, arrays: FactorGraphArrays, mesh,
                 damping: float = 0.5, damping_nodes: str = "vars",
                 stability: float = 0.1, noise: float = 0.0,
                 batch: int = 1, precision=None,
                 decimation_p: float = 0.0, decimation_every: int = 0,
                 bnb: bool = False):
        from ..ops.pallas_kernels import (NARY_FALLBACK_TEXT,
                                          nary_fast_eligible)

        if bnb:
            # loud rejection, never a silent downgrade: the fused mesh
            # layout's slot-assembly factor update has no pruned twin
            # (the lane/edge mesh layouts and every single-chip layout
            # do) — route bnb runs through layout lane_major/edge_major
            raise ValueError(
                "the fused mesh layout does not support bnb pruned "
                "reductions; use -p layout:lane_major or edge_major "
                "for branch-and-bound mesh runs")
        # binary buckets are unconditional (no hypercube unroll); the
        # shared (env-overridable) cell gate bounds only the n-ary
        # lane-major sweep — mirrors MaxSumFusedSolver.eligible
        if any(b.arity < 2
               or not nary_fast_eligible(arrays.max_domain, b.arity)
               for b in arrays.buckets):
            raise ValueError(
                "the fused mesh layout needs factor arities >= 2 — "
                "fold unary constraints into variable costs first "
                f"(filter_dcop) — with {NARY_FALLBACK_TEXT}")
        self._init_params(arrays, mesh, damping, damping_nodes,
                          stability, noise, batch, precision=precision,
                          decimation_p=decimation_p,
                          decimation_every=decimation_every)
        self.layout = "fused"
        self.use_pallas = False
        self._build_fused_shards(arrays)
        self._build_step()

    # ----------------------------------------------------- host layout

    def _build_fused_shards(self, arrays):
        V, D, tp = self.V, self.D, self.tp
        shard_buckets, edge_var, e_loc = _partition(arrays, tp)
        # kept for the on-device cost trace (the slot tables below are
        # message-passing transforms; cost evaluation reads raw cubes)
        self.buckets = shard_buckets
        self.var_costs = np.concatenate(
            [np.asarray(arrays.var_costs, dtype=np.float32),
             np.full((1, D), BIG, dtype=np.float32)])
        self._all_binary = all(sb.arity == 2 for sb in shard_buckets)

        # ONE global variable ordering: bucket by the max-over-shards
        # local degree, so every shard's slot table has the same shape
        # — the SAME layout helper as the single-chip fused solver
        # (their exact-equality contract depends on identical layouts)
        from ..algorithms.maxsum import degree_slot_layout

        deg_g = np.zeros((tp, V), dtype=np.int64)
        for g in range(tp):
            ev = edge_var[g]
            deg_g[g] = np.bincount(ev[ev < V], minlength=V)
        var_order, var_pos, kbuckets, slot_base, ep = \
            degree_slot_layout(deg_g.max(axis=0))

        # per-slot ORIGINAL variable (shared by all shards)
        slot_var = np.repeat(
            var_order, np.concatenate(
                [[k] * nv for _o, _v, nv, k in kbuckets]).astype(
                    np.int64)) if kbuckets else np.zeros(0, np.int64)

        # per-shard slot assignment: real edges grouped by variable in
        # local edge order, padded to the shared bucket widths
        slot_edge = np.full((tp, ep), -1, dtype=np.int64)
        slot_of_local = np.full((tp, e_loc), -1, dtype=np.int64)
        for g in range(tp):
            ev = edge_var[g]
            real = np.where(ev < V)[0]
            order = real[np.argsort(ev[real], kind="stable")]
            dg = deg_g[g]
            run_start = np.concatenate([[0], np.cumsum(dg)[:-1]])
            rank = np.arange(len(order), dtype=np.int64) - \
                np.repeat(run_start, dg)
            slots = slot_base[ev[order]] + rank
            slot_edge[g, slots] = order
            slot_of_local[g, order] = slots

        valid = slot_edge >= 0                       # (TP, EP)
        emask = (np.asarray(arrays.domain_mask)[slot_var].T[None]
                 & valid[:, None, :])                # (TP, D, EP)
        self.EP = ep
        self._kbuckets = kbuckets
        self._np = {
            "emask": emask,
            "var_costsT_sorted":
                np.asarray(arrays.var_costs).T[:, var_order]
                .astype(np.float32),
            "domain_maskT_sorted":
                np.asarray(arrays.domain_mask).T[:, var_order],
            "slot_dsize": np.maximum(
                np.asarray(arrays.domain_size)[slot_var], 1)
                .astype(np.float32),
            "var_pos": var_pos,
            # decimation constants, SORTED variable order: per-slot
            # sorted-variable owner (the freeze clamp's map) and the
            # per-sorted-variable domain size (freeze eligibility)
            "slot_sorted_var": np.repeat(
                np.arange(V), np.concatenate(
                    [[k] * nv for _o, _v, nv, k in kbuckets]).astype(
                        np.int64)).astype(np.int32) if kbuckets
            else np.zeros(0, np.int32),
            "dsize_sorted": np.asarray(
                arrays.domain_size)[var_order].astype(np.int32),
        }

        if not self._all_binary:
            # arity-bucketed slot tables (the n-ary form, mirroring the
            # single-chip fused solver): per (arity, position) bucket
            # ONE static gather reads that position's incoming
            # messages out of slot space; results come back in local
            # canonical edge order, so the assembly map is slot ->
            # local edge id (e_loc = the appended zeros column for
            # padding slots).  Dummy factors' edges have no slot; their
            # gather indices clip to 0 and their messages are never
            # assembled.  Zero scatters.
            pos_slots = []   # per bucket: (TP, arity, fmax)
            cubesT = []      # per bucket: (TP, D, ..., D, fmax)
            for sb in shard_buckets:
                a = sb.arity
                f = sb.cubes.shape[1]
                eids = sb.offset + np.arange(f * a).reshape(f, a)
                ps = np.maximum(
                    slot_of_local[:, eids], 0)       # (TP, f, a)
                pos_slots.append(np.transpose(ps, (0, 2, 1))
                                 .astype(np.int32).copy())
                cubesT.append(np.moveaxis(sb.cubes, 1, -1).copy())
            self._np["pos_slots"] = pos_slots
            self._np["cubesT"] = cubesT
            self._np["slot_src"] = np.where(
                valid, slot_edge, e_loc).astype(np.int32)
            return

        # binary-only: the single slot-aligned table.  Local canonical
        # partner: within each bucket block, edges 2i/2i+1 are the
        # factor's two endpoints (same for all shards)
        partner_local = np.empty(e_loc, dtype=np.int64)
        for sb in shard_buckets:
            f = sb.cubes.shape[1]
            rel = np.arange(2 * f, dtype=np.int64)
            partner_local[sb.offset + rel] = sb.offset + (rel ^ 1)

        partner_slot = np.zeros((tp, ep), dtype=np.int32)
        cube_slotT = np.zeros((tp, D, D, ep), dtype=np.float32)
        for g in range(tp):
            valid_g = valid[g]
            partner_slot[g, valid_g] = slot_of_local[
                g, partner_local[slot_edge[g, valid_g]]]
            # oriented cube slices written straight into this shard's
            # slot table (no dense per-edge temporary): pos 0 receives
            # over the cube's second axis (transpose), pos 1 over the
            # first — the same orientation rule as the single-chip
            # fused solver
            for sb in shard_buckets:
                f = sb.cubes.shape[1]
                # both sides put the advanced (slot) index FIRST:
                # shapes are (n, D_other, D_self)
                for pos, axes in ((0, (0, 2, 1)), (1, (0, 1, 2))):
                    les = sb.offset + 2 * np.arange(f) + pos
                    ss = slot_of_local[g, les]
                    ok = ss >= 0
                    cube_slotT[g, :, :, ss[ok]] = np.transpose(
                        sb.cubes[g][ok], axes)
        self._np["partner_slot"] = partner_slot
        self._np["cube_slotT"] = cube_slotT

    # ---------------------------------------------------------- device

    def _init_state(self):
        B = self.B
        n = self._np
        q0 = np.where(n["emask"], 0.0, BIG).astype(np.float32)
        q0 = np.broadcast_to(q0[None], (B,) + q0.shape).copy()
        sh = NamedSharding(self.mesh, P("dp", "tp"))
        return {"q": jax.device_put(q0, sh),
                "r": jax.device_put(np.zeros_like(q0), sh)}

    def _make_consts(self):
        mesh = self.mesh
        n = self._np
        store = self.policy.store_dtype
        tp_sh = NamedSharding(mesh, P("tp"))
        rep = NamedSharding(mesh, P())
        consts = {
            "emask": jax.device_put(n["emask"], tp_sh),
            "var_costsT_sorted": jax.device_put(
                jnp.asarray(n["var_costsT_sorted"], dtype=store), rep),
            "domain_maskT_sorted": jax.device_put(
                jnp.asarray(n["domain_maskT_sorted"]), rep),
            "slot_dsize": jax.device_put(
                jnp.asarray(n["slot_dsize"]), rep),
        }
        if self.decimation:
            consts["slot_sorted_var"] = jax.device_put(
                jnp.asarray(n["slot_sorted_var"]), rep)
            consts["dsize_sorted"] = jax.device_put(
                jnp.asarray(n["dsize_sorted"]), rep)
        if self._all_binary:
            consts["partner_slot"] = jax.device_put(
                n["partner_slot"], tp_sh)
            consts["cube_slotT"] = jax.device_put(
                np.asarray(n["cube_slotT"], dtype=store), tp_sh)
        else:
            consts["pos_slots"] = [
                jax.device_put(ps, tp_sh) for ps in n["pos_slots"]]
            consts["cubesT"] = [
                jax.device_put(np.asarray(c, dtype=store), tp_sh)
                for c in n["cubesT"]]
            consts["slot_src"] = jax.device_put(n["slot_src"], tp_sh)
        return consts

    def _mesh_sel_device(self, state):
        # the fused layout solves in degree-sorted order; the cost
        # trace evaluates raw cubes, which index ORIGINAL variables
        return state["sel"][:, jnp.asarray(self._np["var_pos"])]

    def _step_args(self, consts):
        if self._all_binary:
            args = (consts["partner_slot"], consts["cube_slotT"],
                    consts["emask"], consts["var_costsT_sorted"],
                    consts["domain_maskT_sorted"], consts["slot_dsize"])
        else:
            args = (consts["pos_slots"], consts["cubesT"],
                    consts["slot_src"], consts["emask"],
                    consts["var_costsT_sorted"],
                    consts["domain_maskT_sorted"],
                    consts["slot_dsize"])
        if self._features_on():  # fused: decimation only (bnb rejected)
            args = args + (consts["slot_sorted_var"],
                           consts["dsize_sorted"])
        return args

    def _decode_sel(self, sel_np: np.ndarray) -> np.ndarray:
        return sel_np[:, self._np["var_pos"]]

    # ------------------------------------------------------------ step

    def _fused_cycle_core(self, q1, r1, k1, new_r, emask, vcT, dsize):
        """The variable-update body shared by ALL fused step variants
        (binary/n-ary, plain/decimated): masking, damping, the static
        per-bucket partial sums + one psum, mean normalization, noise.
        Returns ``(q_new, new_r, belief)``."""
        D = self.D
        damping, damping_nodes = self.damping, self.damping_nodes
        noise = self.noise
        kbuckets = self._kbuckets

        new_r = jnp.where(emask, new_r, 0.0)
        if damping_nodes in ("factors", "both") and damping > 0:
            new_r = damping * r1 + (1 - damping) * new_r
        # static per-bucket partial sums -> one psum over tp
        parts = []
        for s_off, v_off, nv, k in kbuckets:
            parts.append(new_r[:, s_off:s_off + nv * k]
                         .reshape(D, nv, k).sum(axis=2))
        partial_sum = parts[0] if len(parts) == 1 else \
            jnp.concatenate(parts, axis=1)       # (D, V)
        belief = vcT + jax.lax.psum(partial_sum, "tp")
        blocks = []
        for s_off, v_off, nv, k in kbuckets:
            blk = new_r[:, s_off:s_off + nv * k] \
                .reshape(D, nv, k)
            blocks.append(
                (belief[:, v_off:v_off + nv, None] - blk)
                .reshape(D, nv * k))
        q_new = blocks[0] if len(blocks) == 1 else \
            jnp.concatenate(blocks, axis=1)
        mean = (jnp.sum(jnp.where(emask, q_new, 0.0), axis=0)
                / dsize)
        q_new = q_new - mean[None, :]
        if noise > 0:
            tp_idx = jax.lax.axis_index("tp")
            sub = jax.random.fold_in(k1, tp_idx)
            q_new = q_new + noise * jax.random.uniform(
                sub, q_new.shape)
        if damping_nodes in ("vars", "both") and damping > 0:
            q_new = damping * q1 + (1 - damping) * q_new
        q_new = jnp.where(emask, q_new, BIG)
        return q_new, new_r, belief

    def _fused_select(self, belief, dmT):
        return jnp.argmin(
            jnp.where(dmT, belief, jnp.asarray(SENTINEL, belief.dtype)),
            axis=0)

    def _fused_cycle_tail(self, q1, r1, k1, new_r, emask, vcT, dmT,
                          dsize):
        """Everything after the factor update — shared by the binary
        (slot-aligned single-gather) and n-ary (arity-bucketed) factor
        updates so the two modes can never diverge on variable-update
        or convergence semantics."""
        q_new, new_r, belief = self._fused_cycle_core(
            q1, r1, k1, new_r, emask, vcT, dsize)
        sel = self._fused_select(belief, dmT)
        if self.EP and (self.stability > 0 or self._telemetry_delta):
            delta = jax.lax.pmax(jnp.max(jnp.where(
                emask, jnp.abs(q_new - q1), 0.0)), "tp")
        else:
            delta = jnp.float32(0)
        return q_new, new_r, sel, delta

    def _fused_cycle_tail_ext(self, q1, r1, k1, new_r, emask, vcT,
                              dmT, dsize):
        """The decimated variant's per-instance tail: same core, but
        the convergence delta moves AFTER the freeze clamp (computed
        in ``_fused_features_tail``) and the belief is returned for
        the margin computation."""
        q_new, new_r, belief = self._fused_cycle_core(
            q1, r1, k1, new_r, emask, vcT, dsize)
        return q_new, new_r, self._fused_select(belief, dmT), belief

    def _fused_features_tail(self, q_old, q2, r2, sel, beliefs,
                             frozen, pin, cycle, emask, dmT,
                             slot_sorted_var, dsize_sorted):
        """Post-vmap decimation for the fused mesh layouts: freeze
        events in a scalar ``lax.cond`` (skipped entirely off-event),
        the per-slot clamp through the sorted-owner map, and the
        convergence delta on the clamped messages — all in SORTED
        variable order, like the carry."""
        D = self.D
        do = ((cycle + 1) % self.decimation_every) == 0
        elig = dsize_sorted > 1

        def _on(_):
            with jax.named_scope("maxsum/decimation"):
                margins = jax.vmap(
                    lambda b: belief_margins(b, dmT, axis=0))(beliefs)
                return jax.vmap(
                    lambda m, f: decimation_select(
                        m, f, elig, self.decimation_p))(margins,
                                                        frozen)

        newly = jax.lax.cond(
            do, _on, lambda _: jnp.zeros_like(frozen), None)
        frozen2 = jnp.logical_or(frozen, newly)
        pin2 = jnp.where(newly, sel, pin)
        froz_slot = frozen2[:, slot_sorted_var]         # (B, EP)
        pin_slot = pin2[:, slot_sorted_var]
        clamp = jnp.where(
            jnp.arange(D)[None, :, None] == pin_slot[:, None, :],
            0.0, BIG)
        q2 = jnp.where(froz_slot[:, None, :],
                       clamp.astype(q2.dtype), q2)
        sel = jnp.where(frozen2, pin2, sel)
        if self.EP and (self.stability > 0 or self._telemetry_delta):
            delta = jax.lax.pmax(jnp.max(jnp.where(
                emask[None], jnp.abs(q2 - q_old), 0.0),
                axis=(1, 2)), "tp")
        else:
            delta = jnp.zeros((q2.shape[0],), jnp.float32)
        pruned = jnp.zeros((q2.shape[0],), jnp.float32)
        return q2, r2, sel, delta, frozen2, pin2, pruned

    def _keys_for(self, key, n):
        """Per-instance keys, differing across dp shards (parity with
        ShardedMaxSum's stream layout)."""
        dp_idx = jax.lax.axis_index("dp")
        return jax.vmap(
            lambda i: jax.random.fold_in(
                jax.random.fold_in(key, dp_idx), i))(jnp.arange(n))

    def _build_step(self):
        if self._all_binary:
            self._build_step_binary()
        else:
            self._build_step_nary()

    def _build_step_binary(self):
        if self._features_on():  # fused: decimation only (bnb rejected)
            self._build_step_binary_features()
            return

        def local_step(q, r, key, partner, cube, emask, vcT, dmT,
                       dsize):
            # q, r: (B_loc, D, EP) shard-local var-sorted slots
            def one(q1, r1, k1):
                q_part = q1[:, partner]          # the ONE local gather
                new_r = jnp.min(cube + q_part[:, None, :], axis=0)
                return self._fused_cycle_tail(
                    q1, r1, k1, new_r, emask, vcT, dmT, dsize)

            keys = self._keys_for(key, q.shape[0])
            return jax.vmap(one)(q, r, keys)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(P("dp", "tp"), P("dp", "tp"), P(),
                      P("tp"), P("tp"), P("tp"), P(), P(), P()),
            out_specs=(P("dp", "tp"), P("dp", "tp"), P("dp"), P("dp")),
        )
        def sharded(q, r, key, partner, cube, emask, vcT, dmT, dsize):
            q2, r2, sel, delta = local_step(
                q[:, 0], r[:, 0], key, partner[0], cube[0], emask[0],
                vcT, dmT, dsize)
            return q2[:, None], r2[:, None], sel, delta

        self._step = jax.jit(sharded)

    def _build_step_binary_features(self):
        """The decimated binary fused step: the identical slot-aligned
        factor update, then the shared features tail (freeze events,
        per-slot clamp, post-clamp delta) — signature extended by
        ``(frozen, pin, cycle)`` in and ``(frozen, pin, pruned)``
        out, like the lane/edge mesh layouts."""
        def local_step(q, r, key, frozen, pin, cycle, partner, cube,
                       emask, vcT, dmT, dsize, ssv, dss):
            def one(q1, r1, k1):
                q_part = q1[:, partner]
                new_r = jnp.min(cube + q_part[:, None, :], axis=0)
                return self._fused_cycle_tail_ext(
                    q1, r1, k1, new_r, emask, vcT, dmT, dsize)

            keys = self._keys_for(key, q.shape[0])
            q2, r2, sel, beliefs = jax.vmap(one)(q, r, keys)
            return self._fused_features_tail(
                q, q2, r2, sel, beliefs, frozen, pin, cycle, emask,
                dmT, ssv, dss)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(P("dp", "tp"), P("dp", "tp"), P(),
                      P("dp"), P("dp"), P(),
                      P("tp"), P("tp"), P("tp"), P(), P(), P(),
                      P(), P()),
            out_specs=(P("dp", "tp"), P("dp", "tp"), P("dp"), P("dp"),
                       P("dp"), P("dp"), P("dp")),
        )
        def sharded(q, r, key, frozen, pin, cycle, partner, cube,
                    emask, vcT, dmT, dsize, ssv, dss):
            q2, r2, sel, delta, frozen2, pin2, pruned = local_step(
                q[:, 0], r[:, 0], key, frozen, pin, cycle,
                partner[0], cube[0], emask[0], vcT, dmT, dsize,
                ssv, dss)
            return (q2[:, None], r2[:, None], sel, delta,
                    frozen2, pin2, pruned)

        self._step = jax.jit(sharded)

    def _build_step_nary(self):
        if self._features_on():
            self._build_step_nary_features()
            return

        from ..ops.pallas_kernels import factor_messages_lane_major

        D = self.D
        nb = len(self._np["pos_slots"])

        def local_step(q, r, key, pos_slots, cubesT, slot_src, emask,
                       vcT, dmT, dsize):
            def one(q1, r1, k1):
                # one static gather per (arity, position) bucket, the
                # shared lane-major hypercube sweep, one assembly
                # permutation back to slots — zero scatters
                blocks = []
                for ps, cu in zip(pos_slots, cubesT):
                    a = cu.ndim - 1
                    f = cu.shape[-1]
                    q_in = [q1[:, ps[p]] for p in range(a)]
                    msgs = factor_messages_lane_major(cu, q_in, a)
                    blocks.append(jnp.stack(msgs, axis=2)
                                  .reshape(D, a * f))
                m = blocks[0] if len(blocks) == 1 else \
                    jnp.concatenate(blocks, axis=1)
                m = jnp.concatenate(
                    [m, jnp.zeros((D, 1), m.dtype)], axis=1)
                new_r = m[:, slot_src]
                return self._fused_cycle_tail(
                    q1, r1, k1, new_r, emask, vcT, dmT, dsize)

            keys = self._keys_for(key, q.shape[0])
            return jax.vmap(one)(q, r, keys)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(P("dp", "tp"), P("dp", "tp"), P(),
                      [P("tp")] * nb, [P("tp")] * nb, P("tp"),
                      P("tp"), P(), P(), P()),
            out_specs=(P("dp", "tp"), P("dp", "tp"), P("dp"), P("dp")),
        )
        def sharded(q, r, key, pos_slots, cubesT, slot_src, emask,
                    vcT, dmT, dsize):
            q2, r2, sel, delta = local_step(
                q[:, 0], r[:, 0], key,
                [p[0] for p in pos_slots], [c[0] for c in cubesT],
                slot_src[0], emask[0], vcT, dmT, dsize)
            return q2[:, None], r2[:, None], sel, delta

        self._step = jax.jit(sharded)

    def _build_step_nary_features(self):
        """The decimated n-ary fused step: identical arity-bucketed
        slot-space factor update, then the shared features tail."""
        from ..ops.pallas_kernels import factor_messages_lane_major

        D = self.D
        nb = len(self._np["pos_slots"])

        def local_step(q, r, key, frozen, pin, cycle, pos_slots,
                       cubesT, slot_src, emask, vcT, dmT, dsize, ssv,
                       dss):
            def one(q1, r1, k1):
                blocks = []
                for ps, cu in zip(pos_slots, cubesT):
                    a = cu.ndim - 1
                    f = cu.shape[-1]
                    q_in = [q1[:, ps[p]] for p in range(a)]
                    msgs = factor_messages_lane_major(cu, q_in, a)
                    blocks.append(jnp.stack(msgs, axis=2)
                                  .reshape(D, a * f))
                m = blocks[0] if len(blocks) == 1 else \
                    jnp.concatenate(blocks, axis=1)
                m = jnp.concatenate(
                    [m, jnp.zeros((D, 1), m.dtype)], axis=1)
                new_r = m[:, slot_src]
                return self._fused_cycle_tail_ext(
                    q1, r1, k1, new_r, emask, vcT, dmT, dsize)

            keys = self._keys_for(key, q.shape[0])
            q2, r2, sel, beliefs = jax.vmap(one)(q, r, keys)
            return self._fused_features_tail(
                q, q2, r2, sel, beliefs, frozen, pin, cycle, emask,
                dmT, ssv, dss)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(P("dp", "tp"), P("dp", "tp"), P(),
                      P("dp"), P("dp"), P(),
                      [P("tp")] * nb, [P("tp")] * nb, P("tp"),
                      P("tp"), P(), P(), P(), P(), P()),
            out_specs=(P("dp", "tp"), P("dp", "tp"), P("dp"), P("dp"),
                       P("dp"), P("dp"), P("dp")),
        )
        def sharded(q, r, key, frozen, pin, cycle, pos_slots, cubesT,
                    slot_src, emask, vcT, dmT, dsize, ssv, dss):
            q2, r2, sel, delta, frozen2, pin2, pruned = local_step(
                q[:, 0], r[:, 0], key, frozen, pin, cycle,
                [p[0] for p in pos_slots], [c[0] for c in cubesT],
                slot_src[0], emask[0], vcT, dmT, dsize, ssv, dss)
            return (q2[:, None], r2[:, None], sel, delta,
                    frozen2, pin2, pruned)

        self._step = jax.jit(sharded)


class ShardedAMaxSum(ShardedMaxSum):
    """Asynchronous MaxSum over the mesh: each cycle an independent
    random subset of shard-local edges refreshes its messages (the
    stochastic-activation model of the single-chip ``AMaxSumSolver``),
    everything else rides :class:`ShardedMaxSum` unchanged."""

    def __init__(self, arrays: FactorGraphArrays, mesh,
                 activation: float = 0.7, **kwargs):
        if float(kwargs.get("decimation_p", 0) or 0) != 0:
            # the same loud rejection as the single-chip AMaxSumSolver:
            # the stochastic activation mask below re-admits PRE-freeze
            # messages on non-activated edges, silently undoing the
            # frozen-variable clamp decimation depends on
            raise ValueError(
                "amaxsum does not support decimation: stochastic edge "
                "activation re-admits pre-freeze messages, undoing the "
                "frozen-variable clamp; use maxsum for decimated runs")
        self.activation = float(activation)
        super().__init__(arrays, mesh, **kwargs)

    def _build_step(self):
        super()._build_step()
        base_step = self._step
        activation = self.activation
        mesh = self.mesh

        @partial(
            shard_map, mesh=mesh,
            in_specs=(P("dp", "tp"), P("dp", "tp"), P(),
                      P("dp", "tp"), P("dp", "tp")),
            out_specs=(P("dp", "tp"), P("dp", "tp")),
        )
        def mask_update(q_new, r_new, key, q_old, r_old):
            # per-(dp, tp) shard streams
            dp_idx = jax.lax.axis_index("dp")
            tp_idx = jax.lax.axis_index("tp")
            sub = jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(key, 1), dp_idx),
                tp_idx)
            k_q, k_r = jax.random.split(sub)
            act_q = jax.random.uniform(k_q, q_new.shape[:-1]) \
                < activation
            act_r = jax.random.uniform(k_r, r_new.shape[:-1]) \
                < activation
            q = jnp.where(act_q[..., None], q_new, q_old)
            r = jnp.where(act_r[..., None], r_new, r_old)
            return q, r

        mask_update = jax.jit(mask_update)

        if self._features_on():
            # bnb only (decimation is rejected at __init__): the
            # extended signature flows through, the activation mask
            # still touches just the message planes
            def step(q, r, key, frozen, pin, cycle, *args):
                (q_new, r_new, sel, delta, frozen2, pin2,
                 pruned) = base_step(q, r, key, frozen, pin, cycle,
                                     *args)
                q2, r2 = mask_update(q_new, r_new, key, q, r)
                return q2, r2, sel, delta, frozen2, pin2, pruned
        else:
            def step(q, r, key, *args):
                q_new, r_new, sel, delta = base_step(q, r, key, *args)
                q2, r2 = mask_update(q_new, r_new, key, q, r)
                return q2, r2, sel, delta

        self._step = step


class ShardedDynamicMaxSum(ShardedMaxSum):
    """The mesh path for maxsum_dynamic: MaxSum over (dp, tp) with
    host-swappable factor tables.

    Mirrors the single-chip :class:`~pydcop_tpu.algorithms.\
maxsum_dynamic.DynamicMaxSumSolver` (reference maxsum_dynamic.py:40-186):
    the sharded cost cubes are session state the HOST can rewrite
    between steps — ``change_factor_function`` swaps one factor's
    table in place on its owning tp shard while the message arrays
    (q, r) are preserved, so belief propagation continues through the
    dynamics instead of restarting.  The compiled sharded step is
    reused unchanged across swaps (same trick as the single-chip
    solver's cubes-in-state pytree).

    Drive it as a session::

        sdm.start(seed)
        sdm.step_cycles(5)
        sdm.change_factor_function("c3", new_constraint)
        sel = sdm.step_cycles(5)
    """

    def __init__(self, arrays: FactorGraphArrays, mesh, **kwargs):
        if kwargs.get("bnb"):
            # same loud rejection as the single-chip dynamic solver:
            # bnb plans are build-time constants of the cube CONTENTS
            # and this class swaps cubes between steps — a swap would
            # leave the plans silently stale
            raise ValueError(
                "maxsum_dynamic does not support bnb: pruned-reduction "
                "plans are build-time cube constants and factor tables "
                "are host-swappable here; use the static maxsum solver")
        if float(kwargs.get("decimation_p", 0) or 0) != 0:
            # the session driver (step_cycles) deliberately keeps the
            # historical 4-output step; a freeze plane across host
            # cube swaps would also pin variables against a problem
            # that no longer exists
            raise ValueError(
                "maxsum_dynamic does not support decimation: frozen "
                "variables would stay pinned across host factor "
                "swaps; use the static maxsum solver for decimated "
                "runs")
        super().__init__(arrays, mesh, **kwargs)
        self.arrays = arrays
        # factor name -> (bucket index, bucket row, tp shard, shard row)
        # (the partition is round-robin: bucket row i lands on shard
        # i % tp at local row i // tp, see _round_robin)
        self._factor_pos = {}
        for b_idx, b in enumerate(arrays.buckets):
            for i, f_id in enumerate(b.factor_ids):
                self._factor_pos[arrays.factor_names[int(f_id)]] = (
                    b_idx, i, i % self.tp, i // self.tp)
        self._session = None

    # -------------------------------------------------------- session

    def start(self, seed: int = 0):
        state, consts = self._device_put()
        self._session = {
            "q": state["q"], "r": state["r"],
            "consts": consts,
            "key": jax.random.PRNGKey(seed),
            "sel": None,
        }
        return self

    def step_cycles(self, n: int = 1) -> np.ndarray:
        """Advance ``n`` sharded cycles; returns the (B, V) selections."""
        s = self._session
        if s is None:
            raise RuntimeError("call start() first")
        c = s["consts"]
        args = self._step_args(c)
        for _ in range(n):
            s["key"], sub = jax.random.split(s["key"])
            s["q"], s["r"], s["sel"], _delta = self._step(
                s["q"], s["r"], sub, *args)
        return np.asarray(jax.device_get(s["sel"]))

    # ---------------------------------------------------- host dynamics

    def change_factor_function(self, factor_name: str, constraint):
        """Swap one factor's cost function, dimensions unchanged —
        the update touches exactly the owning tp shard's row of the
        sharded cube stack (reference maxsum_dynamic.py:40-110)."""
        from ..graphs.arrays import _padded_cube

        if self._session is None:
            raise RuntimeError("call start() first")
        try:
            b_idx, row, g, loc = self._factor_pos[factor_name]
        except KeyError:
            raise KeyError(f"unknown factor {factor_name!r}")
        bucket = self.arrays.buckets[b_idx]
        if constraint.arity != bucket.arity:
            raise ValueError(
                f"change_factor_function: factor {factor_name!r} has "
                f"arity {bucket.arity}, new constraint has "
                f"{constraint.arity}; dimension changes need a rebuild")
        expect = [self.arrays.var_names[int(v)]
                  for v in bucket.var_ids[row]]
        got = [v.name for v in constraint.dimensions]
        if expect != got:
            raise ValueError(
                f"change_factor_function: factor {factor_name!r} scope "
                f"is {expect}, new constraint scope is {got}; dimension "
                f"changes need a rebuild")
        cube = _padded_cube(constraint, self.D, self.arrays.sign)
        # rewrite the owning shard's row on the HOST copy and re-place
        # it with the same P("tp") sharding (an eager scatter on the
        # explicitly-sharded device array would need a mesh context)
        sb = self.buckets[b_idx]
        sb.cubes[g, loc] = cube
        cubes = list(self._session["consts"]["cubes"])
        cubes[b_idx] = jax.device_put(
            sb.cubes, NamedSharding(self.mesh, P("tp")))
        self._session["consts"]["cubes"] = cubes
        # the device-constant cache, the cost evaluator AND the mesh
        # engine's compiled chunks (which closure-captured the consts
        # at trace time) all hold stale cubes: rebuild lazily
        self._invalidate_mesh_cache()

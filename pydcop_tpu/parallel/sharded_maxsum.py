"""Multi-chip MaxSum: dp x tp sharded step over a jax.sharding.Mesh.

This is the framework's "distributed communication backend" for the data
plane (SURVEY.md §2.8): where the reference scales out by placing agent
processes on machines and POSTing JSON messages over HTTP
(pydcop/infrastructure/communication.py:313-441), the TPU framework
shards the *stacked message arrays* over a device mesh:

* ``dp`` (data-parallel) axis — independent problem instances (the batch
  dimension of BASELINE config 5),
* ``tp`` (tensor-parallel) axis — factors of one instance, partitioned
  across devices; the variable update's segment-sum over incoming
  messages becomes a per-device partial sum + ``psum`` over ``tp`` — the
  XLA collective rides ICI, replacing the reference's network plane.

The factor partition is computed host-side (round-robin per arity bucket,
padded with inert dummy factors so every shard has identical static
shapes); dummy edges point at a sink variable row which every reduction
masks out.
"""

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..graphs.arrays import BIG, FactorGraphArrays
from ..ops.kernels import factor_messages

SAME_COUNT = 4


@dataclass
class _ShardedBucket:
    arity: int
    cubes: np.ndarray      # (TP, F, D, ..., D)
    edge_ids: np.ndarray   # (TP, F, arity) — local edge ids
    var_ids: np.ndarray    # (TP, F, arity) — global var ids (V = sink)


def _partition(arrays: FactorGraphArrays, tp: int):
    """Split factors across tp shards; every shard gets identical static
    shapes (padded with dummy factors)."""
    D = arrays.max_domain
    V = arrays.n_vars
    shard_buckets: List[_ShardedBucket] = []
    # per-shard local edge counter
    edge_count = [0] * tp
    # collect (bucket, shard) -> list of (factor local slot data)
    for b in arrays.buckets:
        a = b.arity
        n = b.cubes.shape[0]
        groups = [list(range(g, n, tp)) for g in range(tp)]
        fmax = max(len(g) for g in groups) if groups else 0
        cubes = np.full((tp, fmax) + (D,) * a, BIG, dtype=np.float32)
        edge_ids = np.zeros((tp, fmax, a), dtype=np.int32)
        var_ids = np.full((tp, fmax, a), V, dtype=np.int32)
        for g in range(tp):
            for slot, fi in enumerate(groups[g]):
                cubes[g, slot] = b.cubes[fi]
                var_ids[g, slot] = b.var_ids[fi]
            # assign local edge ids for every slot (incl. dummies)
            for slot in range(fmax):
                for p in range(a):
                    edge_ids[g, slot, p] = edge_count[g]
                    edge_count[g] += 1
        shard_buckets.append(_ShardedBucket(a, cubes, edge_ids, var_ids))
    e_loc = max(edge_count) if edge_count else 0
    # edge_var per shard: (TP, E_loc)
    edge_var = np.full((tp, e_loc), V, dtype=np.int32)
    for sb in shard_buckets:
        a = sb.arity
        for g in range(tp):
            for slot in range(sb.cubes.shape[1]):
                for p in range(a):
                    edge_var[g, sb.edge_ids[g, slot, p]] = \
                        sb.var_ids[g, slot, p]
    return shard_buckets, edge_var, e_loc


class ShardedMaxSum:
    """MaxSum over a (dp, tp) mesh.

    ``cost_cubes_batch`` may carry a leading batch axis (B,) of
    per-instance cost-table variations sharing the topology; B must be a
    multiple of the mesh's dp size.
    """

    def __init__(self, arrays: FactorGraphArrays, mesh,
                 damping: float = 0.5, batch: int = 1):
        self.mesh = mesh
        self.tp = mesh.shape["tp"]
        self.dp = mesh.shape["dp"]
        self.damping = float(damping)
        self.V = arrays.n_vars
        self.D = arrays.max_domain
        if batch % self.dp != 0:
            raise ValueError(
                f"batch {batch} must be a multiple of dp={self.dp}")
        self.B = batch

        shard_buckets, edge_var, e_loc = _partition(arrays, self.tp)
        self.E_loc = e_loc
        self.buckets = shard_buckets
        self.edge_var = edge_var                        # (TP, E_loc)

        vc = np.concatenate(
            [arrays.var_costs,
             np.full((1, self.D), BIG, dtype=np.float32)])
        self.var_costs = vc                             # (V+1, D)
        dm = np.concatenate(
            [arrays.domain_mask, np.zeros((1, self.D), dtype=bool)])
        self.domain_mask = dm
        ds = np.concatenate(
            [arrays.domain_size, np.ones((1,), dtype=np.int32)])
        self.domain_size = ds

        self._build_step()

    def _device_put(self):
        """Shard the state and constants onto the mesh."""
        from jax.sharding import NamedSharding

        B, TP, E, D = self.B, self.tp, self.E_loc, self.D
        mesh = self.mesh
        mask_e = self.domain_mask[self.edge_var]        # (TP, E, D)
        q0 = np.where(mask_e, 0.0, BIG).astype(np.float32)
        q0 = np.broadcast_to(q0[None], (B, TP, E, D)).copy()
        sh = NamedSharding(mesh, P("dp", "tp"))
        q = jax.device_put(q0, sh)
        consts = {
            "edge_var": jax.device_put(
                self.edge_var, NamedSharding(mesh, P("tp"))),
            "cubes": [
                jax.device_put(sb.cubes, NamedSharding(mesh, P("tp")))
                for sb in self.buckets
            ],
            "edge_ids": [
                jax.device_put(sb.edge_ids, NamedSharding(mesh, P("tp")))
                for sb in self.buckets
            ],
            "var_costs": jax.device_put(
                jnp.asarray(self.var_costs),
                NamedSharding(mesh, P())),
            "domain_mask": jax.device_put(
                jnp.asarray(self.domain_mask), NamedSharding(mesh, P())),
            "domain_size": jax.device_put(
                jnp.asarray(self.domain_size), NamedSharding(mesh, P())),
        }
        return q, consts

    def _build_step(self):
        V, D, E = self.V, self.D, self.E_loc
        damping = self.damping
        arities = [sb.arity for sb in self.buckets]

        def local_step(q, edge_var, cubes, edge_ids, var_costs,
                       domain_mask, domain_size):
            # q: (B_loc, E, D); edge_var: (E,); cubes[i]: (F, D..)
            # factor->var messages (new_r) are recomputed from q each
            # step, never carried: damping applies on the var->factor
            # side only, matching the single-chip solver
            def one(q1):
                new_r = jnp.zeros((E, D), dtype=q1.dtype)
                for a, cu, ei in zip(arities, cubes, edge_ids):
                    if a == 0:
                        continue
                    q_in = [q1[ei[:, p]] for p in range(a)]
                    msgs = factor_messages(cu, q_in)
                    for p in range(a):
                        new_r = new_r.at[ei[:, p]].set(msgs[p])
                partial_sum = jax.ops.segment_sum(
                    new_r, edge_var, num_segments=V + 1)
                sum_r = jax.lax.psum(partial_sum, "tp")
                belief = var_costs + sum_r
                q_new = belief[edge_var] - new_r
                mask_e = domain_mask[edge_var]
                mean = (jnp.sum(jnp.where(mask_e, q_new, 0.0), axis=1)
                        / domain_size[edge_var])
                q_new = q_new - mean[:, None]
                q_new = damping * q1 + (1 - damping) * q_new
                q_new = jnp.where(mask_e, q_new, BIG)
                sel = jnp.argmin(
                    jnp.where(domain_mask[:V], belief[:V], BIG * 2),
                    axis=-1)
                return q_new, sel

            return jax.vmap(one)(q)

        @partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(
                P("dp", "tp"), P("tp"),
                [P("tp") for _ in self.buckets],
                [P("tp") for _ in self.buckets],
                P(), P(), P(),
            ),
            out_specs=(P("dp", "tp"), P("dp")),
        )
        def sharded(q, edge_var, cubes, edge_ids, var_costs,
                    domain_mask, domain_size):
            # local blocks: q (B_loc, 1, E, D); squeeze the tp axis
            q_l = q[:, 0]
            cubes_l = [c[0] for c in cubes]
            eids_l = [e[0] for e in edge_ids]
            q2, sel = local_step(
                q_l, edge_var[0], cubes_l, eids_l,
                var_costs, domain_mask, domain_size)
            return q2[:, None], sel

        self._step = jax.jit(sharded)

    def run(self, n_cycles: int, tol: float = 1e-2
            ) -> Tuple[np.ndarray, int]:
        """Run up to ``n_cycles``, returning ((B, V) selections, cycles)."""
        q, consts = self._device_put()
        args = (consts["edge_var"], consts["cubes"], consts["edge_ids"],
                consts["var_costs"], consts["domain_mask"],
                consts["domain_size"])
        prev_sel = None
        same = 0
        cycle = 0
        sel = None
        while cycle < n_cycles:
            q, sel = self._step(q, *args)
            cycle += 1
            if cycle % 8 == 0 or cycle == n_cycles:
                sel_h = np.asarray(jax.device_get(sel))
                if prev_sel is not None and np.array_equal(sel_h, prev_sel):
                    same += 1
                    if same >= SAME_COUNT:
                        break
                else:
                    same = 0
                prev_sel = sel_h
        return np.asarray(jax.device_get(sel)), cycle

    def step_once(self):
        """One sharded step (for compile-checking the multi-chip path)."""
        q, consts = self._device_put()
        args = (consts["edge_var"], consts["cubes"], consts["edge_ids"],
                consts["var_costs"], consts["domain_mask"],
                consts["domain_size"])
        q, sel = self._step(q, *args)
        jax.block_until_ready(sel)
        return np.asarray(jax.device_get(sel))

"""Multi-chip local search: dp x tp sharded DSA over a jax.sharding.Mesh.

Companion of :mod:`sharded_maxsum` for the local-search family
(SURVEY.md §2.8): constraints are partitioned across the ``tp`` axis;
each device computes its shard's contribution to the per-variable
candidate-cost matrix, a ``psum`` over ``tp`` assembles the full
``(V, D)`` matrix (the collective rides ICI), and the DSA-B decision —
move to the best value with probability p when it improves — runs
replicated per device on the small reduced state.  ``dp`` shards
independent problem instances.

This is the scale-out story for the 10k-agent grid configs
(BASELINE.md #4): the expensive part (constraint-slice enumeration,
O(C * D * arity)) is tp-sharded; the per-variable decision is O(V * D).
"""

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from ._mesh_cost import build_mesh_cost
from ..engine._cache import enable_persistent_cache
from ..engine.mesh_engine import MeshSolverMixin
from ..graphs.arrays import SENTINEL, HypergraphArrays
from ..ops.kernels import bucket_cost, candidate_costs
from ..ops.precision import resolve as resolve_precision


def _value_plane_stats(solver, msgs_per_edge: int = 1):
    """Per-cycle message traffic of a constraint-partitioned local
    search family, for result reporting: each real variable-constraint
    edge carries ``msgs_per_edge`` int32 value announcements per cycle
    per restart instance (2 for the MGM family: value + gain round).
    This is the layout-derived count ``solve -m sharded`` reports
    instead of the old hardcoded zeros."""
    e_real = int(sum(
        int((vi[:, :, 0] < solver.V).sum()) * a
        for a, _c, vi in solver.sharded_buckets if a >= 1))
    msgs = msgs_per_edge * e_real * solver.B
    return {"msg_per_cycle": msgs,
            "bytes_per_cycle": msgs * np.dtype(np.int32).itemsize}


def _partition_constraints(arrays: HypergraphArrays, tp: int):
    """Round-robin each bucket's constraints over tp shards, padding
    with inert all-zero dummy constraints that point at a sink variable
    row so shapes stay identical per shard.  One vectorized gather per
    bucket — the only Python loop is over the tp shards for the index
    table (the old per-constraint nested loops were O(C) interpreter
    time on 100k-constraint grids)."""
    D = arrays.max_domain
    V = arrays.n_vars
    out = []
    for b in arrays.buckets:
        a = b.arity
        n = b.cubes.shape[0]
        fmax = (n + tp - 1) // tp if n else 0
        idx = np.full((tp, fmax), -1, dtype=np.int64)
        for g in range(tp):
            ids = np.arange(g, n, tp)
            idx[g, : len(ids)] = ids
        valid = idx >= 0
        # dummy constraints contribute 0 to the sink row only
        cubes = np.zeros((tp, fmax) + (D,) * a, dtype=np.float32)
        var_ids = np.full((tp, fmax, a), V, dtype=np.int32)
        cubes[valid] = b.cubes[idx[valid]]
        var_ids[valid] = b.var_ids[idx[valid]]
        out.append((a, cubes, var_ids))
    return out


class ShardedDsa(MeshSolverMixin):
    """DSA-B over a (dp, tp) mesh; ``batch`` independent instances."""

    def __init__(self, arrays: HypergraphArrays, mesh,
                 probability: float = 0.7, batch: int = 1,
                 precision=None):
        enable_persistent_cache()
        # mixed-precision policy: constraint cubes + unary planes in
        # store_dtype, candidate sums in accum f32 (ops/precision.py)
        self.policy = resolve_precision(precision)
        self.mesh = mesh
        self.tp = mesh.shape["tp"]
        self.dp = mesh.shape["dp"]
        if batch % self.dp != 0:
            raise ValueError(
                f"batch {batch} must be a multiple of dp={self.dp}")
        self.B = batch
        self.V = arrays.n_vars
        self.D = arrays.max_domain
        self.probability = float(probability)
        self.sharded_buckets = _partition_constraints(arrays, self.tp)
        # sink row for dummy constraints
        self.var_costs = np.concatenate(
            [arrays.var_costs,
             np.zeros((1, self.D), dtype=np.float32)])
        self.domain_mask = np.concatenate(
            [arrays.domain_mask, np.ones((1, self.D), dtype=bool)])
        self.domain_size = np.concatenate(
            [arrays.domain_size, np.full((1,), self.D, np.int32)])
        self._build_step()

    def _build_step(self):
        V, D = self.V, self.D
        prob = self.probability
        arities = [a for a, _, _ in self.sharded_buckets]

        def local_step(x, key, cubes, var_ids, var_costs, domain_mask):
            # x: (B_loc, V+1) current value indices (incl. sink)
            def one(x1, k1):
                # shard-local constraint contributions; unary costs are
                # added AFTER the psum (they are replicated — adding
                # them per shard would count them tp times).  The
                # accumulator is f32 even when the planes are
                # bf16-stored: sums upcast at the reduction boundary
                cand = jnp.zeros(var_costs.shape,
                                 dtype=self.policy.accum_dtype)
                violated = jnp.zeros((V + 1,), dtype=jnp.int32)
                for a, cu, vi in zip(arities, cubes, var_ids):
                    cand = cand + candidate_costs(
                        cu, vi, x1, V + 1,
                        accum_dtype=self.policy.accum_dtype)
                    ccost = bucket_cost(cu, vi, x1)
                    # per-constraint optimum from the shard-local cubes
                    # (dummy all-zero constraints: optimum == cost == 0,
                    # so they never read as violated)
                    opt = jnp.min(cu.reshape(cu.shape[0], -1), axis=-1)
                    viol = (ccost > opt + 1e-6).astype(jnp.int32)
                    for p in range(a):
                        violated = violated.at[vi[:, p]].add(viol)
                cand = jax.lax.psum(cand, "tp")
                violated = jax.lax.psum(violated, "tp") > 0
                cand = cand + var_costs
                cand = jnp.where(domain_mask, cand,
                                 jnp.asarray(SENTINEL, cand.dtype))
                best = jnp.argmin(cand, axis=-1)          # (V+1,)
                cur_cost = jnp.take_along_axis(
                    cand, x1[:, None], axis=-1)[:, 0]
                best_cost = jnp.min(cand, axis=-1)
                k_move = jax.random.fold_in(k1, 0)
                # DSA-B (reference dsa.py variants): move on strict
                # improvement, or on an equal-cost tie when an incident
                # constraint is violated (plateau escape)
                improve = best_cost < cur_cost
                sideways = (best_cost == cur_cost) & violated & \
                    (best != x1)
                move = (improve | sideways) & (
                    jax.random.uniform(k_move, (V + 1,)) < prob)
                return jnp.where(move, best, x1)

            # per-instance keys must differ across dp shards too
            dp_idx = jax.lax.axis_index("dp")
            keys = jax.vmap(
                lambda i: jax.random.fold_in(
                    jax.random.fold_in(key, dp_idx), i))(
                jnp.arange(x.shape[0]))
            return jax.vmap(one)(x, keys)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(
                P("dp"), P(),
                [P("tp") for _ in self.sharded_buckets],
                [P("tp") for _ in self.sharded_buckets],
                P(), P(),
            ),
            out_specs=P("dp"),
        )
        def sharded(x, key, cubes, var_ids, var_costs, domain_mask):
            cubes_l = [c[0] for c in cubes]
            vids_l = [v[0] for v in var_ids]
            return local_step(x, key, cubes_l, vids_l, var_costs,
                              domain_mask)

        self._step = jax.jit(sharded)

    def _init_x(self, seed: int):
        rng = np.random.default_rng(seed)
        x0 = rng.integers(
            0, np.maximum(self.domain_size, 1),
            size=(self.B, self.V + 1)).astype(np.int32)
        return jax.device_put(x0, NamedSharding(self.mesh, P("dp")))

    def _make_consts(self):
        mesh = self.mesh
        store = self.policy.store_dtype
        return (
            [jax.device_put(np.asarray(c, dtype=store),
                            NamedSharding(mesh, P("tp")))
             for _, c, _ in self.sharded_buckets],
            [jax.device_put(v, NamedSharding(mesh, P("tp")))
             for _, _, v in self.sharded_buckets],
            jax.device_put(jnp.asarray(self.var_costs, dtype=store),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.domain_mask),
                           NamedSharding(mesh, P())),
        )

    def _device_put(self, seed: int):
        return self._init_x(seed), self._consts()

    # ---------------------------------------------- mesh engine protocol

    def mesh_init(self, seed: int):
        import jax.numpy as _jnp

        return {"x": self._init_x(seed),
                "key": jax.random.PRNGKey(seed),
                "cycle": _jnp.int32(0),
                # DSA has no self-termination rule: the flag never
                # flips, runs stop at the cycle budget like the eager
                # loop always did
                "finished": _jnp.bool_(False)}

    def mesh_step(self, s):
        key, sub = jax.random.split(s["key"])
        x = self._step(s["x"], sub, *self._consts())
        out = dict(s)
        out.update(x=x, key=key, cycle=s["cycle"] + 1)
        return out

    def _build_cost_fn(self, with_violations: bool = False):
        return build_mesh_cost(
            self.mesh, self.V,
            [(c, v, None) for _a, c, v in self.sharded_buckets],
            self.var_costs, x_has_sink=True,
            with_violations=with_violations)

    def message_plane_stats(self):
        return _value_plane_stats(self)

    def _mesh_sel(self, state):
        return state["x"]

    def _decode_sel(self, sel_np: np.ndarray) -> np.ndarray:
        return sel_np[:, :self.V]

    # ------------------------------------------------------------- runs

    def run(self, n_cycles: int, seed: int = 0,
            collect_cost_every: Optional[int] = None,
            collect_metrics: bool = False, spans: bool = False,
            chunk_size: Optional[int] = None,
            timeout: Optional[float] = None
            ) -> Tuple[np.ndarray, int]:
        """Returns ((B, V) selections, cycles run); cycles execute in
        compiled chunks on device (engine/mesh_engine.py).
        ``collect_metrics``/``spans`` fill the telemetry surfaces
        (``last_cycle_metrics``, ``last_spans``,
        ``last_compile_stats``)."""
        return self._drive_mesh(
            self.mesh_init(seed), n_cycles,
            collect_cost_every=collect_cost_every,
            collect_metrics=collect_metrics, spans=spans,
            chunk_size=chunk_size, timeout=timeout)

    def run_eager(self, n_cycles: int, seed: int = 0
                  ) -> Tuple[np.ndarray, int]:
        """Pre-engine loop (one dispatch per cycle): the equivalence
        oracle for the chunked engine and the A/B bench leg."""
        import time as _time

        t0 = _time.perf_counter()
        x, (cubes, var_ids, var_costs, domain_mask) = \
            self._device_put(seed)
        key = jax.random.PRNGKey(seed)
        for cycle in range(n_cycles):
            key, sub = jax.random.split(key)
            x = self._step(x, sub, cubes, var_ids, var_costs,
                           domain_mask)
        self.finished = False  # DSA has no self-termination rule
        sel = np.asarray(jax.device_get(x))[:, :self.V]
        self.last_run_stats = self._eager_stats(n_cycles,
                                                "MAX_CYCLES", t0)
        return sel, n_cycles

    def step_once(self, seed: int = 0) -> np.ndarray:
        x, (cubes, var_ids, var_costs, domain_mask) = \
            self._device_put(seed)
        key = jax.random.PRNGKey(seed)
        x = self._step(x, key, cubes, var_ids, var_costs, domain_mask)
        jax.block_until_ready(x)
        return np.asarray(jax.device_get(x))[:, :self.V]


class ShardedMgm(MeshSolverMixin):
    """MGM over a (dp, tp) mesh (the round-2 gap: no mgm-family solver
    had a sharded path).

    Same mechanics as :class:`ShardedDsa` for the candidate-cost psum;
    the MGM decision needs one extra collective round: the
    "strictly-largest gain in my neighborhood" test.  Each shard
    scatter-maxes its constraints' participant gains (excluding self)
    into a per-variable neighbor-max, ``pmax`` over tp assembles the
    global view, and the lexic tie-break (lower variable index wins, as
    in the single-chip ``MgmSolver``) uses a second scatter-max over the
    at-max neighbors' priorities.  Monotonic: only strictly-improving
    moves, so the conflict count never increases.
    """

    def __init__(self, arrays: HypergraphArrays, mesh, batch: int = 1,
                 precision=None):
        enable_persistent_cache()
        self.policy = resolve_precision(precision)
        self.mesh = mesh
        self.tp = mesh.shape["tp"]
        self.dp = mesh.shape["dp"]
        if batch % self.dp != 0:
            raise ValueError(
                f"batch {batch} must be a multiple of dp={self.dp}")
        self.B = batch
        self.V = arrays.n_vars
        self.D = arrays.max_domain
        self.sharded_buckets = _partition_constraints(arrays, self.tp)
        self.var_costs = np.concatenate(
            [arrays.var_costs,
             np.zeros((1, self.D), dtype=np.float32)])
        self.domain_mask = np.concatenate(
            [arrays.domain_mask, np.ones((1, self.D), dtype=bool)])
        self.domain_size = np.concatenate(
            [arrays.domain_size, np.full((1,), self.D, np.int32)])
        self._build_step()

    def _build_step(self):
        V, D = self.V, self.D
        arities = [a for a, _, _ in self.sharded_buckets]
        # lexic tie-break: lower variable index wins (MgmSolver:35-37);
        # the sink row gets the worst priority
        priority = jnp.concatenate(
            [-jnp.arange(V, dtype=jnp.float32),
             jnp.asarray([-jnp.inf], dtype=jnp.float32)])

        def local_step(x, cubes, var_ids, var_costs, domain_mask):
            def one(x1):
                cand = jnp.zeros(var_costs.shape,
                                 dtype=self.policy.accum_dtype)
                for a, cu, vi in zip(arities, cubes, var_ids):
                    cand = cand + candidate_costs(
                        cu, vi, x1, V + 1,
                        accum_dtype=self.policy.accum_dtype)
                cand = jax.lax.psum(cand, "tp")
                cand = cand + var_costs
                cand = jnp.where(domain_mask, cand,
                                 jnp.asarray(SENTINEL, cand.dtype))
                best = jnp.argmin(cand, axis=-1)          # (V+1,)
                cur_cost = jnp.take_along_axis(
                    cand, x1[:, None], axis=-1)[:, 0]
                gain = cur_cost - jnp.min(cand, axis=-1)  # >= 0

                # pass 1: neighbor max gain (excluding self) per shard,
                # assembled with pmax over tp
                nbr_max = jnp.full((V + 1,), -jnp.inf)
                for a, cu, vi in zip(arities, cubes, var_ids):
                    if a < 2:
                        continue
                    g_part = gain[vi]                     # (F, a)
                    for p in range(a):
                        others = jnp.max(
                            jnp.concatenate([
                                g_part[:, :p], g_part[:, p + 1:]
                            ], axis=1), axis=1)
                        nbr_max = nbr_max.at[vi[:, p]].max(others)
                nbr_max = jax.lax.pmax(nbr_max, "tp")

                # pass 2: best priority among at-max neighbors
                nbr_pri = jnp.full((V + 1,), -jnp.inf)
                for a, cu, vi in zip(arities, cubes, var_ids):
                    if a < 2:
                        continue
                    g_part = gain[vi]
                    p_part = priority[vi]
                    for p in range(a):
                        g_o = jnp.concatenate(
                            [g_part[:, :p], g_part[:, p + 1:]], axis=1)
                        p_o = jnp.concatenate(
                            [p_part[:, :p], p_part[:, p + 1:]], axis=1)
                        at_max = g_o >= nbr_max[vi[:, p]][:, None] - 1e-9
                        best_o = jnp.max(
                            jnp.where(at_max, p_o, -jnp.inf), axis=1)
                        nbr_pri = nbr_pri.at[vi[:, p]].max(best_o)
                nbr_pri = jax.lax.pmax(nbr_pri, "tp")

                wins = (gain > nbr_max + 1e-9) | (
                    (gain >= nbr_max - 1e-9) & (priority > nbr_pri))
                change = (gain > 1e-9) & wins
                return jnp.where(change, best, x1)

            return jax.vmap(one)(x)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(
                P("dp"),
                [P("tp") for _ in self.sharded_buckets],
                [P("tp") for _ in self.sharded_buckets],
                P(), P(),
            ),
            out_specs=P("dp"),
        )
        def sharded(x, cubes, var_ids, var_costs, domain_mask):
            cubes_l = [c[0] for c in cubes]
            vids_l = [v[0] for v in var_ids]
            return local_step(x, cubes_l, vids_l, var_costs,
                              domain_mask)

        self._step = jax.jit(sharded)

    def _init_x(self, seed: int, x0: Optional[np.ndarray] = None):
        if x0 is None:
            rng = np.random.default_rng(seed)
            x0 = rng.integers(
                0, np.maximum(self.domain_size, 1),
                size=(self.B, self.V + 1)).astype(np.int32)
        else:
            sink = np.zeros((self.B, 1), dtype=np.int32)
            x0 = np.concatenate(
                [np.asarray(x0, dtype=np.int32), sink], axis=1)
        return jax.device_put(x0, NamedSharding(self.mesh, P("dp")))

    def _make_consts(self):
        mesh = self.mesh
        store = self.policy.store_dtype
        return (
            [jax.device_put(np.asarray(c, dtype=store),
                            NamedSharding(mesh, P("tp")))
             for _, c, _ in self.sharded_buckets],
            [jax.device_put(v, NamedSharding(mesh, P("tp")))
             for _, _, v in self.sharded_buckets],
            jax.device_put(jnp.asarray(self.var_costs, dtype=store),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.domain_mask),
                           NamedSharding(mesh, P())),
        )

    def _device_put(self, seed: int, x0: Optional[np.ndarray] = None):
        return self._init_x(seed, x0), self._consts()

    # ---------------------------------------------- mesh engine protocol

    def mesh_init(self, seed: int, x0: Optional[np.ndarray] = None):
        return {"x": self._init_x(seed, x0),
                "cycle": jnp.int32(0),
                # MGM runs the full budget by design
                "finished": jnp.bool_(False)}

    def mesh_step(self, s):
        x = self._step(s["x"], *self._consts())
        out = dict(s)
        out.update(x=x, cycle=s["cycle"] + 1)
        return out

    def _build_cost_fn(self, with_violations: bool = False):
        return build_mesh_cost(
            self.mesh, self.V,
            [(c, v, None) for _a, c, v in self.sharded_buckets],
            self.var_costs, x_has_sink=True,
            with_violations=with_violations)

    def message_plane_stats(self):
        # MGM exchanges a value round AND a gain round per cycle
        return _value_plane_stats(self, msgs_per_edge=2)

    def _mesh_sel(self, state):
        return state["x"]

    def _decode_sel(self, sel_np: np.ndarray) -> np.ndarray:
        return sel_np[:, :self.V]

    # ------------------------------------------------------------- runs

    def run(self, n_cycles: int, seed: int = 0,
            x0: Optional[np.ndarray] = None,
            collect_cost_every: Optional[int] = None,
            collect_metrics: bool = False, spans: bool = False,
            chunk_size: Optional[int] = None,
            timeout: Optional[float] = None) -> Tuple[np.ndarray, int]:
        """Returns ((B, V) selections, cycles run).  ``x0`` optionally
        fixes the initial (B, V) assignment (equivalence tests);
        cycles execute in compiled chunks on device.
        ``collect_metrics``/``spans`` fill the telemetry surfaces."""
        return self._drive_mesh(
            self.mesh_init(seed, x0), n_cycles,
            collect_cost_every=collect_cost_every,
            collect_metrics=collect_metrics, spans=spans,
            chunk_size=chunk_size, timeout=timeout)

    def run_eager(self, n_cycles: int, seed: int = 0,
                  x0: Optional[np.ndarray] = None
                  ) -> Tuple[np.ndarray, int]:
        """Pre-engine loop (one dispatch per cycle): the equivalence
        oracle for the chunked engine and the A/B bench leg."""
        import time as _time

        t0 = _time.perf_counter()
        x, (cubes, var_ids, var_costs, domain_mask) = \
            self._device_put(seed, x0)
        for cycle in range(n_cycles):
            x = self._step(x, cubes, var_ids, var_costs, domain_mask)
        self.finished = False  # runs the full budget by design
        sel = np.asarray(jax.device_get(x))[:, :self.V]
        self.last_run_stats = self._eager_stats(n_cycles,
                                                "MAX_CYCLES", t0)
        return sel, n_cycles

    def step_once(self, seed: int = 0) -> np.ndarray:
        x, (cubes, var_ids, var_costs, domain_mask) = \
            self._device_put(seed)
        x = self._step(x, cubes, var_ids, var_costs, domain_mask)
        jax.block_until_ready(x)
        return np.asarray(jax.device_get(x))[:, :self.V]

"""Multi-chip local search: dp x tp sharded DSA over a jax.sharding.Mesh.

Companion of :mod:`sharded_maxsum` for the local-search family
(SURVEY.md §2.8): constraints are partitioned across the ``tp`` axis;
each device computes its shard's contribution to the per-variable
candidate-cost matrix, a ``psum`` over ``tp`` assembles the full
``(V, D)`` matrix (the collective rides ICI), and the DSA-B decision —
move to the best value with probability p when it improves — runs
replicated per device on the small reduced state.  ``dp`` shards
independent problem instances.

This is the scale-out story for the 10k-agent grid configs
(BASELINE.md #4): the expensive part (constraint-slice enumeration,
O(C * D * arity)) is tp-sharded; the per-variable decision is O(V * D).
"""

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..graphs.arrays import BIG, HypergraphArrays
from ..ops.kernels import bucket_cost, candidate_costs


def _partition_constraints(arrays: HypergraphArrays, tp: int):
    """Round-robin each bucket's constraints over tp shards, padding
    with inert (all-BIG... actually all-zero) dummy constraints that
    point at a sink variable row so shapes stay identical per shard."""
    D = arrays.max_domain
    V = arrays.n_vars
    out = []
    for b in arrays.buckets:
        a = b.arity
        n = b.cubes.shape[0]
        groups = [list(range(g, n, tp)) for g in range(tp)]
        fmax = max(len(g) for g in groups) if groups else 0
        # dummy constraints contribute 0 to the sink row only
        cubes = np.zeros((tp, fmax) + (D,) * a, dtype=np.float32)
        var_ids = np.full((tp, fmax, a), V, dtype=np.int32)
        for g in range(tp):
            for slot, ci in enumerate(groups[g]):
                cubes[g, slot] = b.cubes[ci]
                var_ids[g, slot] = b.var_ids[ci]
        out.append((a, cubes, var_ids))
    return out


class ShardedDsa:
    """DSA-B over a (dp, tp) mesh; ``batch`` independent instances."""

    def __init__(self, arrays: HypergraphArrays, mesh,
                 probability: float = 0.7, batch: int = 1):
        self.mesh = mesh
        self.tp = mesh.shape["tp"]
        self.dp = mesh.shape["dp"]
        if batch % self.dp != 0:
            raise ValueError(
                f"batch {batch} must be a multiple of dp={self.dp}")
        self.B = batch
        self.V = arrays.n_vars
        self.D = arrays.max_domain
        self.probability = float(probability)
        self.sharded_buckets = _partition_constraints(arrays, self.tp)
        # sink row for dummy constraints
        self.var_costs = np.concatenate(
            [arrays.var_costs,
             np.zeros((1, self.D), dtype=np.float32)])
        self.domain_mask = np.concatenate(
            [arrays.domain_mask, np.ones((1, self.D), dtype=bool)])
        self.domain_size = np.concatenate(
            [arrays.domain_size, np.full((1,), self.D, np.int32)])
        self._build_step()

    def _build_step(self):
        V, D = self.V, self.D
        prob = self.probability
        arities = [a for a, _, _ in self.sharded_buckets]

        def local_step(x, key, cubes, var_ids, var_costs, domain_mask):
            # x: (B_loc, V+1) current value indices (incl. sink)
            def one(x1, k1):
                # shard-local constraint contributions; unary costs are
                # added AFTER the psum (they are replicated — adding
                # them per shard would count them tp times)
                cand = jnp.zeros_like(var_costs)  # (V+1, D)
                violated = jnp.zeros((V + 1,), dtype=jnp.int32)
                for a, cu, vi in zip(arities, cubes, var_ids):
                    cand = cand + candidate_costs(cu, vi, x1, V + 1)
                    ccost = bucket_cost(cu, vi, x1)
                    # per-constraint optimum from the shard-local cubes
                    # (dummy all-zero constraints: optimum == cost == 0,
                    # so they never read as violated)
                    opt = jnp.min(cu.reshape(cu.shape[0], -1), axis=-1)
                    viol = (ccost > opt + 1e-6).astype(jnp.int32)
                    for p in range(a):
                        violated = violated.at[vi[:, p]].add(viol)
                cand = jax.lax.psum(cand, "tp")
                violated = jax.lax.psum(violated, "tp") > 0
                cand = cand + var_costs
                cand = jnp.where(domain_mask, cand, BIG * 2)
                best = jnp.argmin(cand, axis=-1)          # (V+1,)
                cur_cost = jnp.take_along_axis(
                    cand, x1[:, None], axis=-1)[:, 0]
                best_cost = jnp.min(cand, axis=-1)
                k_move = jax.random.fold_in(k1, 0)
                # DSA-B (reference dsa.py variants): move on strict
                # improvement, or on an equal-cost tie when an incident
                # constraint is violated (plateau escape)
                improve = best_cost < cur_cost
                sideways = (best_cost == cur_cost) & violated & \
                    (best != x1)
                move = (improve | sideways) & (
                    jax.random.uniform(k_move, (V + 1,)) < prob)
                return jnp.where(move, best, x1)

            # per-instance keys must differ across dp shards too
            dp_idx = jax.lax.axis_index("dp")
            keys = jax.vmap(
                lambda i: jax.random.fold_in(
                    jax.random.fold_in(key, dp_idx), i))(
                jnp.arange(x.shape[0]))
            return jax.vmap(one)(x, keys)

        @partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(
                P("dp"), P(),
                [P("tp") for _ in self.sharded_buckets],
                [P("tp") for _ in self.sharded_buckets],
                P(), P(),
            ),
            out_specs=P("dp"),
        )
        def sharded(x, key, cubes, var_ids, var_costs, domain_mask):
            cubes_l = [c[0] for c in cubes]
            vids_l = [v[0] for v in var_ids]
            return local_step(x, key, cubes_l, vids_l, var_costs,
                              domain_mask)

        self._step = jax.jit(sharded)

    def _device_put(self, seed: int):
        mesh = self.mesh
        rng = np.random.default_rng(seed)
        x0 = rng.integers(
            0, np.maximum(self.domain_size, 1),
            size=(self.B, self.V + 1)).astype(np.int32)
        x = jax.device_put(x0, NamedSharding(mesh, P("dp")))
        consts = (
            [jax.device_put(c, NamedSharding(mesh, P("tp")))
             for _, c, _ in self.sharded_buckets],
            [jax.device_put(v, NamedSharding(mesh, P("tp")))
             for _, _, v in self.sharded_buckets],
            jax.device_put(jnp.asarray(self.var_costs),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.domain_mask),
                           NamedSharding(mesh, P())),
        )
        return x, consts

    def run(self, n_cycles: int, seed: int = 0
            ) -> Tuple[np.ndarray, int]:
        """Returns ((B, V) selections, cycles run)."""
        x, (cubes, var_ids, var_costs, domain_mask) = \
            self._device_put(seed)
        key = jax.random.PRNGKey(seed)
        for cycle in range(n_cycles):
            key, sub = jax.random.split(key)
            x = self._step(x, sub, cubes, var_ids, var_costs,
                           domain_mask)
        sel = np.asarray(jax.device_get(x))[:, :self.V]
        return sel, n_cycles

    def step_once(self, seed: int = 0) -> np.ndarray:
        x, (cubes, var_ids, var_costs, domain_mask) = \
            self._device_put(seed)
        key = jax.random.PRNGKey(seed)
        x = self._step(x, key, cubes, var_ids, var_costs, domain_mask)
        jax.block_until_ready(x)
        return np.asarray(jax.device_get(x))[:, :self.V]

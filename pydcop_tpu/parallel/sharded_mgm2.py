"""Multi-chip MGM-2: the 5-phase coordinated-move machine over a
dp x tp mesh.

Closes the round-3 gap: MGM-2 (the BASELINE config-4 algorithm,
reference pydcop/algorithms/mgm2.py:435 — the value / offer / answer /
gain / go state machine) was the only major family with no scale-out
path.  The sharding follows :mod:`sharded_localsearch`: constraints are
partitioned across ``tp`` (each device enumerates its shard's
constraint slices), and the two expensive tensors — the ``(V, D)``
candidate-cost matrix ``L`` and the ``(P, D, D)`` shared-pair slice
tensor ``S`` over the directed neighbor-pair edges — are assembled with
one ``psum`` over ``tp`` each (the collectives ride ICI).  The 5-phase
decision logic (roles, offers, answers, announced gains, go) runs
replicated per device on the small reduced state, exactly as in the
single-chip :class:`~pydcop_tpu.algorithms.mgm2.Mgm2Solver`; ``dp``
shards independent instances.

Selection equality: each instance's PRNG chain replicates the
single-chip solver's (``init_state`` split + 5-way step split), and the
phase arithmetic is the same ops in the same order, so for integer-cost
instances a sharded run is bit-identical to a single-chip engine run
with the same seed (asserted in tests/test_parallel.py).
"""

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from ._mesh_cost import build_mesh_cost
from ..engine._cache import enable_persistent_cache
from ..engine.mesh_engine import MeshSolverMixin
from ..graphs.arrays import (SENTINEL, HypergraphArrays, out_edge_table,
                             pair_edge_lookup, pair_eids_for_bucket)
from ..ops.kernels import candidate_costs
from ..ops.precision import resolve as resolve_precision
from .sharded_localsearch import _partition_constraints

_EPS = 1e-6


class ShardedMgm2(MeshSolverMixin):
    """MGM-2 over a (dp, tp) mesh; ``batch`` independent instances.

    Parameters mirror the single-chip solver: ``threshold`` (offerer
    probability) and ``favor`` (tie policy between unilateral and
    coordinated moves).
    """

    def __init__(self, arrays: HypergraphArrays, mesh,
                 threshold: float = 0.5, favor: str = "unilateral",
                 batch: int = 1, precision=None):
        enable_persistent_cache()
        # mixed-precision policy: cubes + unary planes in store_dtype,
        # candidate/pair-slice sums in accum f32 (ops/precision.py)
        self.policy = resolve_precision(precision)
        self.mesh = mesh
        self.tp = mesh.shape["tp"]
        self.dp = mesh.shape["dp"]
        if batch % self.dp != 0:
            raise ValueError(
                f"batch {batch} must be a multiple of dp={self.dp}")
        self.B = batch
        self.V = arrays.n_vars
        self.D = arrays.max_domain
        self.threshold = float(threshold)
        self.favor = favor
        self.var_names = arrays.var_names

        self.sharded_buckets = _partition_constraints(arrays, self.tp)

        # ---- pair-edge decision plane (replicated; same builders as
        # the single-chip solver) ------------------------------------
        src = np.asarray(arrays.nbr_src, dtype=np.int32)
        dst = np.asarray(arrays.nbr_dst, dtype=np.int32)
        self.has_neighbors = len(src) > 0
        # keep at least one (inert) edge so every P-sized op has a
        # static nonzero shape; dummy contributions sum zeros into it
        if len(src) == 0:
            src = np.zeros(1, dtype=np.int32)
            dst = np.zeros(1, dtype=np.int32)
        self.P = len(src)
        lookup = pair_edge_lookup(src, dst, self.V) \
            if self.has_neighbors else (lambda u, v: np.zeros(
                np.broadcast_shapes(np.shape(u), np.shape(v)),
                dtype=np.int32))
        # per sharded bucket: (TP, F, a, a) pair-edge ids; dummy slots
        # (sink var ids) resolve to 0, where their all-zero cubes land
        self.pair_eids = [
            pair_eids_for_bucket(lookup, var_ids)
            for _a, _c, var_ids in self.sharded_buckets
        ]
        out_edges, deg = out_edge_table(
            src if self.has_neighbors else src[:0], self.V)
        self.out_edges = out_edges
        self.out_degree = deg
        self.pair_src = src
        self.pair_dst = dst

        self.var_costs = np.asarray(arrays.var_costs)       # (V, D)
        self.domain_mask = np.asarray(arrays.domain_mask)   # (V, D)
        self.domain_size = np.asarray(arrays.domain_size)
        self.initial_idx = np.asarray(arrays.initial_idx)
        self.has_initial = np.asarray(arrays.has_initial)

        self._build_step()

    # ------------------------------------------------------------- init

    def _init_instance(self, seed: int):
        """Replicates ``Mgm2Solver.init_state`` bit-for-bit: split the
        instance key, draw the random start (LocalSearchSolver
        .random_values)."""
        key, sub = jax.random.split(jax.random.PRNGKey(int(seed)))
        r = jax.random.uniform(sub, (self.V,))
        rand_idx = (r * self.domain_size).astype(jnp.int32)
        x = jnp.where(jnp.asarray(self.has_initial),
                      jnp.asarray(self.initial_idx), rand_idx)
        return np.asarray(x), np.asarray(key)

    # ------------------------------------------------------------- step

    def _shared_slices_local(self, x_ext, cubes, var_ids_l, pair_eids_l):
        """Shard-local part of the (P, D, D) shared-pair slice tensor
        (same per-bucket arithmetic as ``Mgm2Solver.shared_slices``)."""
        D, Pn = self.D, self.P
        S = jnp.zeros((Pn, D, D), dtype=self.policy.accum_dtype)
        for (a, _c, _v), cu, vi, peid in zip(
                self.sharded_buckets, cubes, var_ids_l, pair_eids_l):
            if a < 2:
                continue
            C = cu.shape[0]
            vals = x_ext[vi]
            for p in range(a):
                for q in range(a):
                    if p == q:
                        continue
                    t = jnp.moveaxis(cu, p + 1, a)      # p -> last
                    q_axis = q + 1 if q < p else q
                    t = jnp.moveaxis(t, q_axis, a - 1)
                    t = t.reshape(C, -1, D, D)
                    idx = jnp.zeros((C,), dtype=jnp.int32)
                    for r in range(a):
                        if r != p and r != q:
                            idx = idx * D + vals[:, r]
                    contrib = t[jnp.arange(C), idx]     # (C, D_q, D_p)
                    contrib = jnp.swapaxes(contrib, 1, 2)
                    # upcast at the reduction boundary: bf16-stored
                    # slices sum in f32 (ops/precision.py)
                    S = S + jax.ops.segment_sum(
                        contrib.astype(S.dtype), peid[:, p, q],
                        num_segments=Pn)
        return S

    def _build_step(self):
        V, D, Pn = self.V, self.D, self.P
        threshold, favor = self.threshold, self.favor
        has_neighbors = self.has_neighbors
        arities = [a for a, _, _ in self.sharded_buckets]

        def one(x1, k1, cubes, var_ids_l, pair_eids_l, var_costs,
                domain_mask, out_edges, out_degree, pair_src, pair_dst):
            key, k_best, k_role, k_pick, k_tie = jax.random.split(k1, 5)
            ar = jnp.arange(V)
            # dummy constraints point at the sink id V: extend x
            x_ext = jnp.concatenate(
                [x1, jnp.zeros((1,), dtype=x1.dtype)])

            # phase 1: local view (psum-assembled candidate costs, then
            # the exact best_response arithmetic of LocalSearchSolver)
            cand = jnp.zeros((V + 1, D), dtype=self.policy.accum_dtype)
            for a, cu, vi in zip(arities, cubes, var_ids_l):
                cand = cand + candidate_costs(
                    cu, vi, x_ext, V + 1,
                    accum_dtype=self.policy.accum_dtype)
            cand = jax.lax.psum(cand, "tp")[:V]
            costs = var_costs + cand
            cur = costs[ar, x1]
            c = jnp.where(domain_mask, costs,
                          jnp.asarray(SENTINEL, costs.dtype))
            best_cost = jnp.min(c, axis=-1)
            is_min = (c <= best_cost[:, None] + 1e-9) & domain_mask
            not_cur = is_min & ~jax.nn.one_hot(x1, D, dtype=bool)
            has_other = jnp.any(not_cur, axis=-1)
            pick_from = jnp.where(has_other[:, None], not_cur, is_min)
            noise = jax.random.uniform(k_best, c.shape)
            best_val = jnp.argmax(pick_from * (1.0 + noise), axis=-1)
            solo_gain = cur - best_cost
            L = costs

            # phase 2: roles + offers (Mgm2Solver.step phase 2)
            offerer = jax.random.uniform(k_role, (V,)) < threshold
            pick = (jax.random.uniform(k_pick, (V,))
                    * jnp.maximum(out_degree, 1)).astype(jnp.int32)
            chosen_edge = out_edges[ar, pick]
            has_nbr = out_degree > 0

            S = jax.lax.psum(
                self._shared_slices_local(
                    x_ext, cubes, var_ids_l, pair_eids_l), "tp")
            o, t = pair_src, pair_dst
            pair_cost = (
                L[o][:, :, None] + L[t][:, None, :]
                - S[jnp.arange(Pn), :, x1[t]][:, :, None]
                - S[jnp.arange(Pn), x1[o], :][:, None, :]
                + S
            )
            mask2 = (domain_mask[o][:, :, None]
                     & domain_mask[t][:, None, :])
            pair_cost = jnp.where(mask2, pair_cost,
                                  jnp.asarray(SENTINEL,
                                              pair_cost.dtype))
            pair_cur = cur[o] + cur[t] - S[jnp.arange(Pn), x1[o], x1[t]]
            flat = pair_cost.reshape(Pn, -1)
            pair_best = jnp.min(flat, axis=1)
            pair_arg = jnp.argmin(flat, axis=1)
            pair_d1 = pair_arg // D
            pair_d2 = pair_arg % D
            pair_gain = pair_cur - pair_best

            is_offer = (offerer[o] & has_nbr[o]
                        & (chosen_edge[o] == jnp.arange(Pn))
                        & ~offerer[t] & (pair_gain > _EPS))

            # phase 3: answers
            tie = jax.random.uniform(k_tie, (Pn,))
            offer_score = jnp.where(
                is_offer, pair_gain + tie * _EPS, -jnp.inf)
            best_offer_at = jax.ops.segment_max(
                offer_score, t, num_segments=V)
            accepted = is_offer & (offer_score >= best_offer_at[t]) \
                & jnp.isfinite(best_offer_at[t])

            in_pair_src = jax.ops.segment_max(
                accepted.astype(jnp.int32), o, num_segments=V) > 0
            in_pair_dst = jax.ops.segment_max(
                accepted.astype(jnp.int32), t, num_segments=V) > 0
            in_pair = in_pair_src | in_pair_dst
            eidx = jnp.arange(Pn)
            edge_of_src = jax.ops.segment_max(
                jnp.where(accepted, eidx, -1), o, num_segments=V)
            edge_of_dst = jax.ops.segment_max(
                jnp.where(accepted, eidx, -1), t, num_segments=V)
            my_edge = jnp.maximum(edge_of_src, edge_of_dst)
            partner = jnp.where(
                in_pair_src, t[jnp.clip(my_edge, 0)],
                o[jnp.clip(my_edge, 0)])

            # phase 4: announced gains
            favor_bonus = {"unilateral": -_EPS, "coordinated": _EPS,
                           "no": 0.0}[favor]
            g_pair = pair_gain[jnp.clip(my_edge, 0)] + favor_bonus
            announced = jnp.where(
                in_pair, g_pair,
                jnp.where(offerer, 0.0, solo_gain))

            # phase 5: go — strict max in neighborhood
            exclude = in_pair[pair_dst] \
                & (pair_src == partner[pair_dst])
            nbr_gain = jnp.where(
                exclude, -jnp.inf, announced[pair_src])
            nbr_max = jax.ops.segment_max(
                nbr_gain, pair_dst, num_segments=V) \
                if has_neighbors else jnp.full((V,), -jnp.inf)

            my_go = announced > nbr_max + _EPS
            partner_go = my_go[partner]
            pair_moves = in_pair & my_go & partner_go \
                & (announced > _EPS)
            solo_moves = (~in_pair) & (~offerer) \
                & (solo_gain > _EPS) & my_go

            pair_val = jnp.where(
                in_pair_src, pair_d1[jnp.clip(my_edge, 0)],
                pair_d2[jnp.clip(my_edge, 0)])
            x_new = jnp.where(pair_moves, pair_val,
                              jnp.where(solo_moves, best_val, x1))
            return x_new, key

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(
                P("dp"), P("dp"),
                [P("tp") for _ in self.sharded_buckets],
                [P("tp") for _ in self.sharded_buckets],
                [P("tp") for _ in self.sharded_buckets],
                P(), P(), P(), P(), P(), P(),
            ),
            out_specs=(P("dp"), P("dp")),
        )
        def sharded(x, keys, cubes, var_ids, pair_eids, var_costs,
                    domain_mask, out_edges, out_degree, pair_src,
                    pair_dst):
            cubes_l = [c[0] for c in cubes]
            vids_l = [v[0] for v in var_ids]
            peids_l = [p[0] for p in pair_eids]
            return jax.vmap(
                lambda x1, k1: one(
                    x1, k1, cubes_l, vids_l, peids_l, var_costs,
                    domain_mask, out_edges, out_degree, pair_src,
                    pair_dst))(x, keys)

        self._step = jax.jit(sharded)

    # -------------------------------------------------------------- run

    def _init_xk(self, seeds: Sequence[int]):
        mesh = self.mesh
        inits = [self._init_instance(s) for s in seeds]
        x0 = np.stack([x for x, _ in inits]).astype(np.int32)
        k0 = np.stack([k for _, k in inits])
        x = jax.device_put(x0, NamedSharding(mesh, P("dp")))
        keys = jax.device_put(k0, NamedSharding(mesh, P("dp")))
        return x, keys

    def _make_consts(self):
        mesh = self.mesh
        store = self.policy.store_dtype
        return (
            [jax.device_put(np.asarray(c, dtype=store),
                            NamedSharding(mesh, P("tp")))
             for _, c, _ in self.sharded_buckets],
            [jax.device_put(v, NamedSharding(mesh, P("tp")))
             for _, _, v in self.sharded_buckets],
            [jax.device_put(pe, NamedSharding(mesh, P("tp")))
             for pe in self.pair_eids],
            jax.device_put(jnp.asarray(self.var_costs, dtype=store),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.domain_mask),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.out_edges),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.out_degree),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.pair_src),
                           NamedSharding(mesh, P())),
            jax.device_put(jnp.asarray(self.pair_dst),
                           NamedSharding(mesh, P())),
        )

    def _device_put(self, seeds: Sequence[int]):
        x, keys = self._init_xk(seeds)
        return x, keys, self._consts()

    # ---------------------------------------------- mesh engine protocol

    def mesh_init(self, seed: int = 0,
                  seeds: Optional[Sequence[int]] = None):
        x, keys = self._init_xk(self._seeds_for(seed, seeds))
        return {"x": x, "keys": keys,
                "cycle": jnp.int32(0),
                # MGM-2 runs the full budget by design
                "finished": jnp.bool_(False)}

    def mesh_step(self, s):
        x, keys = self._step(s["x"], s["keys"], *self._consts())
        out = dict(s)
        out.update(x=x, keys=keys, cycle=s["cycle"] + 1)
        return out

    def _build_cost_fn(self, with_violations: bool = False):
        return build_mesh_cost(
            self.mesh, self.V,
            [(c, v, None) for _a, c, v in self.sharded_buckets],
            self.var_costs, x_has_sink=False,
            with_violations=with_violations)

    def message_plane_stats(self):
        # MGM-2: value + offer + gain rounds per cycle
        from .sharded_localsearch import _value_plane_stats

        return _value_plane_stats(self, msgs_per_edge=3)

    def _mesh_sel(self, state):
        return state["x"]

    # ------------------------------------------------------------- runs

    def run(self, n_cycles: int, seed: int = 0,
            seeds: Optional[Sequence[int]] = None,
            collect_cost_every: Optional[int] = None,
            collect_metrics: bool = False, spans: bool = False,
            chunk_size: Optional[int] = None,
            timeout: Optional[float] = None
            ) -> Tuple[np.ndarray, int]:
        """Returns ((B, V) selections, cycles run).  ``seeds`` gives
        each instance its own engine seed (default ``seed + i``); an
        instance's run is then bit-identical to a single-chip
        ``SyncEngine(Mgm2Solver(...)).run(key=that_seed)``.  Cycles
        execute in compiled chunks on device;
        ``collect_metrics``/``spans`` fill the telemetry surfaces."""
        return self._drive_mesh(
            self.mesh_init(seed, seeds), n_cycles,
            collect_cost_every=collect_cost_every,
            collect_metrics=collect_metrics, spans=spans,
            chunk_size=chunk_size, timeout=timeout)

    def run_eager(self, n_cycles: int, seed: int = 0,
                  seeds: Optional[Sequence[int]] = None
                  ) -> Tuple[np.ndarray, int]:
        """Pre-engine loop (one dispatch per cycle): the equivalence
        oracle for the chunked engine and the A/B bench leg."""
        import time as _time

        t0 = _time.perf_counter()
        x, keys, consts = self._device_put(
            self._seeds_for(seed, seeds))
        for _ in range(n_cycles):
            x, keys = self._step(x, keys, *consts)
        self.finished = False  # runs the full budget by design
        self.last_run_stats = self._eager_stats(n_cycles,
                                                "MAX_CYCLES", t0)
        return np.asarray(jax.device_get(x)), n_cycles

    def step_once(self, seed: int = 0) -> np.ndarray:
        """One sharded step (compile-check of the multi-chip path)."""
        x, keys, consts = self._device_put(
            [seed + i for i in range(self.B)])
        x, keys = self._step(x, keys, *consts)
        jax.block_until_ready(x)
        return np.asarray(jax.device_get(x))

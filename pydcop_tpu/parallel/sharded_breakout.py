"""Generic dp x tp sharding harness for the local-search solver family.

Closes the round-3 coverage gap ("no sharded mgm2/gdba/dba/mixeddsa"):
instead of re-implementing each algorithm's step at mesh scale, the
harness runs the UNMODIFIED single-chip solver step inside
``jax.shard_map``.  Two ingredients make that possible:

* **reduction hooks** — every cross-constraint accumulation in the
  solver family routes through ``LocalSearchSolver._reduce_vplane`` /
  ``_reduce_scalar`` (identity on one chip); the harness overrides them
  with ``psum`` over the ``tp`` mesh axis, so candidate-cost sums,
  violation counts and termination totals are assembled across shards
  while the V-plane decision logic stays replicated;
* **a sink-variable view** — constraints are round-robin partitioned
  over ``tp`` with inert all-zero dummy rows whose scope points at one
  extra sink variable, so every scatter lands in a row that is dropped
  from the result (same trick as :mod:`sharded_localsearch`, but
  expressed in the arrays view so the solver's own step can be reused
  verbatim).

Per-constraint algorithm state (DBA weights, GDBA modifier hypercubes)
lives sharded: each tp shard owns exactly its constraints' state, the
natural distributed-breakout layout.  ``dp`` shards independent
instances; each instance's PRNG chain replicates the single-chip
engine's (``init_state`` + step splits), so a sharded run is
bit-identical to a single-chip run of the same sink-augmented view.
"""

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from ._mesh_cost import build_mesh_cost
from ..algorithms.dba import DbaSolver
from ..algorithms.dsa import DsaSolver
from ..algorithms.gdba import GdbaSolver
from ..algorithms.mixeddsa import MixedDsaSolver
from ..engine._cache import enable_persistent_cache
from ..engine.mesh_engine import MeshSolverMixin
from ..graphs.arrays import ConstraintBucket, HypergraphArrays
from .sharded_localsearch import _partition_constraints


def _mesh_reduce_vplane(a):
    """Cross-shard reduction hook installed on the solver during the
    traced step (module-level so a test can deliberately break it and
    prove the dryrun's quality assertions catch wrong collective
    math)."""
    return jax.lax.psum(a, "tp")


def _mesh_reduce_scalar(v):
    return jax.lax.psum(v, "tp")


def _sink_view(arrays: HypergraphArrays,
               shard_buckets, shard_idx: int) -> HypergraphArrays:
    """A copy of ``arrays`` with one extra sink variable and shard
    ``shard_idx``'s padded constraint slice as its buckets."""
    D = arrays.max_domain
    V = arrays.n_vars
    buckets = [
        ConstraintBucket(
            a, np.arange(cubes.shape[1], dtype=np.int32),
            np.asarray(cubes[shard_idx]),
            np.asarray(var_ids[shard_idx]))
        for a, cubes, var_ids in shard_buckets
    ]
    return HypergraphArrays(
        n_vars=V + 1,
        n_constraints=sum(b.cubes.shape[0] for b in buckets),
        max_domain=D,
        sign=arrays.sign,
        var_names=list(arrays.var_names) + ["__sink__"],
        domain_size=np.concatenate(
            [arrays.domain_size, np.full((1,), D, np.int32)]),
        domain_mask=np.concatenate(
            [arrays.domain_mask, np.ones((1, D), dtype=bool)]),
        var_costs=np.concatenate(
            [arrays.var_costs, np.zeros((1, D), dtype=np.float32)]),
        initial_idx=np.concatenate(
            [arrays.initial_idx, np.zeros((1,), dtype=np.int32)]),
        has_initial=np.concatenate(
            [arrays.has_initial, np.zeros((1,), dtype=bool)]),
        buckets=buckets,
        nbr_src=arrays.nbr_src,
        nbr_dst=arrays.nbr_dst,
        max_degree=arrays.max_degree,
        max_arity_minus_one=arrays.max_arity_minus_one,
    )


class ShardedLocalSearch(MeshSolverMixin):
    """Run a :class:`LocalSearchSolver` subclass over a (dp, tp) mesh.

    Subclasses set ``solver_cls``, the per-bucket constant attributes
    to shard (``bucket_attrs``) and the state keys holding per-bucket
    algorithm state (``state_bucket_keys``).
    """

    solver_cls = None
    bucket_attrs: Tuple[str, ...] = ("buckets", "bucket_optima")
    state_bucket_keys: Tuple[str, ...] = ()

    def __init__(self, arrays: HypergraphArrays, mesh, batch: int = 1,
                 **params):
        enable_persistent_cache()
        # mixed-precision policy (ops/precision.py): handled at the
        # HARNESS level — popped here so solver classes that predate
        # the policy never see an unknown kwarg.  Only the cost-plane
        # constants (cubes + per-constraint optima) are store-cast in
        # _make_consts; per-constraint ALGORITHM state (DBA weights,
        # GDBA modifiers) keeps full precision — weights are counters
        # whose increments a bf16 store would start dropping at 256
        from ..ops.precision import resolve as _resolve_precision

        self.policy = _resolve_precision(params.pop("precision", None))
        self.mesh = mesh
        self.tp = mesh.shape["tp"]
        self.dp = mesh.shape["dp"]
        if batch % self.dp != 0:
            raise ValueError(
                f"batch {batch} must be a multiple of dp={self.dp}")
        self.B = batch
        self.V = arrays.n_vars  # real variables (sink dropped)
        self.var_names = arrays.var_names

        shard_buckets = _partition_constraints(arrays, self.tp)
        # raw partitioned cubes + unary costs, kept for the on-device
        # cost trace (algorithm state like DBA weights deliberately
        # excluded: the trace reports RAW assignment cost)
        self.sharded_buckets = shard_buckets
        self._raw_var_costs = np.asarray(arrays.var_costs)
        # one solver per shard view: shard 0's doubles as the template
        # whose step we trace; the others only donate their
        # bucket-derived constants (violation cubes, optima, ...).
        # This re-creates the replicated V-plane constants tp times —
        # transient megabytes, accepted so the harness needs zero
        # per-algorithm knowledge of how those constants derive from
        # the cubes
        shard_solvers = [
            self.solver_cls(_sink_view(arrays, shard_buckets, g),
                            **params)
            for g in range(self.tp)
        ]
        self.solver = shard_solvers[0]

        # stack each per-bucket constant across shards: leading TP axis
        self._attr_stacks = {}
        for attr in self.bucket_attrs:
            per_shard = [getattr(s, attr) for s in shard_solvers]
            stacked = []
            for bucket_i in range(len(per_shard[0])):
                leaves = [per_shard[g][bucket_i] for g in range(self.tp)]
                stacked.append(jax.tree.map(
                    lambda *ls: jnp.stack(ls), *leaves))
            self._attr_stacks[attr] = stacked

        self._build_step()

    # ------------------------------------------------------------- step

    def _build_step(self):
        solver = self.solver
        attr_names = list(self.bucket_attrs)
        state_keys = None  # discovered at trace time

        def local_step(x, keys, bucket_state, attr_locals):
            # install the shard-local constants + psum hooks, then run
            # the solver's own step per instance; originals restored so
            # no tracer outlives the trace on the template solver
            originals = {name: getattr(solver, name)
                         for name in attr_names}
            for name, value in zip(attr_names, attr_locals):
                setattr(solver, name, value)
            solver._reduce_vplane = _mesh_reduce_vplane
            solver._reduce_scalar = _mesh_reduce_scalar
            try:
                def one(x1, k1, bstate):
                    s = {"cycle": jnp.int32(0),
                         "finished": jnp.bool_(False),
                         "key": k1, "x": x1}
                    s.update({k: v for k, v in
                              zip(self.state_bucket_keys, bstate)})
                    out = solver.step(s)
                    return (out["x"], out["key"], out["finished"],
                            tuple(out[k]
                                  for k in self.state_bucket_keys))

                return jax.vmap(one)(x, keys, bucket_state)
            finally:
                for name, value in originals.items():
                    setattr(solver, name, value)
                del solver._reduce_vplane
                del solver._reduce_scalar  # back to the class identity

        n_attr_specs = [
            [P("tp")] * len(self._attr_stacks[a]) for a in attr_names
        ]

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(
                P("dp"), P("dp"),
                tuple([P("dp", "tp")] * len(self.state_bucket_keys)),
                tuple(n_attr_specs),
            ),
            out_specs=(
                P("dp"), P("dp"), P("dp"),
                tuple([P("dp", "tp")] * len(self.state_bucket_keys)),
            ),
            check_vma=False,
        )
        def sharded(x, keys, bucket_state, attr_stacks):
            # drop the leading local tp axis (size 1) of every sharded
            # operand; per-bucket state leaves keep their inner tuple
            # structure
            attr_locals = [
                [jax.tree.map(lambda a: a[0], b) for b in bucket_list]
                for bucket_list in attr_stacks
            ]
            bstate_local = tuple(
                jax.tree.map(lambda a: a[:, 0], entry)
                for entry in bucket_state
            )
            x2, keys2, finished, bstate2 = local_step(
                x, keys, bstate_local, attr_locals)
            bstate_out = tuple(
                jax.tree.map(lambda a: a[:, None], entry)
                for entry in bstate2
            )
            return x2, keys2, finished, bstate_out

        self._step = jax.jit(sharded)

    # -------------------------------------------------------------- run

    def _init_state_arrays(self, seeds: Sequence[int]):
        mesh = self.mesh
        xs, keys, bstates = [], [], []
        for s in seeds:
            st = self.solver.init_state(jax.random.PRNGKey(int(s)))
            xs.append(np.asarray(st["x"], dtype=np.int32))
            keys.append(np.asarray(st["key"]))
            bstates.append(tuple(st[k] for k in self.state_bucket_keys))
        x = jax.device_put(np.stack(xs),
                           NamedSharding(mesh, P("dp")))
        k = jax.device_put(np.stack(keys),
                           NamedSharding(mesh, P("dp")))
        # per-bucket state: (B, TP, ...) — identical initial state on
        # every shard's own constraints (weights start at one, modifiers
        # at zero, so the per-shard slice IS the init value)
        bucket_state = []
        for key_i in range(len(self.state_bucket_keys)):
            leaves = [b[key_i] for b in bstates]  # per instance tuples
            stacked = jax.tree.map(
                lambda *ls: jnp.stack(ls), *leaves)  # (B, ...)
            stacked = jax.tree.map(
                lambda a: jnp.broadcast_to(
                    a[:, None], (a.shape[0], self.tp) + a.shape[1:]),
                stacked)
            bucket_state.append(jax.device_put(
                stacked, NamedSharding(mesh, P("dp", "tp"))))
        return x, k, tuple(bucket_state)

    def _make_consts(self):
        mesh = self.mesh
        store = jnp.dtype(self.policy.store_dtype)
        # cost-plane attrs ride the store dtype; algorithm-state attrs
        # (weights, modifiers, violation indicators) keep theirs
        store_attrs = {"buckets", "bucket_optima"}

        def place(a, cast):
            if cast and jnp.issubdtype(a.dtype, jnp.floating) \
                    and a.dtype != store:
                a = a.astype(store)
            return jax.device_put(a, NamedSharding(mesh, P("tp")))

        return tuple(
            [jax.tree.map(
                lambda a, _c=(attr in store_attrs): place(a, _c), b)
             for b in self._attr_stacks[attr]]
            for attr in self.bucket_attrs
        )

    def _device_put(self, seeds: Sequence[int]):
        x, k, bucket_state = self._init_state_arrays(seeds)
        return x, k, bucket_state, self._consts()

    # ---------------------------------------------- mesh engine protocol

    def mesh_init(self, seed: int = 0,
                  seeds: Optional[Sequence[int]] = None):
        x, k, bucket_state = self._init_state_arrays(
            self._seeds_for(seed, seeds))
        return {"x": x, "keys": k, "bstate": bucket_state,
                "cycle": jnp.int32(0),
                "finished": jnp.bool_(False)}

    def mesh_step(self, s):
        x, keys, fin, bstate = self._step(
            s["x"], s["keys"], s["bstate"], self._consts())
        out = dict(s)
        # the algorithm's own termination (e.g. DBA's zero-violations
        # rule), checked on the FINAL cycle too — all instances must
        # have fired, exactly like the eager loop's np.all
        out.update(x=x, keys=keys, bstate=bstate,
                   cycle=s["cycle"] + 1, finished=jnp.all(fin))
        return out

    def _build_cost_fn(self, with_violations: bool = False):
        return build_mesh_cost(
            self.mesh, self.V,
            [(c, v, None) for _a, c, v in self.sharded_buckets],
            self._raw_var_costs, x_has_sink=True,
            with_violations=with_violations)

    def message_plane_stats(self):
        from .sharded_localsearch import _value_plane_stats

        return _value_plane_stats(self)

    def _mesh_sel(self, state):
        return state["x"]

    def _decode_sel(self, sel_np: np.ndarray) -> np.ndarray:
        return sel_np[:, :self.V]

    # ------------------------------------------------------------- runs

    def run(self, n_cycles: int, seed: int = 0,
            seeds: Optional[Sequence[int]] = None,
            collect_cost_every: Optional[int] = None,
            collect_metrics: bool = False, spans: bool = False,
            chunk_size: Optional[int] = None,
            timeout: Optional[float] = None
            ) -> Tuple[np.ndarray, int]:
        """Returns ((B, V) selections, cycles run); stops early when
        the algorithm's own termination fires on every instance.
        Cycles execute in compiled chunks on device, the termination
        test included (engine/mesh_engine.py);
        ``collect_metrics``/``spans`` fill the telemetry surfaces."""
        return self._drive_mesh(
            self.mesh_init(seed, seeds), n_cycles,
            collect_cost_every=collect_cost_every,
            collect_metrics=collect_metrics, spans=spans,
            chunk_size=chunk_size, timeout=timeout)

    def run_eager(self, n_cycles: int, seed: int = 0,
                  seeds: Optional[Sequence[int]] = None
                  ) -> Tuple[np.ndarray, int]:
        """Pre-engine loop (one dispatch per cycle): the equivalence
        oracle for the chunked engine and the A/B bench leg."""
        import time as _time

        t0 = _time.perf_counter()
        x, keys, bucket_state, consts = self._device_put(
            self._seeds_for(seed, seeds))
        cycle = 0
        self.finished = False
        for cycle in range(1, n_cycles + 1):
            x, keys, finished, bucket_state = self._step(
                x, keys, bucket_state, consts)
            # checked on the FINAL cycle too, so termination firing
            # exactly at the budget still reports finished
            if bool(np.all(np.asarray(jax.device_get(finished)))):
                self.finished = True
                break
        sel = np.asarray(jax.device_get(x))[:, :self.V]
        self.last_run_stats = self._eager_stats(
            cycle, "FINISHED" if self.finished else "MAX_CYCLES", t0)
        return sel, cycle

    def step_once(self, seed: int = 0) -> np.ndarray:
        x, keys, bucket_state, consts = self._device_put(
            [seed + i for i in range(self.B)])
        x, _k, _f, _b = self._step(x, keys, bucket_state, consts)
        jax.block_until_ready(x)
        return np.asarray(jax.device_get(x))[:, :self.V]


class ShardedMixedDsa(ShardedLocalSearch):
    """MixedDSA (two-tier hard/soft move rule) over the mesh."""

    solver_cls = MixedDsaSolver
    bucket_attrs = ("buckets", "bucket_optima", "hard_buckets")


class ShardedDba(ShardedLocalSearch):
    """Distributed Breakout over the mesh: per-constraint weights live
    on the tp shard owning the constraint."""

    solver_cls = DbaSolver
    bucket_attrs = ("buckets", "bucket_optima", "viol_cubes")
    state_bucket_keys = ("weights",)


class ShardedGdba(ShardedLocalSearch):
    """Generalized DBA over the mesh: modifier hypercubes live with
    their constraints' shard."""

    solver_cls = GdbaSolver
    bucket_attrs = ("buckets", "bucket_optima", "cube_min", "cube_max")
    state_bucket_keys = ("modifiers",)


class ShardedDsaHarness(ShardedLocalSearch):
    """DSA through the generic harness (the hand-written
    :class:`~pydcop_tpu.parallel.sharded_localsearch.ShardedDsa`
    remains the optimized path; this exists to validate the harness
    against a known-good algorithm)."""

    solver_cls = DsaSolver


class ShardedAdsa(ShardedLocalSearch):
    """A-DSA (stochastic per-variable activation) over the mesh."""

    from ..algorithms.adsa import ADsaSolver as solver_cls


class ShardedDsatuto(ShardedLocalSearch):
    """DSA-tuto over the mesh."""

    from ..algorithms.dsatuto import DsaTutoSolver as solver_cls

"""GDBA: Generalized Distributed Breakout for *optimization*.

reference parity: pydcop/algorithms/gdba.py (658 LoC).  Per-constraint
modifier hypercubes live in solver state and are combined with the base
cost tables each cycle:

* ``modifier`` A → effective = base + modifier;
  M → effective = base × (modifier + 1)   (gdba.py:575-600)
* ``violation`` NZ → base > 0; NM → base > min(cube);
  MX → base == max(cube)                   (gdba.py:554-574)
* ``increase_mode`` on quasi-local minimum, from each stuck variable's
  perspective (gdba.py:627-654):
  E → the current-assignment cell, R → all values of the stuck variable
  (others at current), C → the stuck variable's current-value hyperplane,
  T → the whole table.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import BIG, HypergraphArrays
from ..ops.kernels import bucket_cost, candidate_costs
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("modifier", "str", ["A", "M"], "A"),
    AlgoParameterDef("violation", "str", ["NZ", "NM", "MX"], "NZ"),
    AlgoParameterDef("increase_mode", "str", ["E", "R", "C", "T"], "E"),
]


class GdbaSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, modifier: str = "A",
                 violation: str = "NZ", increase_mode: str = "E"):
        super().__init__(arrays, stop_cycle=0)
        self.modifier_mode = modifier
        self.violation_mode = violation
        self.increase_mode = increase_mode
        self.lexic_priority = -jnp.arange(self.V, dtype=jnp.float32)
        # per-constraint min/max over valid cells (for NM/MX violation)
        self.cube_min = []
        self.cube_max = []
        for b in arrays.buckets:
            flat = b.cubes.reshape(b.cubes.shape[0], -1)
            valid = flat < BIG * 0.5
            self.cube_min.append(jnp.asarray(
                np.min(np.where(valid, flat, np.inf), axis=1)))
            self.cube_max.append(jnp.asarray(
                np.max(np.where(valid, flat, -np.inf), axis=1)))

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
            "modifiers": tuple(
                jnp.zeros_like(cubes) for cubes, _ in self.buckets
            ),
        }

    def effective_cubes(self, modifiers):
        out = []
        for (cubes, var_ids), mod in zip(self.buckets, modifiers):
            valid = cubes < BIG * 0.5
            if self.modifier_mode == "A":
                eff = jnp.where(valid, cubes + mod, cubes)
            else:  # M
                eff = jnp.where(valid, cubes * (mod + 1.0), cubes)
            out.append((eff, var_ids))
        return out

    def constraint_violated(self, x, bucket_i):
        """(C,) is each constraint violated at assignment x, per the
        violation mode (evaluated on *base* costs, gdba.py:554-574)."""
        cubes, var_ids = self.buckets[bucket_i]
        cost = bucket_cost(cubes, var_ids, x)
        if self.violation_mode == "NZ":
            return cost > 1e-9
        if self.violation_mode == "NM":
            return cost > self.cube_min[bucket_i] + 1e-9
        return cost >= self.cube_max[bucket_i] - 1e-9  # MX

    def step(self, s):
        key, k_best = jax.random.split(s["key"])
        x, modifiers = s["x"], s["modifiers"]
        ar = jnp.arange(self.V)

        eff = self.effective_cubes(modifiers)
        costs = self.var_costs
        for cubes, var_ids in eff:
            costs = costs + candidate_costs(cubes, var_ids, x, self.V)
        from ..ops.kernels import masked_min, random_argmin

        cur = jnp.where(self.domain_mask, costs, BIG * 2)[ar, x]
        best = masked_min(costs, self.domain_mask)
        best_val = random_argmin(k_best, costs, self.domain_mask)
        improve = cur - best

        nbr_max = self.neighbor_max_gain(improve)
        wins = self.wins_tie(improve, nbr_max, self.lexic_priority)
        move = (improve > 1e-9) & wins
        x_new = jnp.where(move, best_val, x)

        # breakout: quasi-local-minimum variables raise modifiers of their
        # violated constraints
        qlm = (improve <= 1e-9) & (nbr_max <= 1e-9)
        new_mods = []
        for i, ((cubes, var_ids), mod) in enumerate(
                zip(self.buckets, modifiers)):
            arity = cubes.ndim - 1
            C, D = cubes.shape[0], self.D
            violated = self.constraint_violated(x, i)
            vals = x[var_ids]  # (C, arity)
            for p in range(arity):
                amount = jnp.where(
                    violated & qlm[var_ids[:, p]], 1.0, 0.0)  # (C,)
                if self.increase_mode == "T":
                    mod = mod + amount.reshape(
                        (C,) + (1,) * arity)
                    continue
                # work with axis p last: (C, M, D)
                m_t = jnp.moveaxis(mod, p + 1, arity)
                m_shape = m_t.shape
                m_r = m_t.reshape(C, -1, D)
                idx = jnp.zeros((C,), dtype=jnp.int32)
                for q in range(arity):
                    if q != p:
                        idx = idx * D + vals[:, q]
                if self.increase_mode == "E":
                    m_r = m_r.at[jnp.arange(C), idx, vals[:, p]].add(amount)
                elif self.increase_mode == "R":
                    m_r = m_r.at[jnp.arange(C), idx, :].add(
                        amount[:, None])
                else:  # C: whole hyperplane at the current value of p
                    m_r = m_r.at[jnp.arange(C), :, vals[:, p]].add(
                        amount[:, None])
                mod = jnp.moveaxis(m_r.reshape(m_shape), arity, p + 1)
            new_mods.append(mod)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": jnp.bool_(False),
            "key": key,
            "x": x_new,
            "modifiers": tuple(new_mods),
        }

def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> GdbaSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return GdbaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

"""GDBA: Generalized Distributed Breakout for *optimization*.

reference parity: pydcop/algorithms/gdba.py (658 LoC).  Per-constraint
modifier hypercubes live in solver state and are combined with the base
cost tables each cycle:

* ``modifier`` A → effective = base + modifier;
  M → effective = base × (modifier + 1)   (gdba.py:575-600)
* ``violation`` NZ → base > 0; NM → base > min(cube);
  MX → base == max(cube)                   (gdba.py:554-574)
* ``increase_mode`` on quasi-local minimum, from each stuck variable's
  perspective (gdba.py:627-654):
  E → the current-assignment cell, R → all values of the stuck variable
  (others at current), C → the stuck variable's current-value hyperplane,
  T → the whole table.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import BIG, HypergraphArrays
from ..ops.kernels import bucket_cost, candidate_costs
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("modifier", "str", ["A", "M"], "A"),
    AlgoParameterDef("violation", "str", ["NZ", "NM", "MX"], "NZ"),
    AlgoParameterDef("increase_mode", "str", ["E", "R", "C", "T"], "E"),
]


class GdbaSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, modifier: str = "A",
                 violation: str = "NZ", increase_mode: str = "E",
                 stop_cycle: int = 0):
        super().__init__(arrays, stop_cycle=stop_cycle)
        self.modifier_mode = modifier
        self.violation_mode = violation
        self.increase_mode = increase_mode
        self.lexic_priority = -jnp.arange(self.V, dtype=jnp.float32)
        # per-constraint min/max over valid cells (for NM/MX violation)
        self.cube_min = []
        self.cube_max = []
        for b in arrays.buckets:
            flat = b.cubes.reshape(b.cubes.shape[0], -1)
            valid = flat < BIG * 0.5
            self.cube_min.append(jnp.asarray(
                np.min(np.where(valid, flat, np.inf), axis=1)))
            self.cube_max.append(jnp.asarray(
                np.max(np.where(valid, flat, -np.inf), axis=1)))

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
            "modifiers": tuple(
                jnp.zeros_like(cubes) for cubes, _ in self.buckets
            ),
        }

    def effective_cubes(self, modifiers):
        out = []
        for (cubes, var_ids), mod in zip(self.buckets, modifiers):
            valid = cubes < BIG * 0.5
            if self.modifier_mode == "A":
                eff = jnp.where(valid, cubes + mod, cubes)
            else:  # M
                eff = jnp.where(valid, cubes * (mod + 1.0), cubes)
            out.append((eff, var_ids))
        return out

    def constraint_violated(self, x, bucket_i):
        """(C,) is each constraint violated at assignment x, per the
        violation mode (evaluated on *base* costs, gdba.py:554-574)."""
        cubes, var_ids = self.buckets[bucket_i]
        cost = bucket_cost(cubes, var_ids, x)
        if self.violation_mode == "NZ":
            return cost > 1e-9
        if self.violation_mode == "NM":
            return cost > self.cube_min[bucket_i] + 1e-9
        return cost >= self.cube_max[bucket_i] - 1e-9  # MX

    def step(self, s):
        key, k_best = jax.random.split(s["key"])
        x, modifiers = s["x"], s["modifiers"]
        ar = jnp.arange(self.V)

        eff = self.effective_cubes(modifiers)
        acc = jnp.zeros((self.V, self.D))
        for cubes, var_ids in eff:
            acc = acc + candidate_costs(cubes, var_ids, x, self.V)
        costs = self.var_costs + self._reduce_vplane(acc)
        from ..ops.kernels import masked_min, random_argmin

        cur = jnp.where(self.domain_mask, costs, BIG * 2)[ar, x]
        best = masked_min(costs, self.domain_mask)
        best_val = random_argmin(k_best, costs, self.domain_mask)
        improve = cur - best

        nbr_max = self.neighbor_max_gain(improve)
        wins = self.wins_tie(improve, nbr_max, self.lexic_priority)
        move = (improve > 1e-9) & wins
        x_new = jnp.where(move, best_val, x)

        # breakout: quasi-local-minimum variables raise modifiers of their
        # violated constraints
        qlm = (improve <= 1e-9) & (nbr_max <= 1e-9)
        new_mods = []
        for i, ((cubes, var_ids), mod) in enumerate(
                zip(self.buckets, modifiers)):
            arity = cubes.ndim - 1
            C, D = cubes.shape[0], self.D
            violated = self.constraint_violated(x, i)
            vals = x[var_ids]  # (C, arity)
            for p in range(arity):
                amount = jnp.where(
                    violated & qlm[var_ids[:, p]], 1.0, 0.0)  # (C,)
                if self.increase_mode == "T":
                    mod = mod + amount.reshape(
                        (C,) + (1,) * arity)
                    continue
                # work with axis p last: (C, M, D)
                m_t = jnp.moveaxis(mod, p + 1, arity)
                m_shape = m_t.shape
                m_r = m_t.reshape(C, -1, D)
                idx = jnp.zeros((C,), dtype=jnp.int32)
                for q in range(arity):
                    if q != p:
                        idx = idx * D + vals[:, q]
                if self.increase_mode == "E":
                    m_r = m_r.at[jnp.arange(C), idx, vals[:, p]].add(amount)
                elif self.increase_mode == "R":
                    m_r = m_r.at[jnp.arange(C), idx, :].add(
                        amount[:, None])
                else:  # C: whole hyperplane at the current value of p
                    m_r = m_r.at[jnp.arange(C), :, vals[:, p]].add(
                        amount[:, None])
                mod = jnp.moveaxis(m_r.reshape(m_shape), arity, p + 1)
            new_mods.append(mod)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": jnp.bool_(False),
            "key": key,
            "x": x_new,
            "modifiers": tuple(new_mods),
        }

def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> GdbaSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return GdbaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend: GDBA running ON the agent fabric
# (reference: gdba.py:189-658).  ok/improve waves as in DBA, but over
# real costs with per-assignment modifiers: EffCost A/M, IsViolated
# NZ/NM/MX, IncreaseMode E/R/C/T.
# ---------------------------------------------------------------------

import itertools as _it
from typing import Dict

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    SynchronousComputationMixin, VariableComputation, message_type,
    register)
from . import AlgoParameterDef
from ._mp import mp_rng, seed_param, sign_for_mode

algo_params = algo_params + [
    AlgoParameterDef("stop_cycle", "int", None, 0),
    seed_param(),
]

GdbaOkMessage = message_type("gdba_ok", ["value"])
GdbaImproveMessage = message_type("gdba_improve", ["improve"])


class GdbaMpComputation(SynchronousComputationMixin, VariableComputation):
    """Generalized DBA on the agent fabric (reference: gdba.py:189-658).

    Each constraint carries per-assignment modifiers (base 0 additive /
    1 multiplicative); the effective cost of an assignment is
    ``base (+|*) modifier``, and modifiers of violated constraints grow
    when nobody in the neighborhood can improve."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.modifier_mode = params.get("modifier", "A")
        self.violation_mode = params.get("violation", "NZ")
        self.increase_mode = params.get("increase_mode", "E")
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.constraints = list(comp_def.node.constraints)
        self._rnd = mp_rng(params, self.name)
        base = 0.0 if self.modifier_mode == "A" else 1.0
        self._base_modifier = base
        # per-constraint: {frozenset(assignment.items()): modifier}
        self._modifiers = [dict() for _ in self.constraints]
        self._extrema = []
        for c in self.constraints:
            m = c.to_matrix().matrix
            self._extrema.append((float(m.min()), float(m.max())))
        self._neighbor_values: Dict[str, object] = {}
        self._neighbor_improves: Dict[str, float] = {}
        self._my_improve = 0.0
        self._new_value = None
        self._current_eval = 0.0
        self._violated = []

    def on_start(self):
        self.start_cycle()
        self.value_selection(
            self._rnd.choice(list(self.variable.domain.values)))
        if not self.neighbors:
            self.finished()
            return
        self.post_to_all_neighbors(
            GdbaOkMessage(self.current_value), MSG_ALGO)

    def on_fast_forward(self, cycle_id):
        if cycle_id % 2 == 0:
            self.post_to_all_neighbors(
                GdbaOkMessage(self.current_value), MSG_ALGO)
        else:
            self.post_to_all_neighbors(GdbaImproveMessage(0.0), MSG_ALGO)

    @register("gdba_ok")
    def _on_ok(self, sender, msg, t):  # pragma: no cover
        pass  # rounds are delivered through on_new_cycle

    @register("gdba_improve")
    def _on_improve(self, sender, msg, t):  # pragma: no cover
        pass

    def on_new_cycle(self, messages, cycle_id):
        if cycle_id % 2 == 0:
            self._ok_phase(messages)
        else:
            self._improve_phase(messages)

    # ------------------------------------------------------- internals

    def _scope_assignment(self, c, val):
        assignment = dict(self._neighbor_values)
        assignment[self.variable.name] = val
        return {n: assignment[n] for n in c.scope_names}

    def _eff_cost(self, i, asgt):
        """base cost combined with the assignment's modifier
        (reference: gdba.py:576-600)."""
        c = self.constraints[i]
        base = c(**asgt)
        mod = self._modifiers[i].get(
            frozenset(asgt.items()), self._base_modifier)
        return base + mod if self.modifier_mode == "A" else base * mod

    def _is_violated(self, i, asgt):
        """NZ: non-zero cost, NM: above the constraint's own minimum,
        MX: at its maximum (reference: gdba.py:552-574)."""
        c = self.constraints[i]
        cost = c(**asgt)
        mini, maxi = self._extrema[i]
        if self.violation_mode == "NZ":
            return cost != 0
        if self.violation_mode == "NM":
            return cost != mini
        return cost == maxi

    def _eval_value(self, val):
        """(signed effective cost, violated constraint indices) under
        the neighbors' values (reference: gdba.py:428-461)."""
        sign = sign_for_mode(self.mode)
        total = sign * self.variable.cost_for_val(val)
        violated = []
        for i, c in enumerate(self.constraints):
            asgt = self._scope_assignment(c, val)
            total += sign * self._eff_cost(i, asgt)
            if self._is_violated(i, asgt):
                violated.append(i)
        return total, violated

    def _ok_phase(self, messages):
        for sender, (msg, _) in messages.items():
            self._neighbor_values[sender] = msg.value
        self._current_eval, self._violated = self._eval_value(
            self.current_value)
        best_vals, best_eval = [], None
        for v in self.variable.domain.values:
            ev, _ = self._eval_value(v)
            if best_eval is None or ev < best_eval - 1e-9:
                best_vals, best_eval = [v], ev
            elif ev <= best_eval + 1e-9:
                best_vals.append(v)
        self._my_improve = self._current_eval - best_eval
        if self._my_improve > 1e-9:
            self._new_value = self._rnd.choice(best_vals)
        else:
            self._new_value = self.current_value
        self.post_to_all_neighbors(
            GdbaImproveMessage(self._my_improve), MSG_ALGO)

    def _improve_phase(self, messages):
        """Strictly-best improver moves (sorted-name tie-break); if the
        whole neighborhood is stuck, increase the violated constraints'
        modifiers per increase_mode (reference: gdba.py:494-550)."""
        self._neighbor_improves = {
            sender: float(msg.improve)
            for sender, (msg, _) in messages.items()}
        maxi = self._my_improve
        max_list = [self.name]
        for n, imp in self._neighbor_improves.items():
            if imp > maxi + 1e-9:
                maxi, max_list = imp, [n]
            elif abs(imp - maxi) <= 1e-9:
                max_list.append(n)
        if self._my_improve > 1e-9:
            if sorted(max_list)[0] == self.name:
                sign = sign_for_mode(self.mode)
                self.value_selection(
                    self._new_value,
                    sign * (self._current_eval - self._my_improve))
        elif abs(maxi) <= 1e-9:
            for i in self._violated:
                self._increase_modifiers(i)

        self._neighbor_values.clear()
        self._neighbor_improves.clear()
        self._violated = []
        self.new_cycle()
        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()
            return
        self.post_to_all_neighbors(
            GdbaOkMessage(self.current_value), MSG_ALGO)

    def _increase_modifiers(self, i):
        """E: this assignment only; R: my whole row; C: the column (all
        neighbor assignments, my value fixed); T: every cell
        (reference: gdba.py:622-651)."""
        c = self.constraints[i]
        if self.increase_mode == "E":
            self._bump(i, self._scope_assignment(c, self.current_value))
        elif self.increase_mode == "R":
            for v in self.variable.domain.values:
                self._bump(i, self._scope_assignment(c, v))
        elif self.increase_mode in ("C", "T"):
            others = [d for d in c.dimensions
                      if d.name != self.variable.name]
            for combo in _it.product(
                    *[list(d.domain.values) for d in others]):
                asgt = dict(zip([d.name for d in others], combo))
                if self.increase_mode == "C":
                    asgt[self.variable.name] = self.current_value
                    if self.variable.name not in c.scope_names:
                        asgt.pop(self.variable.name)
                    self._bump(i, asgt)
                else:
                    for v in self.variable.domain.values:
                        full = dict(asgt)
                        if self.variable.name in c.scope_names:
                            full[self.variable.name] = v
                        self._bump(i, full)
        else:  # pragma: no cover - validated by algo_params
            raise ValueError(self.increase_mode)

    def _bump(self, i, asgt):
        key = frozenset(asgt.items())
        self._modifiers[i][key] = self._modifiers[i].get(
            key, self._base_modifier) + 1.0


def build_computation(comp_def) -> GdbaMpComputation:
    return GdbaMpComputation(comp_def)

"""Synchronous MGM (Maximum Gain Message).

reference parity: pydcop/algorithms/mgm.py (609 LoC).  The reference's two
message phases per cycle — value messages, then gain messages, mover =
strictly largest gain among neighbors with lexic/random tie-break
(mgm.py:213-420) — collapse into one jitted step: gains for all variables
are computed at once and the "largest gain in my neighborhood" test is a
segment-max over the variable-pair edge list.  Monotonic: only moves with
strictly positive gain.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("break_mode", "str", ["lexic", "random"], "lexic"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
]


class MgmSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays,
                 break_mode: str = "lexic", stop_cycle: int = 0):
        super().__init__(arrays, stop_cycle)
        self.break_mode = break_mode
        # lexic tie-break: lower variable index wins -> encode priority as
        # -index so that "higher priority wins" applies uniformly
        self.lexic_priority = -jnp.arange(self.V, dtype=jnp.float32)

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_pri = jax.random.split(s["key"], 3)
        x = s["x"]
        _, cur, best_cost, best_val = self.best_response(k_best, x)
        gain = cur - best_cost  # >= 0

        if self.break_mode == "random":
            priority = jax.random.uniform(k_pri, (self.V,))
        else:
            priority = self.lexic_priority
        nbr_max = self.neighbor_max_gain(gain)
        wins = self.wins_tie(gain, nbr_max, priority)
        change = (gain > 1e-9) & wins
        x_new = jnp.where(change, best_val, x)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> MgmSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return MgmSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

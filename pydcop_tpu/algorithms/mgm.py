"""Synchronous MGM (Maximum Gain Message).

reference parity: pydcop/algorithms/mgm.py (609 LoC).  The reference's two
message phases per cycle — value messages, then gain messages, mover =
strictly largest gain among neighbors with lexic/random tie-break
(mgm.py:213-420) — collapse into one jitted step: gains for all variables
are computed at once and the "largest gain in my neighborhood" test is a
segment-max over the variable-pair edge list.  Monotonic: only moves with
strictly positive gain.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("break_mode", "str", ["lexic", "random"], "lexic"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    # mixed-precision policy (ops/precision.py): bf16 cost planes with
    # f32 accumulation; None defers to PYDCOP_TPU_PRECISION, then f32
    AlgoParameterDef("precision", "str", ["f32", "bf16", "auto"], None),
]


class MgmSolver(LocalSearchSolver):
    # pad-stable per-variable draws: a shape-padded fused campaign row
    # must reproduce its unpadded subprocess solve bit-exactly
    pad_stable_rng = True

    def __init__(self, arrays: HypergraphArrays,
                 break_mode: str = "lexic", stop_cycle: int = 0,
                 precision=None):
        super().__init__(arrays, stop_cycle, precision=precision)
        self.break_mode = break_mode
        # lexic tie-break: lower variable index wins -> encode priority as
        # -index so that "higher priority wins" applies uniformly
        self.lexic_priority = -jnp.arange(self.V, dtype=jnp.float32)

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_pri = jax.random.split(s["key"], 3)
        x = s["x"]
        _, cur, best_cost, best_val = self.best_response(k_best, x)
        gain = cur - best_cost  # >= 0

        if self.break_mode == "random":
            priority = self.uniform_v(k_pri)
        else:
            priority = self.lexic_priority
        nbr_max = self.neighbor_max_gain(gain)
        wins = self.wins_tie(gain, nbr_max, priority)
        change = (gain > 1e-9) & wins
        x_new = jnp.where(change, best_val, x)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> MgmSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints,
                                    precision=params.get("precision"))
    return MgmSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend: MGM running ON the agent fabric
# (reference: mgm.py:213-420).  Two alternating synchronous phases —
# value messages, then gain messages; the strictly-largest gain in the
# neighborhood moves, ties broken lexic (lower name) or random.  Used by
# orchestrated runs; the compiled solver above is the data plane.
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    SynchronousComputationMixin, VariableComputation, message_type,
    register)
from ._mp import EPS, best_response, local_cost, mp_rng, seed_param, \
    sign_for_mode

algo_params = algo_params + [seed_param()]

MgmValueMessage = message_type("mgm_value", ["value"])
#: priority carries the sender's tie-break token: the random draw for
#: break_mode=random, unused for lexic (names compare instead)
MgmGainMessage = message_type("mgm_gain", ["gain", "priority"])


class MgmMpComputation(SynchronousComputationMixin, VariableComputation):
    """Synchronous MGM on the agent fabric (reference: mgm.py:213-420).
    Phase alternation rides the sync-mixin cycle parity: even cycles
    deliver value messages, odd cycles deliver gain messages."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.break_mode = params.get("break_mode", "lexic")
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.constraints = list(comp_def.node.constraints)
        self._neighbor_values: Dict[str, object] = {}
        self._gain = 0.0
        self._candidate = None
        self._priority = 0.0
        self._rnd = mp_rng(params, self.name)

    def on_start(self):
        self.start_cycle()
        self.value_selection(
            self._rnd.choice(list(self.variable.domain.values)))
        self.post_to_all_neighbors(
            MgmValueMessage(self.current_value), MSG_ALGO)
        if not self.neighbors:
            # no neighbors: a pure local optimization, done immediately
            _, best, cost = best_response(
                self.variable, self.constraints, {}, self.current_value,
                self.mode)
            self.value_selection(best, cost)
            self.finished()

    def on_fast_forward(self, cycle_id):
        # rejoin for the round being joined: even rounds carry values,
        # odd rounds carry gains
        if cycle_id % 2 == 0:
            self.post_to_all_neighbors(
                MgmValueMessage(self.current_value), MSG_ALGO)
        else:
            self.post_to_all_neighbors(
                MgmGainMessage(0.0, 0.0), MSG_ALGO)

    @register("mgm_value")
    def _on_value(self, sender, msg, t):  # pragma: no cover
        pass

    @register("mgm_gain")
    def _on_gain(self, sender, msg, t):  # pragma: no cover
        pass

    def on_new_cycle(self, messages, cycle_id):
        if cycle_id % 2 == 0:
            self._value_phase(messages)
        else:
            self._gain_phase(messages)

    def _value_phase(self, messages):
        """Collect neighbor values, compute my best gain, announce it
        (reference: mgm.py:213-300)."""
        for sender, (msg, _) in messages.items():
            self._neighbor_values[sender] = msg.value
        cur, best, best_cost = best_response(
            self.variable, self.constraints, self._neighbor_values,
            self.current_value, self.mode, prefer_different=False,
            rnd=self._rnd)
        sign = sign_for_mode(self.mode)
        self._gain = sign * (cur - best_cost) if cur is not None else 0.0
        self._candidate = best
        self._priority = self._rnd.random()
        self.post_to_all_neighbors(
            MgmGainMessage(self._gain, self._priority), MSG_ALGO)

    def _gain_phase(self, messages):
        """Move iff my gain strictly beats every neighbor's, ties broken
        by break_mode (reference: mgm.py:300-420).  Monotonic: only
        strictly-improving moves."""
        wins = True
        for sender, (msg, _) in messages.items():
            g = float(msg.gain or 0.0)
            if g > self._gain + EPS:
                wins = False
            elif abs(g - self._gain) <= EPS:
                if self.break_mode == "random":
                    # identical draws: fall back to name order
                    if (msg.priority, sender) > (self._priority,
                                                 self.name):
                        wins = False
                elif sender < self.name:  # lexic: lower name wins
                    wins = False
        if wins and self._gain > EPS:
            assignment = dict(self._neighbor_values)
            assignment[self.variable.name] = self._candidate
            self.value_selection(
                self._candidate,
                local_cost(self.variable, self.constraints, assignment))
        self.new_cycle()
        # one MGM iteration = value + gain phase: count full iterations
        # (self._cycle_count, bumped by new_cycle), not mixin half-rounds
        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()
            return
        self.post_to_all_neighbors(
            MgmValueMessage(self.current_value), MSG_ALGO)


def build_computation(comp_def) -> MgmMpComputation:
    return MgmMpComputation(comp_def)

"""DPOP: exact dynamic programming on a DFS pseudo-tree.

reference parity: pydcop/algorithms/dpop.py (441 LoC).  Same two sweeps —
UTIL (leaves → root): each node joins its constraints with its children's
UTIL tables and projects out its own variable; VALUE (root → leaves): each
node slices its joined table at the ancestors' chosen values and picks the
arg-optimum (dpop.py:313-439).

The reference implements ``join``/``projection`` as per-assignment Python
loops over every cell of the util hypercube (relations.py:1672-1760) —
exponential Python interpreter time in the separator width.  Here both are
single vectorized broadcast-add / axis-reduce array ops
(pydcop_tpu.dcop.relations.join/projection), the shape XLA and numpy
execute at memory bandwidth.  The sweep itself is host-orchestrated (tree
levels are heterogeneous in shape); per-level tables could be pushed to
device in one batch per unique separator shape, which matters only for
very deep trees.

Memory caution (same as every DPOP): the UTIL table of a node is
exponential in its separator size.  ``memory_limit`` guards against
accidental blow-ups with a clear error instead of an OOM.
"""

import functools
from typing import Any, Dict, Optional

import numpy as np

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import (
    NAryMatrixRelation,
    UnaryFunctionRelation,
    join,
    projection,
)
from ..engine.solver import RunResult
from ..graphs import pseudotree

GRAPH_TYPE = "pseudotree"

algo_params = []

#: device path kicks in when the predicted UTIL work crosses this many
#: table cells — below it, per-level dispatch overhead beats the win
DEVICE_AUTO_CELLS = 2_000_000


def _util_plans(g, var_cost_rel):
    """Host-side schedule for the device UTIL sweep: for every node, the
    output dims (separator..., own variable LAST — the uniform
    projection axis) and the input tables with their axis mappings."""
    plans = {}
    for level in reversed(g.depth_ordered()):
        for node in level:
            inputs = []  # (kind, payload, dim_names)
            own = node.variable
            if node.name in var_cost_rel:
                rel = var_cost_rel[node.name]
                costs = np.asarray(
                    [rel(**{node.name: v}) for v in own.domain.values],
                    dtype=np.float32)
                inputs.append(("const", costs, (node.name,)))
            for c in node.constraints:
                m = c.to_matrix()
                inputs.append(("const",
                               np.asarray(m.matrix, dtype=np.float32),
                               tuple(v.name for v in m.dimensions)))
            for child in node.children:
                child_dims = plans[child]["sep_dims"]
                inputs.append(("child", child, child_dims))
            sep = []
            for _, _, dims in inputs:
                for d in dims:
                    if d != node.name and d not in sep:
                        sep.append(d)
            sep.sort()
            out_dims = tuple(sep) + (node.name,)
            plans[node.name] = {
                "node": node,
                "inputs": inputs,
                "out_dims": out_dims,
                "sep_dims": tuple(sep),
            }
    return plans


def _domain_sizes(g):
    sizes = {}
    for node in g.nodes:
        sizes[node.name] = len(node.variable.domain)
    return sizes


def device_util_sweep(g, var_cost_rel, mode: str,
                      memory_limit: int = 10 ** 8):
    """UTIL phase on the accelerator: per tree level, nodes are grouped
    by their join *signature* (output shape + every input's shape and
    axis mapping) and each group runs as ONE jitted stacked
    broadcast-add + axis-min over all its nodes — the batching that
    makes tiny per-node tables worth a device dispatch
    (VERDICT r2 item 3; the reference's joins are per-cell Python
    loops, relations.py:1672-1760).

    Returns {node name: joined numpy table over plan out_dims}.
    """
    import jax
    import jax.numpy as jnp

    plans = _util_plans(g, var_cost_rel)
    sizes = _domain_sizes(g)
    reduce_fn = jnp.min if mode == "min" else jnp.max

    def run_group(out_shape, input_specs, stacked):
        # eager (unjitted) device ops: one dispatch per input, no
        # per-signature compilation — real DCOP trees are heterogeneous
        # enough (dozens of distinct signatures) that tracing each
        # would cost more than the whole sweep
        n = stacked[0].shape[0]
        total = jnp.zeros((n,) + out_shape, dtype=jnp.float32)
        for arr, (_shape, bdims) in zip(stacked, input_specs):
            total = total + jax.lax.broadcast_in_dim(
                jnp.asarray(arr), (n,) + out_shape,
                (0,) + tuple(d + 1 for d in bdims))
        return total, reduce_fn(total, axis=-1)

    joined_of = {}
    util_of = {}
    for level in reversed(g.depth_ordered()):
        groups = {}
        for node in level:
            plan = plans[node.name]
            out_dims = plan["out_dims"]
            out_shape = tuple(sizes[d] for d in out_dims)
            if int(np.prod(out_shape)) > memory_limit:
                raise MemoryError(
                    f"DPOP UTIL table for {node.name} exceeds memory "
                    f"limit: shape {out_shape}")
            axis_of = {d: i for i, d in enumerate(out_dims)}
            specs = []
            arrays = []
            for kind, payload, dims in plan["inputs"]:
                arr = payload if kind == "const" else util_of[payload]
                positions = [axis_of[d] for d in dims]
                # broadcast_in_dim needs strictly increasing target
                # axes: pre-transpose on host into output-axis order
                perm = sorted(range(len(positions)),
                              key=lambda i: positions[i])
                if perm != list(range(len(positions))):
                    arr = np.ascontiguousarray(
                        np.transpose(arr, perm))
                    positions = [positions[i] for i in perm]
                specs.append((tuple(arr.shape), tuple(positions)))
                arrays.append(arr)
            sig = (out_shape, tuple(specs))
            groups.setdefault(sig, []).append((node.name, arrays))
        for (out_shape, specs), members in groups.items():
            stacked = [
                np.stack([arrays[i] for _, arrays in members])
                for i in range(len(specs))
            ]
            joined, util = run_group(out_shape, specs, stacked)
            # utils feed the next level's joins (host staging keeps the
            # level loop simple; the math itself ran on device); joined
            # tables come back for the host VALUE slicing
            joined = np.asarray(jax.device_get(joined))
            util = np.asarray(jax.device_get(util))
            for row, (name, _) in enumerate(members):
                joined_of[name] = joined[row]
                util_of[name] = util[row]
    return plans, joined_of


def computation_memory(*args, **kwargs):
    """Not defined for DPOP (reference: dpop.py:80-85 raises too)."""
    raise NotImplementedError("DPOP has no computation_memory model")


def communication_load(*args, **kwargs):
    raise NotImplementedError("DPOP has no communication_load model")


def message_size(util: NAryMatrixRelation) -> int:
    """UTIL message size = product of its dims (reference: dpop.py:88-109)."""
    return int(np.prod(util.matrix.shape)) if util.arity else 1


def solve_direct(dcop: DCOP, params: Optional[Dict] = None,
                 memory_limit: int = 10 ** 8,
                 timeout: Optional[float] = None,
                 device: str = "auto",
                 **_kwargs) -> RunResult:
    """Run DPOP to optimality (or TIMEOUT with an empty assignment —
    DPOP has no meaningful anytime solution mid-UTIL-sweep).

    ``device``: "host" = vectorized numpy joins; "jax" = the batched
    device UTIL sweep (:func:`device_util_sweep`); "auto" picks the
    device once the predicted UTIL work crosses ``DEVICE_AUTO_CELLS``.
    """
    import time

    t0 = time.perf_counter()
    if params:
        device = params.get("device", device) or device

    def out_of_time():
        return timeout is not None and time.perf_counter() - t0 > timeout
    mode = dcop.objective
    g = pseudotree.build_computation_graph(dcop)

    # fold variable costs in as unary relations so they take part in the
    # optimization (the reference models them through variable computations)
    var_cost_rel: Dict[str, UnaryFunctionRelation] = {}
    for v in dcop.variables.values():
        if v.has_cost:
            var_cost_rel[v.name] = UnaryFunctionRelation(
                f"__cost_{v.name}", v, v.cost_for_val)

    if device == "auto":
        sizes = _domain_sizes(g)
        cells = 0
        for name, plan in _util_plans(g, var_cost_rel).items():
            cells += int(np.prod([sizes[d]
                                  for d in plan["out_dims"]]))
        device = "jax" if cells >= DEVICE_AUTO_CELLS else "host"
    if device == "jax":
        return _solve_device(dcop, g, var_cost_rel, mode, memory_limit,
                             t0, timeout)

    levels = g.depth_ordered()
    util_of: Dict[str, Any] = {}
    joined_of: Dict[str, Any] = {}
    msg_count, msg_size = 0, 0

    # --- UTIL phase: deepest level first -----------------------------------
    for level in reversed(levels):
        for node in level:
            if out_of_time():
                return RunResult({}, 0, False, float("inf"), 0,
                                 time.perf_counter() - t0,
                                 status="TIMEOUT")
            rel = NAryMatrixRelation([node.variable],
                                     name=f"util_{node.name}")
            if node.name in var_cost_rel:
                rel = join(rel, var_cost_rel[node.name].to_matrix())
            for c in node.constraints:
                rel = join(rel, c.to_matrix())
            for child in node.children:
                rel = join(rel, util_of[child])
            if rel.matrix.size > memory_limit:
                raise MemoryError(
                    f"DPOP UTIL table for {node.name} exceeds memory "
                    f"limit: shape {rel.matrix.shape}"
                )
            joined_of[node.name] = rel
            if not node.is_root:
                util = projection(rel, node.variable, mode)
                util_of[node.name] = util
                msg_count += 1
                msg_size += message_size(util) \
                    if hasattr(util, "matrix") else 1

    # --- VALUE phase: root level first -------------------------------------
    assignment: Dict[str, Any] = {}
    for level in levels:
        for node in level:
            rel = joined_of[node.name]
            fixed = {
                n: assignment[n] for n in rel.scope_names
                if n != node.name and n in assignment
            }
            sliced = rel.slice(fixed) if fixed else rel
            costs = np.asarray(sliced.matrix).reshape(-1)
            i = int(np.argmin(costs) if mode == "min"
                    else np.argmax(costs))
            assignment[node.name] = node.variable.domain.values[i]
            if not node.is_root:
                msg_count += 1

    cost, violations = dcop.solution_cost(assignment)
    return RunResult(
        assignment=assignment,
        cycles=len(levels),
        finished=True,
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status="FINISHED",
        metrics={"msg_count": msg_count, "msg_size": msg_size},
    )


def _solve_device(dcop, g, var_cost_rel, mode, memory_limit, t0,
                  timeout):
    """Device path: batched UTIL sweep on the accelerator, VALUE phase
    host-side over the returned joined tables (tiny slicing argmins)."""
    import time

    plans, joined_of = device_util_sweep(
        g, var_cost_rel, mode, memory_limit=memory_limit)
    levels = g.depth_ordered()
    dom_index = {
        node.name: {v: i for i, v in
                    enumerate(node.variable.domain.values)}
        for node in g.nodes
    }
    assignment: Dict[str, Any] = {}
    msg_count, msg_size = 0, 0
    for level in levels:
        for node in level:
            arr = joined_of[node.name]
            dims = plans[node.name]["out_dims"]
            idx = tuple(
                dom_index[d][assignment[d]] if d != node.name
                else slice(None) for d in dims)
            costs = np.asarray(arr[idx]).reshape(-1)
            i = int(np.argmin(costs) if mode == "min"
                    else np.argmax(costs))
            assignment[node.name] = node.variable.domain.values[i]
            if not node.is_root:
                # one UTIL message up + one VALUE message down per node
                msg_count += 2
                msg_size += int(np.prod(arr.shape[:-1]))
    cost, violations = dcop.solution_cost(assignment)
    return RunResult(
        assignment=assignment,
        cycles=len(levels),
        finished=True,
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status="FINISHED",
        metrics={"msg_count": msg_count, "msg_size": msg_size,
                 "device": "jax"},
    )


# ---------------------------------------------------------------------
# Message-passing backend: DPOP running ON the agent fabric
# (reference: dpop.py:151-441).  UTIL tables flow leaves -> root, VALUE
# assignments root -> leaves; each node's join/projection is the same
# vectorized broadcast-add / axis-reduce used by solve_direct above (the
# reference's per-cell Python loops, relations.py:1672-1760, never
# appear).  UTIL tables cross the wire as (dims, nested costs) lists so
# the JSON transport carries them between processes / machines.
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    VariableComputation, message_type, register)
from ..dcop.objects import Domain
from ..dcop.relations import find_arg_optimal

#: dims: [[var_name, [domain values...]], ...], costs: nested list with
#: one axis per dim (JSON-safe: the reference ships pickled relation
#: objects instead, dpop.py:88-109)
DpopUtilMessage = message_type("dpop_util", ["dims", "costs"])
#: assignment: [[var_name, value], ...] for the receiver's separator
DpopValueMessage = message_type("dpop_value", ["assignment"])


def _wire_util(util: NAryMatrixRelation):
    dims = [[v.name, list(v.domain.values)] for v in util.dimensions]
    return dims, util.matrix.tolist()


def _unwire_util(dims, costs) -> NAryMatrixRelation:
    variables = [
        Variable(name, Domain(f"d_{name}", "", values))
        for name, values in dims]
    return NAryMatrixRelation(variables, np.asarray(costs),
                              name="util")


class DpopMpComputation(VariableComputation):
    """One DPOP variable on the agent fabric (reference: dpop.py:151-441).

    Asynchronous by construction: leaves fire their UTIL at start; every
    node forwards once all children reported; the root kicks off the
    VALUE wave and each node finishes right after selecting its value
    (DPOP is not iterative — reference dpop.py:292-312)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        node = comp_def.node
        self.mode = comp_def.algo.mode
        self.parent = node.parent
        self.children = list(node.children)
        self.constraints = list(node.constraints)
        # lowest-node rule is already applied by the graph build
        # (graphs/pseudotree.py), unlike the reference which re-filters
        # in the computation (dpop.py:186-202)
        self._waited_children = set(self.children)
        self._children_separator: Dict[str, list] = {}
        rel = NAryMatrixRelation([self.variable],
                                 name=f"util_{self.name}")
        if self.variable.has_cost:
            costs = [self.variable.cost_for_val(v)
                     for v in self.variable.domain.values]
            rel = join(rel, NAryMatrixRelation(
                [self.variable], np.asarray(costs),
                name=f"cost_{self.name}"))
        self._joined_utils = rel

    @property
    def is_root(self):
        return self.parent is None

    @property
    def is_leaf(self):
        return not self.children

    def on_start(self):
        if self.is_leaf and not self.is_root:
            util = self._compute_util()
            dims, costs = _wire_util(util)
            self.post_msg(self.parent, DpopUtilMessage(dims, costs),
                          MSG_ALGO)
        elif self.is_leaf:
            # isolated variable: optimize alone (reference: dpop.py:255-283)
            for c in self.constraints:
                self._joined_utils = join(self._joined_utils,
                                          c.to_matrix())
            values, cost = find_arg_optimal(
                self.variable, self._joined_utils, self.mode)
            self._select_and_finish(values[0], float(cost))

    def _compute_util(self) -> NAryMatrixRelation:
        for c in self.constraints:
            self._joined_utils = join(self._joined_utils, c.to_matrix())
        return projection(self._joined_utils, self.variable, self.mode)

    def _select_and_finish(self, value, cost):
        self.value_selection(value, cost)
        self.finished()

    @register("dpop_util")
    def _on_util(self, sender, msg, t):
        util = _unwire_util(msg.dims, msg.costs)
        self._joined_utils = join(self._joined_utils, util)
        self._waited_children.discard(sender)
        self._children_separator[sender] = [d[0] for d in msg.dims]
        if self._waited_children:
            return
        if self.is_root:
            for c in self.constraints:
                self._joined_utils = join(self._joined_utils,
                                          c.to_matrix())
            values, cost = find_arg_optimal(
                self.variable, self._joined_utils, self.mode)
            selected = values[0]
            for child in self.children:
                self.post_msg(child, DpopValueMessage(
                    [[self.name, selected]]), MSG_ALGO)
            self._select_and_finish(selected, float(cost))
        else:
            util = self._compute_util()
            dims, costs = _wire_util(util)
            self.post_msg(self.parent, DpopUtilMessage(dims, costs),
                          MSG_ALGO)

    @register("dpop_value")
    def _on_value(self, sender, msg, t):
        value_dict = {name: value for name, value in msg.assignment}
        fixed = {n: value_dict[n]
                 for n in self._joined_utils.scope_names
                 if n != self.name and n in value_dict}
        rel = self._joined_utils.slice(fixed) if fixed \
            else self._joined_utils
        values, cost = find_arg_optimal(self.variable, rel, self.mode)
        selected = values[0]
        for child in self.children:
            assignment = [[self.name, selected]]
            for v in self._children_separator.get(child, []):
                if v in value_dict:
                    assignment.append([v, value_dict[v]])
            self.post_msg(child, DpopValueMessage(assignment), MSG_ALGO)
        self._select_and_finish(selected, float(cost))


def build_computation(comp_def) -> DpopMpComputation:
    return DpopMpComputation(comp_def)

"""DPOP: exact dynamic programming on a DFS pseudo-tree.

reference parity: pydcop/algorithms/dpop.py (441 LoC).  Same two sweeps —
UTIL (leaves → root): each node joins its constraints with its children's
UTIL tables and projects out its own variable; VALUE (root → leaves): each
node slices its joined table at the ancestors' chosen values and picks the
arg-optimum (dpop.py:313-439).

The reference implements ``join``/``projection`` as per-assignment Python
loops over every cell of the util hypercube (relations.py:1672-1760) —
exponential Python interpreter time in the separator width.  Here both are
single vectorized broadcast-add / axis-reduce array ops
(pydcop_tpu.dcop.relations.join/projection), the shape XLA and numpy
execute at memory bandwidth.  The sweep itself is host-orchestrated (tree
levels are heterogeneous in shape); per-level tables could be pushed to
device in one batch per unique separator shape, which matters only for
very deep trees.

Memory caution (same as every DPOP): the UTIL table of a node is
exponential in its separator size.  ``memory_limit`` guards against
accidental blow-ups with a clear error instead of an OOM.
"""

import functools
from typing import Any, Dict, Optional

import numpy as np

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import (
    NAryMatrixRelation,
    UnaryFunctionRelation,
    join,
    projection,
)
from ..engine.solver import RunResult
from ..graphs import pseudotree
from . import AlgoParameterDef

GRAPH_TYPE = "pseudotree"

algo_params = [
    # execution engine for the UTIL/VALUE sweeps: vectorized-numpy
    # host path, the jitted device spine, or auto-select on predicted
    # table work (see device_util_sweep)
    AlgoParameterDef("device", "str", ["auto", "host", "jax"], "auto"),
]

#: compiled spine programs, keyed by the spine's structural signature —
#: re-solving the same problem shape (the normal batch/bench pattern)
#: reuses the executable instead of re-tracing and re-compiling.
#: Bounded: a stream of structurally distinct problems would otherwise
#: accumulate XLA executables forever
_SPINE_CACHE: Dict[Any, Any] = {}
_SPINE_CACHE_MAX = 16

#: device path kicks in when the predicted UTIL work crosses this many
#: table cells — below it, per-level dispatch overhead beats the win
DEVICE_AUTO_CELLS = 2_000_000


def _util_plans(g, var_cost_rel):
    """Host-side schedule for the device UTIL sweep: for every node, the
    output dims (separator..., own variable LAST — the uniform
    projection axis) and the input tables with their axis mappings."""
    plans = {}
    for level in reversed(g.depth_ordered()):
        for node in level:
            inputs = []  # (kind, payload, dim_names)
            own = node.variable
            if node.name in var_cost_rel:
                rel = var_cost_rel[node.name]
                costs = np.asarray(
                    [rel(**{node.name: v}) for v in own.domain.values],
                    dtype=np.float32)
                inputs.append(("const", costs, (node.name,)))
            for c in node.constraints:
                m = c.to_matrix()
                inputs.append(("const",
                               np.asarray(m.matrix, dtype=np.float32),
                               tuple(v.name for v in m.dimensions)))
            for child in node.children:
                child_dims = plans[child]["sep_dims"]
                inputs.append(("child", child, child_dims))
            sep = []
            for _, _, dims in inputs:
                for d in dims:
                    if d != node.name and d not in sep:
                        sep.append(d)
            sep.sort()
            out_dims = tuple(sep) + (node.name,)
            plans[node.name] = {
                "node": node,
                "inputs": inputs,
                "out_dims": out_dims,
                "sep_dims": tuple(sep),
            }
    return plans


def _domain_sizes(g):
    sizes = {}
    for node in g.nodes:
        sizes[node.name] = len(node.variable.domain)
    return sizes


def _pack_input(arr: np.ndarray, dims, out_dims, sizes):
    """Host-side prep of one input table for the packed device layout.

    The device table's two minormost dims (last separator dim, own
    variable) are merged into one axis of size ``s_last * s_own`` so the
    minor dim is a lane-friendly multiple of 128 instead of a tiny
    domain that TPU tiling would pad 8x (a 1 GB table would occupy
    8 GB of HBM in naive (…, 16, 16) layout).  Inputs touching either
    merged dim are expanded over BOTH (inputs are small — constraint
    matrices and child utils, far below the table size) and reshaped so
    their last axis is the merged pair; all other dims map one-to-one.

    Returns (packed array, packed axis positions).
    """
    pair = out_dims[-2:]
    axis_of = {d: i for i, d in enumerate(out_dims)}
    # sort input dims into output order first
    order = sorted(range(len(dims)), key=lambda i: axis_of[dims[i]])
    if order != list(range(len(dims))):
        arr = np.transpose(arr, order)
        dims = tuple(dims[i] for i in order)
    touches = [d for d in dims if d in pair]
    lead = [d for d in dims if d not in pair]
    n_packed_axes = len(out_dims) - 1
    if not touches:
        return arr, tuple(axis_of[d] for d in dims)
    # expand over the full merged pair, then fold it into one axis
    shape = tuple(arr.shape[: len(lead)]) + tuple(
        arr.shape[len(lead) + touches.index(d)] if d in touches else 1
        for d in pair)
    arr = arr.reshape(shape)
    full = tuple(arr.shape[: len(lead)]) + tuple(
        sizes[d] for d in pair)
    arr = np.ascontiguousarray(np.broadcast_to(arr, full))
    arr = arr.reshape(arr.shape[: len(lead)] + (-1,))
    positions = tuple(axis_of[d] for d in lead) + (n_packed_axes - 1,)
    return arr, positions


def _spine_mesh():
    """Default 1-axis ("tp") mesh over every visible device, used to
    shard oversized UTIL tables; None when only one device exists."""
    import jax

    devs = jax.devices()
    if len(devs) < 2:
        return None
    return jax.sharding.Mesh(np.array(devs), ("tp",))


def device_util_sweep(g, var_cost_rel, mode: str,
                      memory_limit: int = 10 ** 8,
                      node_device_cells: int = 200_000,
                      mesh=None):
    """Hybrid UTIL/VALUE split: the pseudo-tree *spine* — every node
    whose table crosses ``node_device_cells`` plus all its ancestors up
    to the root — runs as ONE jitted device program (joins, projections
    AND the top-down VALUE slicing), everything below runs in vectorized
    numpy (VERDICT r2 item 3; the reference's joins are per-cell Python
    loops, relations.py:1672-1760).

    Why this shape: real pseudo-trees are skewed — one or two
    wide-separator nodes near the root own almost all the work — and on
    a tunneled TPU the wires dominate: a 67 MB util costs ~2 s to
    download and every eager dispatch ~70 ms, while the chip crunches a
    1 GB table join in ~0.1 s.  So big tables must never cross the
    tunnel and the whole spine must be one dispatch.  Tables are held
    in the lane-packed layout (last separator dim and own variable
    merged into one >=256-wide minor axis) because naive (…, 16, 16)
    tiling pads the minor dim 8x.

    Returns (plans, host_joined, spine_assignment) where
    ``spine_assignment`` maps spine node names to chosen value indices
    and ``host_joined`` carries the numpy joined tables of non-spine
    nodes for the host VALUE phase.
    """
    plans = _util_plans(g, var_cost_rel)
    sizes = _domain_sizes(g)

    # ---- spine membership: big nodes + ancestors (upward-closed) ----
    # A table beyond one device's memory_limit is sharded over the tp
    # mesh (leading separator axis carries a NamedSharding) instead of
    # failing — the multi-chip escape hatch for wide separators
    # (reference dpop.py:313-377 joins at beyond-one-chip scale).
    cells_of = {}
    oversized = set()
    for name, plan in plans.items():
        cells_of[name] = int(np.prod(
            [sizes[d] for d in plan["out_dims"]]))
        if cells_of[name] > memory_limit:
            oversized.add(name)
    if oversized:
        if mesh is None:
            mesh = _spine_mesh()
        ntp = mesh.shape["tp"] if mesh is not None else 1
        for name in sorted(oversized):
            # sharding splits only the LEADING separator axis: with a
            # leading domain of size L over tp=N devices the largest
            # shard holds ceil(L/N) slices, not cells/N (e.g. L=3 on
            # tp=8 leaves cells/3 per device)
            lead = sizes[plans[name]["out_dims"][0]]
            slice_cells = cells_of[name] // lead
            per_device = ((lead + ntp - 1) // ntp) * slice_cells
            if mesh is None or per_device > memory_limit:
                raise MemoryError(
                    f"DPOP UTIL table for {name} exceeds memory limit "
                    f"({cells_of[name]} cells"
                    + (f", {per_device} per device over tp={ntp}"
                       if mesh is not None else ", single device")
                    + ")")
    spine = set()
    for level in reversed(g.depth_ordered()):
        for node in level:
            if (cells_of[node.name] >= node_device_cells
                    or node.name in oversized or any(
                    c in spine for c in node.children)):
                spine.add(node.name)

    def np_reduce_last(total):
        return (np.min if mode == "min" else np.max)(total, axis=-1)

    # ---- host part: all non-spine nodes, bottom-up ------------------
    host_joined = {}
    util_of = {}
    for level in reversed(g.depth_ordered()):
        for node in level:
            if node.name in spine:
                continue
            plan = plans[node.name]
            out_dims = plan["out_dims"]
            out_shape = tuple(sizes[d] for d in out_dims)
            axis_of = {d: i for i, d in enumerate(out_dims)}
            total = np.zeros(out_shape, dtype=np.float32)
            for kind, payload, dims in plan["inputs"]:
                arr = np.asarray(
                    payload if kind == "const" else util_of[payload],
                    dtype=np.float32)
                positions = [axis_of[d] for d in dims]
                perm = sorted(range(len(positions)),
                              key=lambda i: positions[i])
                if perm != list(range(len(positions))):
                    arr = np.transpose(arr, perm)
                    positions = [positions[i] for i in perm]
                shape = [1] * len(out_shape)
                for ax, size in zip(positions, arr.shape):
                    shape[ax] = size
                total = total + arr.reshape(shape)
            host_joined[node.name] = total
            util_of[node.name] = np_reduce_last(total)

    spine_assignment = {}
    if spine:
        spine_assignment = _run_spine(
            g, plans, sizes, spine, util_of, mode,
            mesh=mesh if oversized else None, oversized=oversized)
    return plans, host_joined, spine_assignment


def _run_spine(g, plans, sizes, spine, host_util_of, mode,
               mesh=None, oversized=frozenset()):
    """Compile + run the spine as one device program.  The jitted
    function takes every external input table as an argument (host
    utils of the spine's children, constraint matrices, unary costs),
    runs the bottom-up packed joins and the top-down VALUE argmins
    on-device, and returns one value index per spine node."""
    import jax
    import jax.numpy as jnp

    from ..engine._cache import enable_persistent_cache

    enable_persistent_cache()

    # bottom-up spine order (the VALUE pass iterates it reversed)
    bottom_up = [n for level in reversed(g.depth_ordered())
                 for n in level if n.name in spine]

    # external inputs, flattened in a stable order
    ext_arrays = []
    ext_index = {}

    def ext(arr):
        key = id(arr)
        if key not in ext_index:
            ext_index[key] = len(ext_arrays)
            ext_arrays.append(np.asarray(arr, dtype=np.float32))
        return ext_index[key]

    node_specs = []
    for node in bottom_up:
        plan = plans[node.name]
        out_dims = plan["out_dims"]
        packed = len(out_dims) >= 2
        inputs = []
        for kind, payload, dims in plan["inputs"]:
            if kind == "child" and payload in spine:
                inputs.append(("spine", payload, tuple(dims)))
            else:
                arr = payload if kind == "const" \
                    else host_util_of[payload]
                if packed:
                    arr2, positions = _pack_input(
                        np.asarray(arr, dtype=np.float32), tuple(dims),
                        out_dims, sizes)
                    inputs.append(("ext", ext(arr2), positions))
                else:
                    a = np.asarray(arr, dtype=np.float32)
                    inputs.append(("ext", ext(a),
                                   tuple(range(a.ndim))))
        node_specs.append((node.name, out_dims, packed, inputs))

    dom_sizes = sizes

    # oversized tables carry a NamedSharding over the tp mesh on their
    # leading separator axis; XLA/GSPMD partitions the joins, the
    # projection reduce_window and the VALUE slicing accordingly, so
    # the table never materializes on one device
    shard_spec = {}
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        for name, out_dims, packed, _inputs in node_specs:
            if name in oversized and packed and len(out_dims) >= 2:
                ndim = len(out_dims) - 1  # packed: minor pair merged
                shard_spec[name] = NamedSharding(
                    mesh, PartitionSpec("tp", *([None] * (ndim - 1))))

    def spine_fn(*args):
        util = {}
        joined = {}
        sep_layout = {}
        for name, out_dims, packed, inputs in node_specs:
            s_own = dom_sizes[out_dims[-1]]
            if packed:
                shape = tuple(dom_sizes[d] for d in out_dims[:-2]) + (
                    dom_sizes[out_dims[-2]] * s_own,)
            else:
                shape = (s_own,)
            total = jnp.zeros(shape, dtype=jnp.float32)
            for kind, ref, positions in inputs:
                if kind == "ext":
                    arr = args[ref]
                else:
                    # a spine child's util, resident on device, over its
                    # (sorted) separator dims; pack it for this node
                    arr = util[ref]
                    arr, positions = _pack_traced(
                        arr, sep_layout[ref], out_dims, dom_sizes)
                total = total + jax.lax.broadcast_in_dim(
                    arr, shape, positions)
            if name in shard_spec:
                total = jax.lax.with_sharding_constraint(
                    total, shard_spec[name])
            joined[name] = total
            if packed:
                window = (1,) * (total.ndim - 1) + (s_own,)
                init = jnp.inf if mode == "min" else -jnp.inf
                comp = jax.lax.min if mode == "min" else jax.lax.max
                u = jax.lax.reduce_window(
                    total, init, comp, window_dimensions=window,
                    window_strides=window, padding="VALID")
            else:
                u = (jnp.min if mode == "min" else jnp.max)(total)
            util[name] = u
            sep_layout[name] = tuple(out_dims[:-1])

        # ---- VALUE: top-down argmin slicing, all on device ----------
        chosen = {}
        out = []
        for name, out_dims, packed, _inputs in reversed(node_specs):
            table = joined[name]
            s_own = dom_sizes[out_dims[-1]]
            if packed:
                # spine is upward-closed: every separator dim belongs
                # to an ancestor spine node, so chosen[] has them all
                starts = [jnp.asarray(chosen[d], dtype=jnp.int32)
                          for d in out_dims[:-2]]
                starts.append(jnp.asarray(
                    chosen[out_dims[-2]] * s_own, dtype=jnp.int32))
                sizes_slice = (1,) * (table.ndim - 1) + (s_own,)
                block = jax.lax.dynamic_slice(table, starts,
                                              sizes_slice)
                costs = block.reshape(-1)
            else:
                costs = table
            idx = (jnp.argmin if mode == "min" else jnp.argmax)(costs)
            chosen[name] = idx
            out.append(idx)
        return jnp.stack(out)

    sig = (mode,
           None if mesh is None else
           (tuple(sorted(oversized)), tuple(d.id for d in
                                            mesh.devices.flat)),
           tuple(
        (name, tuple(out_dims), packed,
         tuple((k, r if k == "spine" else ext_arrays[r].shape, p)
               for k, r, p in inputs))
        for name, out_dims, packed, inputs in node_specs))
    fitted = _SPINE_CACHE.get(sig)
    if fitted is None:
        fitted = jax.jit(spine_fn)
        if len(_SPINE_CACHE) >= _SPINE_CACHE_MAX:
            _SPINE_CACHE.pop(next(iter(_SPINE_CACHE)))
        _SPINE_CACHE[sig] = fitted
    idxs = np.asarray(jax.device_get(fitted(*[
        jnp.asarray(a) for a in ext_arrays])))
    names_top_down = [spec[0] for spec in reversed(node_specs)]
    return dict(zip(names_top_down, (int(i) for i in idxs)))


def _pack_traced(arr, arr_dims, out_dims, sizes):
    """Device-side counterpart of :func:`_pack_input` for a spine
    child's util (a traced jax array over ``arr_dims``): transpose into
    output order, expand over the merged (last separator, own) pair and
    fold it, returning (packed array, packed axis positions)."""
    import jax
    import jax.numpy as jnp

    axis_of = {d: i for i, d in enumerate(out_dims)}
    if len(out_dims) < 2:
        # unpacked (single-dim) parent: direct axis mapping
        return arr, tuple(axis_of[d] for d in arr_dims)
    pair = out_dims[-2:]
    order = sorted(range(len(arr_dims)),
                   key=lambda i: axis_of[arr_dims[i]])
    if order != list(range(len(arr_dims))):
        arr = jnp.transpose(arr, order)
        arr_dims = tuple(arr_dims[i] for i in order)
    touches = [d for d in arr_dims if d in pair]
    lead = [d for d in arr_dims if d not in pair]
    n_packed_axes = len(out_dims) - 1
    if not touches:
        return arr, tuple(axis_of[d] for d in arr_dims)
    shape = tuple(arr.shape[: len(lead)]) + tuple(
        arr.shape[len(lead) + touches.index(d)] if d in touches else 1
        for d in pair)
    arr = arr.reshape(shape)
    full = tuple(arr.shape[: len(lead)]) + tuple(
        sizes[d] for d in pair)
    arr = jnp.broadcast_to(arr, full)
    arr = arr.reshape(arr.shape[: len(lead)] + (-1,))
    positions = tuple(axis_of[d] for d in lead) + (n_packed_axes - 1,)
    return arr, positions


def computation_memory(*args, **kwargs):
    """Not defined for DPOP (reference: dpop.py:80-85 raises too)."""
    raise NotImplementedError("DPOP has no computation_memory model")


def communication_load(*args, **kwargs):
    raise NotImplementedError("DPOP has no communication_load model")


def message_size(util: NAryMatrixRelation) -> int:
    """UTIL message size = product of its dims (reference: dpop.py:88-109)."""
    return int(np.prod(util.matrix.shape)) if util.arity else 1


def solve_direct(dcop: DCOP, params: Optional[Dict] = None,
                 memory_limit: int = 10 ** 8,
                 timeout: Optional[float] = None,
                 device: str = "auto",
                 mesh=None,
                 **_kwargs) -> RunResult:
    """Run DPOP to optimality (or TIMEOUT with an empty assignment —
    DPOP has no meaningful anytime solution mid-UTIL-sweep).

    ``device``: "host" = vectorized numpy joins; "jax" = the batched
    device UTIL sweep (:func:`device_util_sweep`); "auto" picks the
    device once the predicted UTIL work crosses ``DEVICE_AUTO_CELLS``
    or any single UTIL table exceeds one device's ``memory_limit``
    (the jax path shards such tables over the ``mesh`` — default: all
    visible devices on a "tp" axis).
    """
    import time

    t0 = time.perf_counter()
    if params:
        device = params.get("device", device) or device

    def out_of_time():
        return timeout is not None and time.perf_counter() - t0 > timeout
    mode = dcop.objective
    g = pseudotree.build_computation_graph(dcop)

    # fold variable costs in as unary relations so they take part in the
    # optimization (the reference models them through variable computations)
    var_cost_rel: Dict[str, UnaryFunctionRelation] = {}
    for v in dcop.variables.values():
        if v.has_cost:
            var_cost_rel[v.name] = UnaryFunctionRelation(
                f"__cost_{v.name}", v, v.cost_for_val)

    if device == "auto":
        sizes = _domain_sizes(g)
        cells, max_node_cells = 0, 0
        for name, plan in _util_plans(g, var_cost_rel).items():
            node_cells = int(np.prod([sizes[d]
                                      for d in plan["out_dims"]]))
            cells += node_cells
            max_node_cells = max(max_node_cells, node_cells)
        device = "jax" if (cells >= DEVICE_AUTO_CELLS
                           or max_node_cells > memory_limit) else "host"
    if device == "jax":
        return _solve_device(dcop, g, var_cost_rel, mode, memory_limit,
                             t0, timeout, mesh=mesh)

    levels = g.depth_ordered()
    util_of: Dict[str, Any] = {}
    joined_of: Dict[str, Any] = {}
    msg_count, msg_size = 0, 0

    # --- UTIL phase: deepest level first -----------------------------------
    for level in reversed(levels):
        for node in level:
            if out_of_time():
                return RunResult({}, 0, False, float("inf"), 0,
                                 time.perf_counter() - t0,
                                 status="TIMEOUT")
            rel = NAryMatrixRelation([node.variable],
                                     name=f"util_{node.name}")
            if node.name in var_cost_rel:
                rel = join(rel, var_cost_rel[node.name].to_matrix())
            for c in node.constraints:
                rel = join(rel, c.to_matrix())
            for child in node.children:
                rel = join(rel, util_of[child])
            if rel.matrix.size > memory_limit:
                raise MemoryError(
                    f"DPOP UTIL table for {node.name} exceeds memory "
                    f"limit: shape {rel.matrix.shape}"
                )
            joined_of[node.name] = rel
            if not node.is_root:
                util = projection(rel, node.variable, mode)
                util_of[node.name] = util
                msg_count += 1
                msg_size += message_size(util) \
                    if hasattr(util, "matrix") else 1

    # --- VALUE phase: root level first -------------------------------------
    assignment: Dict[str, Any] = {}
    for level in levels:
        for node in level:
            rel = joined_of[node.name]
            fixed = {
                n: assignment[n] for n in rel.scope_names
                if n != node.name and n in assignment
            }
            sliced = rel.slice(fixed) if fixed else rel
            costs = np.asarray(sliced.matrix).reshape(-1)
            i = int(np.argmin(costs) if mode == "min"
                    else np.argmax(costs))
            assignment[node.name] = node.variable.domain.values[i]
            if not node.is_root:
                msg_count += 1
                # VALUE message = the separator's (variable, value)
                # pairs: size 2 x |separator| (reference dpop.py:98-108
                # ValueMessage.size) — with UTIL's prod-of-dims above,
                # the getting-started 3-var chain reports the reference
                # tutorial's "4 messages / total size 8"
                msg_size += 2 * len(fixed)

    cost, violations = dcop.solution_cost(assignment)
    return RunResult(
        assignment=assignment,
        cycles=len(levels),
        finished=True,
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status="FINISHED",
        metrics={"msg_count": msg_count, "msg_size": msg_size},
    )


def _solve_device(dcop, g, var_cost_rel, mode, memory_limit, t0,
                  timeout, mesh=None):
    """Device path: the wide spine runs as one jitted device program
    (UTIL joins + VALUE argmins); the host finishes the VALUE walk for
    the small subtrees below it."""
    import time

    plans, host_joined, spine_assignment = device_util_sweep(
        g, var_cost_rel, mode, memory_limit=memory_limit, mesh=mesh)
    levels = g.depth_ordered()
    dom_index = {
        node.name: {v: i for i, v in
                    enumerate(node.variable.domain.values)}
        for node in g.nodes
    }
    assignment: Dict[str, Any] = {}
    msg_count, msg_size = 0, 0
    for level in levels:
        for node in level:
            dims = plans[node.name]["out_dims"]
            if node.name in spine_assignment:
                i = spine_assignment[node.name]
                assignment[node.name] = node.variable.domain.values[i]
                if not node.is_root:
                    msg_count += 2
                    sizes = [len(g.node(d).variable.domain)
                             for d in dims[:-1]]
                    msg_size += int(np.prod(sizes)) if sizes else 1
                    # + the VALUE message down: 2 x |separator|
                    # (host-path parity, reference dpop.py:98-108)
                    msg_size += 2 * len(dims[:-1])
                continue
            arr = host_joined[node.name]
            idx = tuple(
                dom_index[d][assignment[d]] if d != node.name
                else slice(None) for d in dims)
            costs = np.asarray(arr[idx]).reshape(-1)
            i = int(np.argmin(costs) if mode == "min"
                    else np.argmax(costs))
            assignment[node.name] = node.variable.domain.values[i]
            if not node.is_root:
                # one UTIL message up + one VALUE message down per node
                msg_count += 2
                msg_size += int(np.prod(arr.shape[:-1]))
                msg_size += 2 * (len(dims) - 1)
    cost, violations = dcop.solution_cost(assignment)
    return RunResult(
        assignment=assignment,
        cycles=len(levels),
        finished=True,
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status="FINISHED",
        metrics={"msg_count": msg_count, "msg_size": msg_size,
                 "device": "jax"},
    )


# ---------------------------------------------------------------------
# Message-passing backend: DPOP running ON the agent fabric
# (reference: dpop.py:151-441).  UTIL tables flow leaves -> root, VALUE
# assignments root -> leaves; each node's join/projection is the same
# vectorized broadcast-add / axis-reduce used by solve_direct above (the
# reference's per-cell Python loops, relations.py:1672-1760, never
# appear).  UTIL tables cross the wire as (dims, nested costs) lists so
# the JSON transport carries them between processes / machines.
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    VariableComputation, message_type, register)
from ..dcop.objects import Domain
from ..dcop.relations import find_arg_optimal

#: dims: [[var_name, [domain values...]], ...], costs: nested list with
#: one axis per dim (JSON-safe: the reference ships pickled relation
#: objects instead, dpop.py:88-109)
DpopUtilMessage = message_type("dpop_util", ["dims", "costs"])
#: assignment: [[var_name, value], ...] for the receiver's separator
DpopValueMessage = message_type("dpop_value", ["assignment"])


_WIRE_INF = 1e30


def _wire_util(util: NAryMatrixRelation):
    dims = [[v.name, list(v.domain.values)] for v in util.dimensions]
    # non-finite costs (hard constraints written as inf) are not
    # JSON-compliant — the HTTP transport rejects them; clamp to a
    # sentinel far above any soft cost
    m = np.nan_to_num(util.matrix, nan=_WIRE_INF, posinf=_WIRE_INF,
                      neginf=-_WIRE_INF)
    return dims, m.tolist()


def _unwire_util(dims, costs) -> NAryMatrixRelation:
    variables = [
        Variable(name, Domain(f"d_{name}", "", values))
        for name, values in dims]
    return NAryMatrixRelation(variables, np.asarray(costs),
                              name="util")


class DpopMpComputation(VariableComputation):
    """One DPOP variable on the agent fabric (reference: dpop.py:151-441).

    Asynchronous by construction: leaves fire their UTIL at start; every
    node forwards once all children reported; the root kicks off the
    VALUE wave and each node finishes right after selecting its value
    (DPOP is not iterative — reference dpop.py:292-312)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        node = comp_def.node
        self.mode = comp_def.algo.mode
        self.parent = node.parent
        self.children = list(node.children)
        self.constraints = list(node.constraints)
        # lowest-node rule is already applied by the graph build
        # (graphs/pseudotree.py), unlike the reference which re-filters
        # in the computation (dpop.py:186-202)
        self._waited_children = set(self.children)
        self._children_separator: Dict[str, list] = {}
        rel = NAryMatrixRelation([self.variable],
                                 name=f"util_{self.name}")
        if self.variable.has_cost:
            costs = [self.variable.cost_for_val(v)
                     for v in self.variable.domain.values]
            rel = join(rel, NAryMatrixRelation(
                [self.variable], np.asarray(costs),
                name=f"cost_{self.name}"))
        self._joined_utils = rel

    @property
    def is_root(self):
        return self.parent is None

    @property
    def is_leaf(self):
        return not self.children

    def on_start(self):
        if self.is_leaf and not self.is_root:
            util = self._compute_util()
            dims, costs = _wire_util(util)
            self.post_msg(self.parent, DpopUtilMessage(dims, costs),
                          MSG_ALGO)
        elif self.is_leaf:
            # isolated variable: optimize alone (reference: dpop.py:255-283)
            for c in self.constraints:
                self._joined_utils = join(self._joined_utils,
                                          c.to_matrix())
            values, cost = find_arg_optimal(
                self.variable, self._joined_utils, self.mode)
            self._select_and_finish(values[0], float(cost))

    def _compute_util(self) -> NAryMatrixRelation:
        for c in self.constraints:
            self._joined_utils = join(self._joined_utils, c.to_matrix())
        return projection(self._joined_utils, self.variable, self.mode)

    def _select_and_finish(self, value, cost):
        self.value_selection(value, cost)
        self.finished()

    @register("dpop_util")
    def _on_util(self, sender, msg, t):
        util = _unwire_util(msg.dims, msg.costs)
        self._joined_utils = join(self._joined_utils, util)
        self._waited_children.discard(sender)
        self._children_separator[sender] = [d[0] for d in msg.dims]
        if self._waited_children:
            return
        if self.is_root:
            for c in self.constraints:
                self._joined_utils = join(self._joined_utils,
                                          c.to_matrix())
            values, cost = find_arg_optimal(
                self.variable, self._joined_utils, self.mode)
            selected = values[0]
            for child in self.children:
                self.post_msg(child, DpopValueMessage(
                    [[self.name, selected]]), MSG_ALGO)
            self._select_and_finish(selected, float(cost))
        else:
            util = self._compute_util()
            dims, costs = _wire_util(util)
            self.post_msg(self.parent, DpopUtilMessage(dims, costs),
                          MSG_ALGO)

    @register("dpop_value")
    def _on_value(self, sender, msg, t):
        value_dict = {name: value for name, value in msg.assignment}
        fixed = {n: value_dict[n]
                 for n in self._joined_utils.scope_names
                 if n != self.name and n in value_dict}
        rel = self._joined_utils.slice(fixed) if fixed \
            else self._joined_utils
        values, cost = find_arg_optimal(self.variable, rel, self.mode)
        selected = values[0]
        for child in self.children:
            assignment = [[self.name, selected]]
            for v in self._children_separator.get(child, []):
                if v in value_dict:
                    assignment.append([v, value_dict[v]])
            self.post_msg(child, DpopValueMessage(assignment), MSG_ALGO)
        self._select_and_finish(selected, float(cost))


def build_computation(comp_def) -> DpopMpComputation:
    return DpopMpComputation(comp_def)

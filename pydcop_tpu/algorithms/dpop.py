"""DPOP: exact dynamic programming on a DFS pseudo-tree.

reference parity: pydcop/algorithms/dpop.py (441 LoC).  Same two sweeps —
UTIL (leaves → root): each node joins its constraints with its children's
UTIL tables and projects out its own variable; VALUE (root → leaves): each
node slices its joined table at the ancestors' chosen values and picks the
arg-optimum (dpop.py:313-439).

The reference implements ``join``/``projection`` as per-assignment Python
loops over every cell of the util hypercube (relations.py:1672-1760) —
exponential Python interpreter time in the separator width.  Here both are
single vectorized broadcast-add / axis-reduce array ops
(pydcop_tpu.dcop.relations.join/projection), the shape XLA and numpy
execute at memory bandwidth.  The sweep itself is host-orchestrated (tree
levels are heterogeneous in shape); per-level tables could be pushed to
device in one batch per unique separator shape, which matters only for
very deep trees.

Memory caution (same as every DPOP): the UTIL table of a node is
exponential in its separator size.  ``memory_limit`` guards against
accidental blow-ups with a clear error instead of an OOM.
"""

from typing import Any, Dict, Optional

import numpy as np

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import (
    NAryMatrixRelation,
    UnaryFunctionRelation,
    join,
    projection,
)
from ..engine.solver import RunResult
from ..graphs import pseudotree

GRAPH_TYPE = "pseudotree"

algo_params = []


def computation_memory(*args, **kwargs):
    """Not defined for DPOP (reference: dpop.py:80-85 raises too)."""
    raise NotImplementedError("DPOP has no computation_memory model")


def communication_load(*args, **kwargs):
    raise NotImplementedError("DPOP has no communication_load model")


def message_size(util: NAryMatrixRelation) -> int:
    """UTIL message size = product of its dims (reference: dpop.py:88-109)."""
    return int(np.prod(util.matrix.shape)) if util.arity else 1


def solve_direct(dcop: DCOP, params: Optional[Dict] = None,
                 memory_limit: int = 10 ** 8,
                 timeout: Optional[float] = None,
                 **_kwargs) -> RunResult:
    """Run DPOP to optimality (or TIMEOUT with an empty assignment —
    DPOP has no meaningful anytime solution mid-UTIL-sweep)."""
    import time

    t0 = time.perf_counter()

    def out_of_time():
        return timeout is not None and time.perf_counter() - t0 > timeout
    mode = dcop.objective
    g = pseudotree.build_computation_graph(dcop)

    # fold variable costs in as unary relations so they take part in the
    # optimization (the reference models them through variable computations)
    var_cost_rel: Dict[str, UnaryFunctionRelation] = {}
    for v in dcop.variables.values():
        if v.has_cost:
            var_cost_rel[v.name] = UnaryFunctionRelation(
                f"__cost_{v.name}", v, v.cost_for_val)

    levels = g.depth_ordered()
    util_of: Dict[str, Any] = {}
    joined_of: Dict[str, Any] = {}
    msg_count, msg_size = 0, 0

    # --- UTIL phase: deepest level first -----------------------------------
    for level in reversed(levels):
        for node in level:
            if out_of_time():
                return RunResult({}, 0, False, float("inf"), 0,
                                 time.perf_counter() - t0,
                                 status="TIMEOUT")
            rel = NAryMatrixRelation([node.variable],
                                     name=f"util_{node.name}")
            if node.name in var_cost_rel:
                rel = join(rel, var_cost_rel[node.name].to_matrix())
            for c in node.constraints:
                rel = join(rel, c.to_matrix())
            for child in node.children:
                rel = join(rel, util_of[child])
            if rel.matrix.size > memory_limit:
                raise MemoryError(
                    f"DPOP UTIL table for {node.name} exceeds memory "
                    f"limit: shape {rel.matrix.shape}"
                )
            joined_of[node.name] = rel
            if not node.is_root:
                util = projection(rel, node.variable, mode)
                util_of[node.name] = util
                msg_count += 1
                msg_size += message_size(util) \
                    if hasattr(util, "matrix") else 1

    # --- VALUE phase: root level first -------------------------------------
    assignment: Dict[str, Any] = {}
    for level in levels:
        for node in level:
            rel = joined_of[node.name]
            fixed = {
                n: assignment[n] for n in rel.scope_names
                if n != node.name and n in assignment
            }
            sliced = rel.slice(fixed) if fixed else rel
            costs = np.asarray(sliced.matrix).reshape(-1)
            i = int(np.argmin(costs) if mode == "min"
                    else np.argmax(costs))
            assignment[node.name] = node.variable.domain.values[i]
            if not node.is_root:
                msg_count += 1

    cost, violations = dcop.solution_cost(assignment)
    return RunResult(
        assignment=assignment,
        cycles=len(levels),
        finished=True,
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status="FINISHED",
        metrics={"msg_count": msg_count, "msg_size": msg_size},
    )

"""Shared machinery for local-search algorithms on the constraints
hypergraph (dsa*, mgm*, dba, gdba, mixeddsa).

All of them share the same data plane: the full ``(n_vars, max_domain)``
best-response cost matrix computed in one shot (``ops.candidate_costs``),
neighbor gain exchange as segment reductions over the variable-pair edge
list, and per-constraint violation tests against precomputed per-constraint
optima.  The reference computes all of this with per-agent Python loops
over ``constraints_hypergraph`` neighbors (e.g. dsa.py:265-357,
mgm.py:213-420).
"""

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.solver import ArraySolver
from ..graphs.arrays import SENTINEL, HypergraphArrays
from ..ops.kernels import bucket_cost, candidate_costs, prefix_uniform
from ..ops.precision import resolve as resolve_precision


class LocalSearchSolver(ArraySolver):
    """Base: holds device arrays + the shared kernels."""

    #: pad-stable RNG: draw per-variable uniforms with
    #: ``ops.kernels.prefix_uniform`` (row i depends only on (key, i))
    #: instead of one shape-coupled ``jax.random.uniform``.  Opted into
    #: by the hetero-fusable algorithms (dsa, mgm) so a job solved
    #: inside a shape-padded fused campaign batch reproduces its
    #: unpadded subprocess solve bit-exactly; the rest of the family
    #: (mgm2, dba, ...) keeps the historical draw order, which their
    #: sharded replicas mirror key-for-key.
    pad_stable_rng = False

    def __init__(self, arrays: HypergraphArrays, stop_cycle: int = 0,
                 precision=None):
        self.arrays = arrays
        self.var_names = arrays.var_names
        self.stop_cycle = int(stop_cycle)
        # mixed-precision policy (ops/precision.py): cost planes
        # (cubes, unary costs, per-constraint optima) live on device in
        # store_dtype; candidate/total sums upcast to accum_dtype at
        # every reduction boundary, so integer-cost instances keep
        # f32-bit-exact selections under bf16 storage
        self.policy = resolve_precision(precision)
        store = self.policy.store_dtype

        self.V = arrays.n_vars
        self.D = arrays.max_domain
        self.var_costs = jnp.asarray(arrays.var_costs, dtype=store)
        self.domain_mask = jnp.asarray(arrays.domain_mask)
        self.domain_size = jnp.asarray(arrays.domain_size)
        self.initial_idx = jnp.asarray(arrays.initial_idx)
        self.has_initial = jnp.asarray(arrays.has_initial)
        self.buckets = [
            (jnp.asarray(b.cubes, dtype=store),
             jnp.asarray(b.var_ids))
            for b in arrays.buckets
        ]
        # per-constraint best achievable value, per bucket (for
        # "violated constraint" tests, reference dsa.py:450-466) —
        # host mins of the store-dtype cubes: exact under bf16 (min is
        # order-preserving, the cubes were already rounded at store)
        self.bucket_optima = [
            jnp.asarray(np.min(
                np.asarray(b.cubes, dtype=store)
                .reshape(b.cubes.shape[0], -1), axis=1))
            for b in arrays.buckets
        ]
        self.nbr_src = jnp.asarray(arrays.nbr_src)
        self.nbr_dst = jnp.asarray(arrays.nbr_dst)
        self.has_neighbors = self.nbr_src.shape[0] > 0

    # --- shared kernels --------------------------------------------------

    def _reduce_vplane(self, arr: jnp.ndarray) -> jnp.ndarray:
        """Cross-shard reduction point for bucket-accumulated variable-
        plane tensors ((V, D) candidate sums, (V,) counts).  Identity on
        a single chip; the sharded harness (parallel/sharded_breakout)
        overrides it with a psum over the tp mesh axis so the SAME step
        code runs tp-sharded."""
        return arr

    def _reduce_scalar(self, v: jnp.ndarray) -> jnp.ndarray:
        """Cross-shard reduction point for bucket-accumulated scalars
        (violation totals).  Identity on a single chip."""
        return v

    def local_costs(self, x: jnp.ndarray) -> jnp.ndarray:
        """(V, D) cost of each candidate value given neighbors at
        ``x`` — accumulated in the policy's accum dtype (f32), the
        unary store-dtype plane upcasting exactly at the final add."""
        accum = self.policy.accum_dtype
        acc = jnp.zeros((self.V, self.D), dtype=accum)
        for cubes, var_ids in self.buckets:
            acc = acc + candidate_costs(cubes, var_ids, x, self.V,
                                        accum_dtype=accum)
        return self.var_costs + self._reduce_vplane(acc)

    def uniform_v(self, key) -> jnp.ndarray:
        """One uniform per variable — pad-stable when the algorithm
        opted in (see :attr:`pad_stable_rng`)."""
        if self.pad_stable_rng:
            return prefix_uniform(key, self.V)
        return jax.random.uniform(key, (self.V,))

    def uniform_vd(self, key) -> jnp.ndarray:
        """(V, D) uniforms, pad-stable per variable row when opted in."""
        if self.pad_stable_rng:
            return prefix_uniform(key, self.V, self.D)
        return jax.random.uniform(key, (self.V, self.D))

    def random_values(self, key) -> jnp.ndarray:
        """Random initial value per variable (or the declared initial)."""
        r = self.uniform_v(key)
        rand_idx = (r * self.domain_size).astype(jnp.int32)
        return jnp.where(self.has_initial, self.initial_idx, rand_idx)

    def total_cost(self, x: jnp.ndarray) -> jnp.ndarray:
        accum = self.policy.accum_dtype
        V = self.var_costs.shape[0]
        unary = jnp.sum(
            self.var_costs[jnp.arange(V), x].astype(accum))
        acc = jnp.zeros((), dtype=accum)
        for cubes, var_ids in self.buckets:
            acc = acc + jnp.sum(
                bucket_cost(cubes, var_ids, x).astype(accum))
        return unary + self._reduce_scalar(acc)

    def var_has_violated_constraint(self, x: jnp.ndarray) -> jnp.ndarray:
        """(V,) bool: does the variable touch a constraint that is not at
        its own optimum (reference dsa.py exists_violated_constraint)."""
        counts = jnp.zeros((self.V,), dtype=jnp.int32)
        for (cubes, var_ids), opt in zip(self.buckets, self.bucket_optima):
            violated = bucket_cost(cubes, var_ids, x) > opt + 1e-6
            for p in range(var_ids.shape[1]):
                counts = counts + jax.ops.segment_sum(
                    violated.astype(jnp.int32), var_ids[:, p],
                    num_segments=self.V,
                )
        return self._reduce_vplane(counts) > 0

    def neighbor_max_gain(self, gain: jnp.ndarray) -> jnp.ndarray:
        """(V,) max gain among each variable's neighbors (-inf if none)."""
        if not self.has_neighbors:
            return jnp.full((self.V,), -jnp.inf)
        return jax.ops.segment_max(
            gain[self.nbr_src], self.nbr_dst, num_segments=self.V)

    def wins_tie(self, gain: jnp.ndarray, nbr_max: jnp.ndarray,
                 priority: jnp.ndarray) -> jnp.ndarray:
        """(V,) bool: strictly-greatest-gain test with tie-breaking by
        ``priority`` (lower wins is encoded by the caller)."""
        if not self.has_neighbors:
            return gain > 0
        at_max = gain[self.nbr_src] >= nbr_max[self.nbr_dst] - 1e-9
        nbr_best_pri = jax.ops.segment_max(
            jnp.where(at_max, priority[self.nbr_src], -jnp.inf),
            self.nbr_dst, num_segments=self.V)
        return (gain > nbr_max + 1e-9) | (
            (gain >= nbr_max - 1e-9) & (priority > nbr_best_pri)
        )

    def best_response(self, key, x: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                 jnp.ndarray]:
        """Returns (costs, current_cost, best_cost, best_val) where
        best_val breaks ties randomly, preferring a value != current when
        several minima exist (reference dsa.py variant_b/c)."""
        costs = self.local_costs(x)
        cur = costs[jnp.arange(self.V), x]
        c = jnp.where(self.domain_mask, costs,
                      jnp.asarray(SENTINEL, costs.dtype))
        best_cost = jnp.min(c, axis=-1)
        is_min = (c <= best_cost[:, None] + 1e-9) & self.domain_mask
        # prefer a minimum other than the current value when one exists
        not_cur = is_min & ~jax.nn.one_hot(x, self.D, dtype=bool)
        has_other = jnp.any(not_cur, axis=-1)
        pick_from = jnp.where(has_other[:, None], not_cur, is_min)
        noise = self.uniform_vd(key)
        best_val = jnp.argmax(pick_from * (1.0 + noise), axis=-1)
        return costs, cur, best_cost, best_val

    # --- engine protocol -------------------------------------------------

    def assignment_indices(self, s):
        return s["x"]

    def cost(self, s):
        return self.total_cost(s["x"])

    def _finish(self, cycle):
        if self.stop_cycle:
            return cycle >= self.stop_cycle
        return jnp.bool_(False)


def hypergraph_footprints(unit_size: float = 1.0):
    """Build computation_memory / communication_load callbacks shared by
    all hypergraph algorithms (reference: dsa.py/mgm.py footprint
    formulas — value messages carry one value, memory is one value per
    neighbor)."""

    def computation_memory(node) -> float:
        return unit_size * len(node.neighbors)

    def communication_load(node, target: str) -> float:
        return unit_size

    return computation_memory, communication_load

"""NCBB: No-Commitment Branch and Bound on a DFS pseudo-tree.

reference parity: pydcop/algorithms/ncbb.py (350 LoC).  The reference
implements Chechetka & Sycara's no-commitment protocol: a greedy descent
initializes upper bounds, then a synchronous search phase explores the
pseudo-tree with bound messages.  The protocol's phases exist to pipeline
a *distributed* search; compiled host-side, the same exploration is an
AND/OR branch-and-bound over the pseudo-tree (children subtrees are
independent given the ancestor context and are bounded separately), with:

* the greedy-descent initial upper bound (ncbb.py init phase),
* best-first value ordering at every node,
* admissible per-subtree lower bounds (min cell of every constraint +
  min variable cost in the subtree).

Exact for min and max; like the reference it supports any constraint the
pseudo-tree carries (the reference is limited to binary constraints,
ncbb.py:139 — this implementation has no such limit).
"""

import time
from typing import Dict, List, Optional

import numpy as np

from ..dcop.dcop import DCOP
from ..engine.solver import RunResult
from ..graphs import pseudotree

GRAPH_TYPE = "pseudotree"

algo_params = []


def computation_memory(node) -> float:
    return len(node.variable.domain)


def communication_load(node, target: str) -> float:
    return 1.0


class _Timeout(Exception):
    pass


def solve_direct(dcop: DCOP, params: Optional[Dict] = None,
                 timeout: Optional[float] = None,
                 **_kwargs) -> RunResult:
    t0 = time.perf_counter()
    sign = 1.0 if dcop.objective == "min" else -1.0
    g = pseudotree.build_computation_graph(dcop)
    nodes = {n.name: n for n in g.nodes}

    # compiled tables per node: (matrix, scope names) + var costs
    tables: Dict[str, list] = {}
    var_costs: Dict[str, np.ndarray] = {}
    doms: Dict[str, list] = {}
    for n in g.nodes:
        doms[n.name] = list(n.variable.domain.values)
        var_costs[n.name] = sign * np.array(
            [n.variable.cost_for_val(v) for v in doms[n.name]],
            dtype=np.float64)
        tables[n.name] = []
        for c in n.constraints:
            m = c.to_matrix()
            tables[n.name].append(
                (np.asarray(m.matrix, dtype=np.float64) * sign,
                 [v.name for v in m.dimensions]))

    # admissible subtree lower bounds
    subtree_lb: Dict[str, float] = {}

    def compute_lb(name: str) -> float:
        n = nodes[name]
        lb = var_costs[name].min() + sum(
            arr.min() for arr, _ in tables[name])
        lb += sum(compute_lb(c) for c in n.children)
        subtree_lb[name] = lb
        return lb

    for root in g.roots:
        compute_lb(root.name)

    def increments(name: str, ctx: Dict[str, int]) -> np.ndarray:
        """Vectorized per-value cost increment given ancestor context."""
        inc = var_costs[name].copy()
        for arr, scope in tables[name]:
            idx = tuple(
                slice(None) if s == name else ctx[s] for s in scope
            )
            inc = inc + arr[idx]
        return inc

    stats = {"expansions": 0}

    def greedy(name: str, ctx: Dict[str, int]) -> float:
        """Greedy descent — the reference's bound-initialization phase."""
        inc = increments(name, ctx)
        vi = int(np.argmin(inc))
        ctx2 = dict(ctx)
        ctx2[name] = vi
        return float(inc[vi]) + sum(
            greedy(c, ctx2) for c in nodes[name].children)

    def search(name: str, ctx: Dict[str, int], ub: float):
        """Best (cost, assignment) of the subtree under ``name`` given
        ancestor context, or (inf, None) if it cannot beat ``ub``."""
        stats["expansions"] += 1
        if timeout is not None and stats["expansions"] % 256 == 0 \
                and time.perf_counter() - t0 > timeout:
            raise _Timeout()
        n = nodes[name]
        inc = increments(name, ctx)
        order = np.argsort(inc, kind="stable")
        children = n.children
        lb_children = sum(subtree_lb[c] for c in children)
        best_cost, best_assign = np.inf, None
        for vi in order:
            vi = int(vi)
            base = float(inc[vi])
            if base + lb_children >= ub:
                break  # best-first: later values are no better
            ctx2 = dict(ctx)
            ctx2[name] = vi
            total = base
            assign = {name: vi}
            feasible = True
            remaining_lb = lb_children
            for c in children:
                remaining_lb -= subtree_lb[c]
                child_ub = ub - total - remaining_lb
                c_cost, c_assign = search(c, ctx2, child_ub)
                if c_assign is None:
                    feasible = False
                    break
                total += c_cost
                assign.update(c_assign)
            if feasible and total < ub:
                ub = total
                best_cost, best_assign = total, assign
        return best_cost, best_assign

    def greedy_assign(name, ctx, out):
        inc = increments(name, ctx)
        vi = int(np.argmin(inc))
        out[name] = vi
        ctx2 = dict(ctx)
        ctx2[name] = vi
        for c in nodes[name].children:
            greedy_assign(c, ctx2, out)

    status = "FINISHED"
    assignment_idx: Dict[str, int] = {}
    for root in g.roots:
        ub = greedy(root.name, {}) + 1e-9
        try:
            cost, assign = search(root.name, {}, ub + 1e-6)
        except _Timeout:
            # anytime fallback: the greedy-descent solution
            status = "TIMEOUT"
            greedy_assign(root.name, {}, assignment_idx)
            continue
        if assign is None:
            # the greedy solution itself was optimal; re-run greedy
            # capturing the assignment
            greedy_assign(root.name, {}, assignment_idx)
        else:
            assignment_idx.update(assign)

    assignment = {
        name: doms[name][vi] for name, vi in assignment_idx.items()
    }
    cost, violations = dcop.solution_cost(assignment) if assignment \
        else (np.inf, 0)
    return RunResult(
        assignment=assignment,
        cycles=stats["expansions"],
        finished=status == "FINISHED",
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status=status,
        metrics={"expansions": stats["expansions"]},
    )


# ---------------------------------------------------------------------
# Message-passing backend: NCBB running ON the agent fabric
# (reference: ncbb.py:137-350).  The reference implements only NCBB's
# initialization phase — greedy top-down value propagation and
# bottom-up subtree cost aggregation; its search phase is an empty stub
# (ncbb.py:337-350).  This backend completes the same INIT phase and
# terminates cleanly with the greedy solution: the root broadcasts a
# stop wave once it knows the full subtree cost (where the reference's
# computations would hang forever, never reporting finished).
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    VariableComputation, message_type, register)

NcbbValueMessage = message_type("ncbb_value", ["value"])
NcbbCostMessage = message_type("ncbb_cost", ["cost"])
NcbbStopMessage = message_type("ncbb_stop", ["bound"])


class NcbbMpComputation(VariableComputation):
    """One NCBB variable on the agent fabric (reference: ncbb.py:137-335).
    Works in signed (minimizing) space."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        node = comp_def.node
        self.mode = comp_def.algo.mode
        self._sign = 1.0 if self.mode != "max" else -1.0
        self.parent = node.parent
        self.children = list(node.children)
        self.ancestors = list(node.pseudo_parents) + \
            ([node.parent] if node.parent else [])
        self.descendants = list(node.pseudo_children) + self.children
        self.constraints = list(node.constraints)
        self._parents_values: Dict[str, object] = {}
        self._children_costs: Dict[str, float] = {}
        self._subtree_cost = 0.0

    @property
    def is_root(self):
        return self.parent is None

    @property
    def is_leaf(self):
        return not self.children

    def on_start(self):
        if not self.is_root:
            return
        # root: free greedy choice, kicked down the tree
        # (reference: ncbb.py:218-227)
        best_val, best_cost = None, None
        for v in self.variable.domain.values:
            cost = self._sign * self.variable.cost_for_val(v)
            if best_cost is None or cost < best_cost:
                best_val, best_cost = v, cost
        self.value_selection(best_val, self._sign * best_cost)
        self._subtree_cost = best_cost
        for d in self.descendants:
            self.post_msg(d, NcbbValueMessage(self.current_value),
                          MSG_ALGO)
        if self.is_leaf and not self.descendants:
            self.finished()

    @register("ncbb_value")
    def _on_value(self, sender, msg, t):
        """Greedy selection once every ancestor's value arrived
        (reference: ncbb.py:252-296)."""
        self._parents_values[sender] = msg.value
        if len(self._parents_values) < len(self.ancestors):
            return
        best_val, best_cost = None, None
        for v in self.variable.domain.values:
            assignment = dict(self._parents_values)
            assignment[self.name] = v
            cost = self._sign * self.variable.cost_for_val(v)
            for c in self.constraints:
                scope = c.scope_names
                if all(n in assignment for n in scope):
                    cost += self._sign * c(
                        **{n: assignment[n] for n in scope})
            if best_cost is None or cost < best_cost:
                best_val, best_cost = v, cost
        self.value_selection(best_val, self._sign * best_cost)
        self._subtree_cost = best_cost
        if not self.is_leaf:
            for d in self.descendants:
                self.post_msg(d, NcbbValueMessage(self.current_value),
                              MSG_ALGO)
        else:
            # leaves start the cost wave (to the tree parent only: the
            # reference posts to every ancestor and would reject the
            # pseudo-parent copies, ncbb.py:290-296,302-310)
            if self.parent:
                self.post_msg(self.parent, NcbbCostMessage(best_cost),
                              MSG_ALGO)
            self.finished()

    @register("ncbb_cost")
    def _on_cost(self, sender, msg, t):
        """Aggregate children subtree costs (reference: ncbb.py:298-330).
        """
        self._children_costs[sender] = float(msg.cost)
        if len(self._children_costs) < len(self.children):
            return
        self._subtree_cost += sum(self._children_costs.values())
        if not self.is_root:
            self.post_msg(self.parent,
                          NcbbCostMessage(self._subtree_cost), MSG_ALGO)
            self.finished()
        else:
            # INIT complete: the greedy bound is known, stop the tree
            self.value_selection(self.current_value,
                                 self._sign * self._subtree_cost)
            for d in self.descendants:
                self.post_msg(d, NcbbStopMessage(self._subtree_cost),
                              MSG_ALGO)
            self.finished()

    @register("ncbb_stop")
    def _on_stop(self, sender, msg, t):
        for d in self.descendants:
            self.post_msg(d, NcbbStopMessage(msg.bound), MSG_ALGO)


def build_computation(comp_def) -> NcbbMpComputation:
    return NcbbMpComputation(comp_def)

"""MGM-2: coordinated 2-variable Maximum Gain Message.

reference parity: pydcop/algorithms/mgm2.py (1,062 LoC).  The reference
runs a 5-state machine per cycle — value, offer, answer, gain, go
(mgm2.py:435) — with offerers chosen with probability ``threshold``
offering coordinated moves to one random neighbor.  Here the five message
phases collapse into *one jitted step*:

1. roles: offerer ~ Bernoulli(threshold) per variable,
2. offers: every offerer picks one random neighbor; the joint pair-move
   cost matrix ``P(d1,d2)`` is computed for **all** neighbor pair edges at
   once from the shared-constraint slice tensor ``S`` (see below), offers
   are just a mask over pair edges,
3. answers: each non-offerer accepts its best received offer (segment-max),
4. gains: matched pairs announce the pair gain, lone non-offerers their
   unilateral MGM gain, rejected offerers 0 (they sit out the cycle, as in
   the reference),
5. go: a pair moves iff its gain strictly beats every neighbor's announced
   gain for *both* members (partner excluded); lone variables follow the
   MGM rule.

The pair-move cost uses the identity
``P(d1,d2) = L_o(d1) + L_t(d2) - S(d1, x_t) - S(x_o, d2) + S(d1, d2)``
where ``L`` is the standard candidate-cost matrix (others fixed) and
``S(d1,d2)`` sums the constraints *shared* by the pair, sliced at the
current values of any third variables.  ``S`` is computed for every
neighbor pair edge by one gather + segment-sum per (position, position)
combination per arity bucket.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import BIG, HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("threshold", "float", None, 0.5),
    AlgoParameterDef("favor", "str", ["unilateral", "coordinated", "no"],
                     "unilateral"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
]

_EPS = 1e-6


class Mgm2Solver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, threshold: float = 0.5,
                 favor: str = "unilateral", stop_cycle: int = 0):
        super().__init__(arrays, stop_cycle)
        self.threshold = float(threshold)
        self.favor = favor

        # --- host-side pair-edge compilation (vectorized builders
        # shared with the sharded solver, graphs/arrays.py) --------------
        from ..graphs.arrays import (out_edge_table, pair_edge_lookup,
                                     pair_eids_for_bucket)

        src = np.asarray(arrays.nbr_src)
        dst = np.asarray(arrays.nbr_dst)
        self.P = len(src)
        lookup = pair_edge_lookup(src, dst, arrays.n_vars)

        # per bucket: pair-edge id for each ordered position pair
        self.pair_eids = [
            jnp.asarray(pair_eids_for_bucket(
                lookup, np.asarray(b.var_ids)))
            for b in arrays.buckets
        ]

        # padded per-variable out-edge lists for random partner choice
        out_edges, deg = out_edge_table(src, arrays.n_vars)
        self.out_edges = jnp.asarray(out_edges)
        self.out_degree = jnp.asarray(deg)
        self.pair_src = jnp.asarray(src.astype(np.int32))
        self.pair_dst = jnp.asarray(dst.astype(np.int32))

    # --- device kernels --------------------------------------------------

    def shared_slices(self, x: jnp.ndarray) -> jnp.ndarray:
        """(P, D, D): for every directed neighbor pair edge (u, v), the sum
        of shared-constraint costs as a function of (u's value, v's value),
        third variables fixed at ``x``."""
        S = jnp.zeros((self.P, self.D, self.D))
        for (cubes, var_ids), pair_eid in zip(self.buckets, self.pair_eids):
            a = cubes.ndim - 1
            if a < 2:
                continue
            C = cubes.shape[0]
            vals = x[var_ids]
            for p in range(a):
                for q in range(a):
                    if p == q:
                        continue
                    t = jnp.moveaxis(cubes, p + 1, a)   # p -> last
                    # after moving p to the end, q's axis is q+1 if q < p
                    # (unchanged) else q (shifted left by one)
                    q_axis = q + 1 if q < p else q
                    t = jnp.moveaxis(t, q_axis, a - 1)
                    t = t.reshape(C, -1, self.D, self.D)
                    idx = jnp.zeros((C,), dtype=jnp.int32)
                    for r in range(a):
                        if r != p and r != q:
                            idx = idx * self.D + vals[:, r]
                    contrib = t[jnp.arange(C), idx]      # (C, D_q, D_p)
                    contrib = jnp.swapaxes(contrib, 1, 2)  # (C, D_p, D_q)
                    S = S + jax.ops.segment_sum(
                        contrib, pair_eid[:, p, q], num_segments=self.P)
        return S

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_role, k_pick, k_tie = jax.random.split(s["key"], 5)
        x = s["x"]
        V, D, P = self.V, self.D, self.P
        ar = jnp.arange(V)

        # phase 1: local view ------------------------------------------------
        L, cur, best_cost, best_val = self.best_response(k_best, x)
        solo_gain = cur - best_cost

        # phase 2: roles + offers -------------------------------------------
        offerer = jax.random.uniform(k_role, (V,)) < self.threshold
        pick = (jax.random.uniform(k_pick, (V,))
                * jnp.maximum(self.out_degree, 1)).astype(jnp.int32)
        chosen_edge = self.out_edges[ar, pick]           # (V,)
        has_nbr = self.out_degree > 0

        S = self.shared_slices(x)                        # (P, D, D)
        o, t = self.pair_src, self.pair_dst
        # P_e(d1, d2) for every pair edge
        pair_cost = (
            L[o][:, :, None] + L[t][:, None, :]
            - S[jnp.arange(P), :, x[t]][:, :, None]
            - S[jnp.arange(P), x[o], :][:, None, :]
            + S
        )
        mask2 = (self.domain_mask[o][:, :, None]
                 & self.domain_mask[t][:, None, :])
        pair_cost = jnp.where(mask2, pair_cost, BIG * 2)
        pair_cur = cur[o] + cur[t] - S[jnp.arange(P), x[o], x[t]]
        flat = pair_cost.reshape(P, -1)
        pair_best = jnp.min(flat, axis=1)
        pair_arg = jnp.argmin(flat, axis=1)
        pair_d1 = pair_arg // D
        pair_d2 = pair_arg % D
        pair_gain = pair_cur - pair_best                 # (P,)

        # an offer lives on edge e iff src is an offerer, chose e, and dst
        # is not an offerer (reference: only non-offerers answer)
        is_offer = (offerer[o] & has_nbr[o]
                    & (chosen_edge[o] == jnp.arange(P))
                    & ~offerer[t] & (pair_gain > _EPS))

        # phase 3: answers — dst accepts its best received offer ------------
        tie = jax.random.uniform(k_tie, (P,))
        offer_score = jnp.where(is_offer, pair_gain + tie * _EPS, -jnp.inf)
        best_offer_at = jax.ops.segment_max(offer_score, t, num_segments=V)
        accepted = is_offer & (offer_score >= best_offer_at[t]) \
            & jnp.isfinite(best_offer_at[t])

        in_pair_src = jax.ops.segment_max(
            accepted.astype(jnp.int32), o, num_segments=V) > 0
        in_pair_dst = jax.ops.segment_max(
            accepted.astype(jnp.int32), t, num_segments=V) > 0
        in_pair = in_pair_src | in_pair_dst
        # per-variable: the accepted edge id (src or dst side)
        eidx = jnp.arange(P)
        edge_of_src = jax.ops.segment_max(
            jnp.where(accepted, eidx, -1), o, num_segments=V)
        edge_of_dst = jax.ops.segment_max(
            jnp.where(accepted, eidx, -1), t, num_segments=V)
        my_edge = jnp.maximum(edge_of_src, edge_of_dst)  # (V,) or -1
        partner = jnp.where(
            in_pair_src, t[jnp.clip(my_edge, 0)], o[jnp.clip(my_edge, 0)])

        # phase 4: announced gains ------------------------------------------
        favor_bonus = {"unilateral": -_EPS, "coordinated": _EPS,
                       "no": 0.0}[self.favor]
        g_pair = pair_gain[jnp.clip(my_edge, 0)] + favor_bonus
        announced = jnp.where(
            in_pair, g_pair,
            jnp.where(offerer, 0.0, solo_gain))

        # phase 5: go — strict max in neighborhood --------------------------
        # neighbor max of announced gains, excluding the partner
        exclude = in_pair[self.pair_dst] \
            & (self.pair_src == partner[self.pair_dst])
        nbr_gain = jnp.where(
            exclude, -jnp.inf, announced[self.pair_src])
        nbr_max = jax.ops.segment_max(
            nbr_gain, self.pair_dst, num_segments=V) \
            if self.has_neighbors else jnp.full((V,), -jnp.inf)

        my_go = announced > nbr_max + _EPS
        # both pair members must go
        partner_go = my_go[partner]
        pair_moves = in_pair & my_go & partner_go & (announced > _EPS)
        solo_moves = (~in_pair) & (~offerer) & (solo_gain > _EPS) & my_go

        # new values: pair members take the pair argmin, solos take best
        pair_val = jnp.where(
            in_pair_src, pair_d1[jnp.clip(my_edge, 0)],
            pair_d2[jnp.clip(my_edge, 0)])
        x_new = jnp.where(pair_moves, pair_val,
                          jnp.where(solo_moves, best_val, x))
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> Mgm2Solver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return Mgm2Solver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend: MGM-2 running ON the agent fabric
# (reference: mgm2.py:435-1062).  The reference's five waiting states —
# value / offer / answer? / gain / go? — with per-state postponed-message
# queues become five sync-mixin sub-cycles per MGM-2 iteration: the
# mixin's round barrier replaces the manual postponing, and states that
# only involve a subset of agents (answer? for offerers, go? for
# committed pairs) ride the mixin's automatic SynchronizationMsg fill.
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    SynchronousComputationMixin, VariableComputation, message_type,
    register)
from ._mp import EPS, best_response, constraints_cost, local_cost, \
    mp_rng, seed_param, sign_for_mode

algo_params = algo_params + [seed_param()]

Mgm2ValueMessage = message_type("mgm2_value", ["value"])
#: offers: list of [my_value, partner_value, gain] triples (a list, not a
#: tuple-keyed dict as in the reference: JSON can't carry tuple keys
#: across processes); gain is in signed (minimizing) space
Mgm2OfferMessage = message_type("mgm2_offer", ["offers", "is_offering"])
Mgm2ResponseMessage = message_type("mgm2_response",
                                   ["accept", "value", "gain"])
Mgm2GainMessage = message_type("mgm2_gain", ["gain"])
Mgm2GoMessage = message_type("mgm2_go", ["go"])

#: sub-cycle roles within one MGM-2 iteration
_PHASE_VALUE, _PHASE_OFFER, _PHASE_RESPONSE, _PHASE_GAIN, _PHASE_GO = \
    range(5)


class Mgm2MpComputation(SynchronousComputationMixin, VariableComputation):
    """MGM-2 on the agent fabric (reference: mgm2.py:435-1062).

    One MGM-2 iteration = five mixin sub-cycles:

    0. value    — everyone announces its value,
    1. offer    — offerers (drawn with prob. ``threshold``) send their
                  coordinated-move offers to one random partner; everyone
                  else receives empty offers (reference sends explicit
                  empty offer messages, mgm2.py:763-770),
    2. response — non-offerers accept/reject the offers they received,
    3. gain     — everyone announces its potential gain (coordinated
                  gain for committed pairs, unilateral otherwise),
    4. go       — committed pairs confirm/cancel the coordinated move.

    All gains travel in signed (minimizing) space, so min/max modes share
    one comparison; the reference's mode-conditional branches
    (mgm2.py:838-847) collapse.
    """

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.threshold = float(params.get("threshold", 0.5))
        self.favor = params.get("favor", "unilateral")
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.constraints = list(comp_def.node.constraints)
        self._rnd = mp_rng(params, self.name)
        self._neighbor_values: Dict[str, object] = {}
        self._neighbor_gains: Dict[str, float] = {}
        self._offers_recv = []  # (sender, offers, is_offering)
        self._partner: Optional[str] = None
        self._is_offerer = False
        self._committed = False
        self._can_move = False
        self._potential_gain = 0.0  # signed space: positive = improves
        self._potential_value = None
        self._current_signed = 0.0

    # ------------------------------------------------------- lifecycle

    def on_start(self):
        self.start_cycle()
        if not self.neighbors:
            _, best, cost = best_response(
                self.variable, self.constraints, {}, None, self.mode,
                rnd=self._rnd)
            self.value_selection(best, cost)
            self.finished()
            return
        self.value_selection(
            self._rnd.choice(list(self.variable.domain.values)))
        self.post_to_all_neighbors(
            Mgm2ValueMessage(self.current_value), MSG_ALGO)

    def on_fast_forward(self, cycle_id):
        # rejoin after repair re-deploy: re-announce what this sub-cycle
        # carries; our own protocol state restarts from a clean slate
        self._clear_iteration()
        phase = cycle_id % 5
        if phase == _PHASE_VALUE:
            self.post_to_all_neighbors(
                Mgm2ValueMessage(self.current_value), MSG_ALGO)
        elif phase == _PHASE_OFFER:
            self.post_to_all_neighbors(
                Mgm2OfferMessage([], False), MSG_ALGO)
        elif phase == _PHASE_GAIN:
            self.post_to_all_neighbors(Mgm2GainMessage(0.0), MSG_ALGO)
        # response / go sub-cycles: nothing to re-announce, the mixin's
        # sync fill closes the round for our neighbors

    @register("mgm2_value")
    def _on_value(self, sender, msg, t):  # pragma: no cover
        pass  # rounds are delivered through on_new_cycle

    @register("mgm2_offer")
    def _on_offer(self, sender, msg, t):  # pragma: no cover
        pass

    @register("mgm2_response")
    def _on_response(self, sender, msg, t):  # pragma: no cover
        pass

    @register("mgm2_gain")
    def _on_gain(self, sender, msg, t):  # pragma: no cover
        pass

    @register("mgm2_go")
    def _on_go(self, sender, msg, t):  # pragma: no cover
        pass

    def on_new_cycle(self, messages, cycle_id):
        phase = cycle_id % 5
        if phase == _PHASE_VALUE:
            self._value_phase(messages)
        elif phase == _PHASE_OFFER:
            self._offer_phase(messages)
        elif phase == _PHASE_RESPONSE:
            self._response_phase(messages)
        elif phase == _PHASE_GAIN:
            self._gain_phase(messages)
        else:
            self._go_phase(messages)

    # ---------------------------------------------------------- phases

    def _value_phase(self, messages):
        """Collect values; draw offerer role; send offers (empty for
        non-partners); compute best unilateral move
        (reference: mgm2.py:734-786)."""
        for sender, (msg, _) in messages.items():
            self._neighbor_values[sender] = msg.value
        sign = sign_for_mode(self.mode)
        assignment = dict(self._neighbor_values)
        assignment[self.variable.name] = self.current_value
        self._current_signed = sign * local_cost(
            self.variable, self.constraints, assignment)

        self._is_offerer = self._rnd.random() < self.threshold
        if self._is_offerer:
            self._partner = self._rnd.choice(sorted(self.neighbors))
        for n in self.neighbors:
            if self._is_offerer and n == self._partner:
                self.post_msg(n, Mgm2OfferMessage(
                    self._compute_offers(), True), MSG_ALGO)
            else:
                self.post_msg(n, Mgm2OfferMessage([], False), MSG_ALGO)

        cur, best, best_cost = best_response(
            self.variable, self.constraints, self._neighbor_values,
            self.current_value, self.mode, rnd=self._rnd)
        gain = sign * (cur - best_cost) if cur is not None else 0.0
        if gain > EPS:
            self._potential_gain = gain
            self._potential_value = best
        else:
            self._potential_gain = 0.0
            self._potential_value = self.current_value

    def _compute_offers(self):
        """All coordinated (my_value, partner_value) moves improving my
        own neighborhood, with their signed gain
        (reference: mgm2.py:520-553)."""
        sign = sign_for_mode(self.mode)
        partner_domain = self._partner_domain()
        offers = []
        for my_val in self.variable.domain.values:
            for p_val in partner_domain:
                assignment = dict(self._neighbor_values)
                assignment[self.variable.name] = my_val
                assignment[self._partner] = p_val
                signed = sign * local_cost(
                    self.variable, self.constraints, assignment)
                gain = self._current_signed - signed
                if gain > EPS:
                    offers.append([my_val, p_val, gain])
        return offers

    def _partner_domain(self):
        for c in self.constraints:
            for v in c.dimensions:
                if v.name == self._partner:
                    return list(v.domain.values)
        # partner shares no constraint with us (cannot happen for
        # hypergraph neighbors): no coordinated move to propose
        return []

    def _offer_phase(self, messages):
        """Non-offerers pick the best received offer and answer every
        offerer; offerers reject any offer they received
        (reference: mgm2.py:787-856)."""
        self._offers_recv = [
            (sender, msg.offers, msg.is_offering)
            for sender, (msg, _) in messages.items()]
        if self._is_offerer:
            for sender, _, is_offering in self._offers_recv:
                if is_offering:
                    self.post_msg(sender, Mgm2ResponseMessage(
                        False, None, 0.0), MSG_ALGO)
            self.sync_neighbors()
            return

        best_offers, best_gain = self._find_best_offer()
        self._committed = False
        accepted_val = None
        if best_offers and best_gain > EPS:
            if best_gain > self._potential_gain + EPS:
                self._committed = True
            elif abs(best_gain - self._potential_gain) <= EPS:
                if self.favor == "coordinated":
                    self._committed = True
                elif self.favor == "no" and self._rnd.random() > 0.5:
                    self._committed = True
        if self._committed:
            p_val, my_val, partner = self._rnd.choice(best_offers)
            accepted_val = p_val
            self._potential_value = my_val
            self._potential_gain = best_gain
            self._partner = partner
        for sender, _, is_offering in self._offers_recv:
            if not is_offering:
                continue
            if self._committed and sender == self._partner:
                self.post_msg(sender, Mgm2ResponseMessage(
                    True, accepted_val, best_gain), MSG_ALGO)
            else:
                self.post_msg(sender, Mgm2ResponseMessage(
                    False, None, 0.0), MSG_ALGO)
        self.sync_neighbors()

    def _find_best_offer(self):
        """Best global gain over all received offers: my local gain over
        the constraints not shared with the offerer, plus the offerer's
        announced local gain (reference: mgm2.py:555-603)."""
        sign = sign_for_mode(self.mode)
        bests, best_gain = [], 0.0
        for sender, offers, is_offering in self._offers_recv:
            if not is_offering:
                continue
            # constraints not involving the offerer: their cost change is
            # mine alone; shared constraints ride the offerer's gain
            not_shared = [
                c for c in self.constraints
                if sender not in c.scope_names]
            for p_val, my_val, partner_gain in offers:
                assignment = dict(self._neighbor_values)
                assignment[sender] = p_val
                assignment[self.variable.name] = my_val
                unary = self.variable.cost_for_val(my_val)
                signed = sign * (
                    constraints_cost(not_shared, assignment) + unary)
                global_gain = (
                    self._current_signed - signed) + float(partner_gain)
                if global_gain > best_gain + EPS:
                    bests = [(p_val, my_val, sender)]
                    best_gain = global_gain
                elif abs(global_gain - best_gain) <= EPS and bests:
                    bests.append((p_val, my_val, sender))
        return bests, best_gain

    def _response_phase(self, messages):
        """Offerers learn their partner's verdict; everyone announces
        its gain (reference: mgm2.py:857-888)."""
        if self._is_offerer:
            self._committed = False
            for sender, (msg, _) in messages.items():
                if sender == self._partner and msg.accept:
                    self._potential_value = msg.value
                    self._potential_gain = float(msg.gain)
                    self._committed = True
        self.post_to_all_neighbors(
            Mgm2GainMessage(self._potential_gain), MSG_ALGO)

    def _gain_phase(self, messages):
        """Committed pairs check the neighborhood and confirm with go
        messages; everyone else applies the MGM unilateral rule
        (reference: mgm2.py:889-968)."""
        for sender, (msg, _) in messages.items():
            self._neighbor_gains[sender] = float(msg.gain)

        if self._potential_gain <= EPS:
            self._can_move = False
            self.sync_neighbors()
            return  # nothing to move this iteration; go sub-cycle idles

        if self._committed:
            others = [g for n, g in self._neighbor_gains.items()
                      if n != self._partner]
            self._can_move = not others or \
                self._potential_gain > max(others) + EPS
            self.post_msg(self._partner,
                          Mgm2GoMessage(bool(self._can_move)), MSG_ALGO)
            self.sync_neighbors()
            return

        self._can_move = False
        gains = self._neighbor_gains
        max_gain = max(gains.values()) if gains else 0.0
        if self._potential_gain > max_gain + EPS:
            self._move_unilateral()
        elif abs(self._potential_gain - max_gain) <= EPS:
            ties = sorted(
                [n for n, g in gains.items()
                 if abs(g - max_gain) <= EPS] + [self.name])
            if ties[0] == self.name:
                self._move_unilateral()
        self.sync_neighbors()

    def _move_unilateral(self):
        sign = sign_for_mode(self.mode)
        self.value_selection(
            self._potential_value,
            sign * (self._current_signed - self._potential_gain))

    def _go_phase(self, messages):
        """Coordinated move happens iff both pair members said go
        (reference: mgm2.py:969-1006); iteration closes, values go out
        for the next one."""
        for sender, (msg, _) in messages.items():
            if sender == self._partner and msg.go and self._can_move:
                self._move_unilateral()
        self.new_cycle()
        self._clear_iteration()
        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()
            return
        self.post_to_all_neighbors(
            Mgm2ValueMessage(self.current_value), MSG_ALGO)

    def _clear_iteration(self):
        self._neighbor_values.clear()
        self._neighbor_gains.clear()
        self._offers_recv = []
        self._partner = None
        self._is_offerer = False
        self._committed = False
        self._can_move = False
        self._potential_gain = 0.0
        self._potential_value = None


def build_computation(comp_def) -> Mgm2MpComputation:
    return Mgm2MpComputation(comp_def)

"""MGM-2: coordinated 2-variable Maximum Gain Message.

reference parity: pydcop/algorithms/mgm2.py (1,062 LoC).  The reference
runs a 5-state machine per cycle — value, offer, answer, gain, go
(mgm2.py:435) — with offerers chosen with probability ``threshold``
offering coordinated moves to one random neighbor.  Here the five message
phases collapse into *one jitted step*:

1. roles: offerer ~ Bernoulli(threshold) per variable,
2. offers: every offerer picks one random neighbor; the joint pair-move
   cost matrix ``P(d1,d2)`` is computed for **all** neighbor pair edges at
   once from the shared-constraint slice tensor ``S`` (see below), offers
   are just a mask over pair edges,
3. answers: each non-offerer accepts its best received offer (segment-max),
4. gains: matched pairs announce the pair gain, lone non-offerers their
   unilateral MGM gain, rejected offerers 0 (they sit out the cycle, as in
   the reference),
5. go: a pair moves iff its gain strictly beats every neighbor's announced
   gain for *both* members (partner excluded); lone variables follow the
   MGM rule.

The pair-move cost uses the identity
``P(d1,d2) = L_o(d1) + L_t(d2) - S(d1, x_t) - S(x_o, d2) + S(d1, d2)``
where ``L`` is the standard candidate-cost matrix (others fixed) and
``S(d1,d2)`` sums the constraints *shared* by the pair, sliced at the
current values of any third variables.  ``S`` is computed for every
neighbor pair edge by one gather + segment-sum per (position, position)
combination per arity bucket.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import BIG, HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("threshold", "float", None, 0.5),
    AlgoParameterDef("favor", "str", ["unilateral", "coordinated", "no"],
                     "unilateral"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
]

_EPS = 1e-6


class Mgm2Solver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, threshold: float = 0.5,
                 favor: str = "unilateral", stop_cycle: int = 0):
        super().__init__(arrays, stop_cycle)
        self.threshold = float(threshold)
        self.favor = favor

        # --- host-side pair-edge compilation -----------------------------
        src = np.asarray(arrays.nbr_src)
        dst = np.asarray(arrays.nbr_dst)
        self.P = len(src)
        eid = {(int(a), int(b)): i for i, (a, b) in enumerate(zip(src, dst))}

        # per bucket: pair-edge id for each ordered position pair
        self.pair_eids = []
        for b in arrays.buckets:
            a = b.arity
            m = np.zeros((b.var_ids.shape[0], a, a), dtype=np.int32)
            for p in range(a):
                for q in range(a):
                    if p == q:
                        continue
                    for c in range(b.var_ids.shape[0]):
                        u, v = int(b.var_ids[c, p]), int(b.var_ids[c, q])
                        m[c, p, q] = eid.get((u, v), 0) if u != v else 0
            self.pair_eids.append(jnp.asarray(m))

        # padded per-variable out-edge lists for random partner choice
        deg = np.zeros(arrays.n_vars, dtype=np.int64)
        for s in src:
            deg[s] += 1
        maxdeg = max(1, int(deg.max()) if len(deg) else 1)
        out_edges = np.zeros((arrays.n_vars, maxdeg), dtype=np.int32)
        fill = np.zeros(arrays.n_vars, dtype=np.int64)
        for i, s in enumerate(src):
            out_edges[s, fill[s]] = i
            fill[s] += 1
        self.out_edges = jnp.asarray(out_edges)
        self.out_degree = jnp.asarray(deg.astype(np.int32))
        self.pair_src = jnp.asarray(src.astype(np.int32))
        self.pair_dst = jnp.asarray(dst.astype(np.int32))

    # --- device kernels --------------------------------------------------

    def shared_slices(self, x: jnp.ndarray) -> jnp.ndarray:
        """(P, D, D): for every directed neighbor pair edge (u, v), the sum
        of shared-constraint costs as a function of (u's value, v's value),
        third variables fixed at ``x``."""
        S = jnp.zeros((self.P, self.D, self.D))
        for (cubes, var_ids), pair_eid in zip(self.buckets, self.pair_eids):
            a = cubes.ndim - 1
            if a < 2:
                continue
            C = cubes.shape[0]
            vals = x[var_ids]
            for p in range(a):
                for q in range(a):
                    if p == q:
                        continue
                    t = jnp.moveaxis(cubes, p + 1, a)   # p -> last
                    # after moving p to the end, q's axis is q+1 if q < p
                    # (unchanged) else q (shifted left by one)
                    q_axis = q + 1 if q < p else q
                    t = jnp.moveaxis(t, q_axis, a - 1)
                    t = t.reshape(C, -1, self.D, self.D)
                    idx = jnp.zeros((C,), dtype=jnp.int32)
                    for r in range(a):
                        if r != p and r != q:
                            idx = idx * self.D + vals[:, r]
                    contrib = t[jnp.arange(C), idx]      # (C, D_q, D_p)
                    contrib = jnp.swapaxes(contrib, 1, 2)  # (C, D_p, D_q)
                    S = S + jax.ops.segment_sum(
                        contrib, pair_eid[:, p, q], num_segments=self.P)
        return S

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_role, k_pick, k_tie = jax.random.split(s["key"], 5)
        x = s["x"]
        V, D, P = self.V, self.D, self.P
        ar = jnp.arange(V)

        # phase 1: local view ------------------------------------------------
        L, cur, best_cost, best_val = self.best_response(k_best, x)
        solo_gain = cur - best_cost

        # phase 2: roles + offers -------------------------------------------
        offerer = jax.random.uniform(k_role, (V,)) < self.threshold
        pick = (jax.random.uniform(k_pick, (V,))
                * jnp.maximum(self.out_degree, 1)).astype(jnp.int32)
        chosen_edge = self.out_edges[ar, pick]           # (V,)
        has_nbr = self.out_degree > 0

        S = self.shared_slices(x)                        # (P, D, D)
        o, t = self.pair_src, self.pair_dst
        # P_e(d1, d2) for every pair edge
        pair_cost = (
            L[o][:, :, None] + L[t][:, None, :]
            - S[jnp.arange(P), :, x[t]][:, :, None]
            - S[jnp.arange(P), x[o], :][:, None, :]
            + S
        )
        mask2 = (self.domain_mask[o][:, :, None]
                 & self.domain_mask[t][:, None, :])
        pair_cost = jnp.where(mask2, pair_cost, BIG * 2)
        pair_cur = cur[o] + cur[t] - S[jnp.arange(P), x[o], x[t]]
        flat = pair_cost.reshape(P, -1)
        pair_best = jnp.min(flat, axis=1)
        pair_arg = jnp.argmin(flat, axis=1)
        pair_d1 = pair_arg // D
        pair_d2 = pair_arg % D
        pair_gain = pair_cur - pair_best                 # (P,)

        # an offer lives on edge e iff src is an offerer, chose e, and dst
        # is not an offerer (reference: only non-offerers answer)
        is_offer = (offerer[o] & has_nbr[o]
                    & (chosen_edge[o] == jnp.arange(P))
                    & ~offerer[t] & (pair_gain > _EPS))

        # phase 3: answers — dst accepts its best received offer ------------
        tie = jax.random.uniform(k_tie, (P,))
        offer_score = jnp.where(is_offer, pair_gain + tie * _EPS, -jnp.inf)
        best_offer_at = jax.ops.segment_max(offer_score, t, num_segments=V)
        accepted = is_offer & (offer_score >= best_offer_at[t]) \
            & jnp.isfinite(best_offer_at[t])

        in_pair_src = jax.ops.segment_max(
            accepted.astype(jnp.int32), o, num_segments=V) > 0
        in_pair_dst = jax.ops.segment_max(
            accepted.astype(jnp.int32), t, num_segments=V) > 0
        in_pair = in_pair_src | in_pair_dst
        # per-variable: the accepted edge id (src or dst side)
        eidx = jnp.arange(P)
        edge_of_src = jax.ops.segment_max(
            jnp.where(accepted, eidx, -1), o, num_segments=V)
        edge_of_dst = jax.ops.segment_max(
            jnp.where(accepted, eidx, -1), t, num_segments=V)
        my_edge = jnp.maximum(edge_of_src, edge_of_dst)  # (V,) or -1
        partner = jnp.where(
            in_pair_src, t[jnp.clip(my_edge, 0)], o[jnp.clip(my_edge, 0)])

        # phase 4: announced gains ------------------------------------------
        favor_bonus = {"unilateral": -_EPS, "coordinated": _EPS,
                       "no": 0.0}[self.favor]
        g_pair = pair_gain[jnp.clip(my_edge, 0)] + favor_bonus
        announced = jnp.where(
            in_pair, g_pair,
            jnp.where(offerer, 0.0, solo_gain))

        # phase 5: go — strict max in neighborhood --------------------------
        # neighbor max of announced gains, excluding the partner
        exclude = in_pair[self.pair_dst] \
            & (self.pair_src == partner[self.pair_dst])
        nbr_gain = jnp.where(
            exclude, -jnp.inf, announced[self.pair_src])
        nbr_max = jax.ops.segment_max(
            nbr_gain, self.pair_dst, num_segments=V) \
            if self.has_neighbors else jnp.full((V,), -jnp.inf)

        my_go = announced > nbr_max + _EPS
        # both pair members must go
        partner_go = my_go[partner]
        pair_moves = in_pair & my_go & partner_go & (announced > _EPS)
        solo_moves = (~in_pair) & (~offerer) & (solo_gain > _EPS) & my_go

        # new values: pair members take the pair argmin, solos take best
        pair_val = jnp.where(
            in_pair_src, pair_d1[jnp.clip(my_edge, 0)],
            pair_d2[jnp.clip(my_edge, 0)])
        x_new = jnp.where(pair_moves, pair_val,
                          jnp.where(solo_moves, best_val, x))
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> Mgm2Solver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return Mgm2Solver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

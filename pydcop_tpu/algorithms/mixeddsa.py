"""MixedDSA: DSA hybrid for problems mixing hard and soft constraints.

reference parity: pydcop/algorithms/mixeddsa.py (476 LoC).  Semantics
(mixeddsa.py:286-320): each cycle a variable first checks whether it can
*reduce the number of violated hard constraints* — if so it moves with
``proba_hard``; otherwise, if the soft cost can be improved (per the DSA
variant rule) it moves with ``proba_soft``.

Hard constraints are recognized at compile time as cost tables containing
infinite (clipped-to-HARD) entries; the per-candidate violated-hard count
is computed exactly like the candidate cost matrix, over indicator cubes.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HARD, HypergraphArrays
from ..ops.kernels import candidate_costs
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("proba_hard", "float", None, 0.7),
    AlgoParameterDef("proba_soft", "float", None, 0.5),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
]

_HARD_THRESH = float(HARD) * 0.99


class MixedDsaSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, proba_hard: float = 0.7,
                 proba_soft: float = 0.5, variant: str = "B",
                 stop_cycle: int = 0):
        super().__init__(arrays, stop_cycle)
        self.proba_hard = float(proba_hard)
        self.proba_soft = float(proba_soft)
        self.variant = variant
        # indicator cubes marking hard-violation cells
        self.hard_buckets = [
            (jnp.asarray((b.cubes >= _HARD_THRESH).astype(np.float32)
                         * (b.cubes < 1e8)),  # exclude BIG padding
             jnp.asarray(b.var_ids))
            for b in arrays.buckets
        ]

    def hard_violation_counts(self, x: jnp.ndarray) -> jnp.ndarray:
        """(V, D) number of violated hard constraints per candidate."""
        total = jnp.zeros((self.V, self.D))
        for cubes, var_ids in self.hard_buckets:
            total = total + candidate_costs(cubes, var_ids, x, self.V)
        return total

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_prob = jax.random.split(s["key"], 3)
        x = s["x"]
        _, cur, best_cost, best_val = self.best_response(k_best, x)
        delta = cur - best_cost

        hard_counts = self.hard_violation_counts(x)
        cur_hard = hard_counts[jnp.arange(self.V), x]
        best_hard = hard_counts[jnp.arange(self.V), best_val]
        reduces_hard = cur_hard > best_hard

        improve = delta > 1e-9
        equal = jnp.abs(delta) <= 1e-9
        if self.variant == "A":
            want = improve
        elif self.variant == "B":
            want = improve | (equal & self.var_has_violated_constraint(x))
        else:
            want = improve | equal

        proba = jnp.where(reduces_hard, self.proba_hard, self.proba_soft)
        lucky = jax.random.uniform(k_prob, (self.V,)) < proba
        change = want & lucky
        x_new = jnp.where(change, best_val, x)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> MixedDsaSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return MixedDsaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

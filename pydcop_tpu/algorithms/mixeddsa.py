"""MixedDSA: DSA hybrid for problems mixing hard and soft constraints.

reference parity: pydcop/algorithms/mixeddsa.py (476 LoC).  Semantics
(mixeddsa.py:286-320): each cycle a variable first checks whether it can
*reduce the number of violated hard constraints* — if so it moves with
``proba_hard``; otherwise, if the soft cost can be improved (per the DSA
variant rule) it moves with ``proba_soft``.

Hard constraints are recognized at compile time as cost tables containing
infinite (clipped-to-HARD) entries; the per-candidate violated-hard count
is computed exactly like the candidate cost matrix, over indicator cubes.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HARD, HypergraphArrays
from ..ops.kernels import candidate_costs
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("proba_hard", "float", None, 0.7),
    AlgoParameterDef("proba_soft", "float", None, 0.5),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
]

_HARD_THRESH = float(HARD) * 0.99


class MixedDsaSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, proba_hard: float = 0.7,
                 proba_soft: float = 0.5, variant: str = "B",
                 stop_cycle: int = 0):
        super().__init__(arrays, stop_cycle)
        self.proba_hard = float(proba_hard)
        self.proba_soft = float(proba_soft)
        self.variant = variant
        # indicator cubes marking hard-violation cells
        self.hard_buckets = [
            (jnp.asarray((b.cubes >= _HARD_THRESH).astype(np.float32)
                         * (b.cubes < 1e8)),  # exclude BIG padding
             jnp.asarray(b.var_ids))
            for b in arrays.buckets
        ]

    def hard_violation_counts(self, x: jnp.ndarray) -> jnp.ndarray:
        """(V, D) number of violated hard constraints per candidate."""
        total = jnp.zeros((self.V, self.D))
        for cubes, var_ids in self.hard_buckets:
            total = total + candidate_costs(cubes, var_ids, x, self.V)
        return self._reduce_vplane(total)

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_prob = jax.random.split(s["key"], 3)
        x = s["x"]
        _, cur, best_cost, best_val = self.best_response(k_best, x)
        delta = cur - best_cost

        hard_counts = self.hard_violation_counts(x)
        cur_hard = hard_counts[jnp.arange(self.V), x]
        best_hard = hard_counts[jnp.arange(self.V), best_val]
        reduces_hard = cur_hard > best_hard

        improve = delta > 1e-9
        equal = jnp.abs(delta) <= 1e-9
        if self.variant == "A":
            want = improve
        elif self.variant == "B":
            want = improve | (equal & self.var_has_violated_constraint(x))
        else:
            want = improve | equal

        proba = jnp.where(reduces_hard, self.proba_hard, self.proba_soft)
        lucky = jax.random.uniform(k_prob, (self.V,)) < proba
        change = want & lucky
        x_new = jnp.where(change, best_val, x)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> MixedDsaSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return MixedDsaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend: MixedDSA running ON the agent fabric
# (reference: mixeddsa.py:154-476).  One value sub-cycle per iteration
# like DSA; the move rule ranks candidates by (violated hard
# constraints, soft cost) and uses proba_hard / proba_soft depending on
# which tier improves.
# ---------------------------------------------------------------------

import math as _math
from typing import Dict as _DictT

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    SynchronousComputationMixin, VariableComputation, message_type,
    register)
from ._mp import EPS, mp_rng, seed_param, sign_for_mode

algo_params = algo_params + [seed_param()]

MixedDsaValueMessage = message_type("mixed_dsa_value", ["value"])


class MixedDsaMpComputation(SynchronousComputationMixin,
                            VariableComputation):
    """MixedDSA on the agent fabric (reference: mixeddsa.py:154-476).
    Hard constraints are those whose cost table contains an infinite
    entry (reference: mixeddsa.py:203-225); candidates are ranked by
    violated-hard count first, soft cost second."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.variant = params.get("variant", "B")
        self.proba_hard = float(params.get("proba_hard", 0.7))
        self.proba_soft = float(params.get("proba_soft", 0.5))
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.constraints = list(comp_def.node.constraints)
        self._rnd = mp_rng(params, self.name)
        self.hard_constraints = []
        self.soft_constraints = []
        for c in self.constraints:
            m = c.to_matrix().matrix
            if _math.isinf(float(abs(m).max())) or \
                    float(abs(m).max()) >= _HARD_THRESH:
                self.hard_constraints.append(c)
            else:
                self.soft_constraints.append(c)
        self._neighbor_values: _DictT[str, object] = {}

    def on_start(self):
        self.start_cycle()
        self.value_selection(
            self._rnd.choice(list(self.variable.domain.values)))
        if not self.neighbors:
            self.finished()
            return
        self.post_to_all_neighbors(
            MixedDsaValueMessage(self.current_value), MSG_ALGO)

    def on_fast_forward(self, cycle_id):
        self.post_to_all_neighbors(
            MixedDsaValueMessage(self.current_value), MSG_ALGO)

    @register("mixed_dsa_value")
    def _on_value(self, sender, msg, t):  # pragma: no cover
        pass  # rounds are delivered through on_new_cycle

    def _tier_cost(self, val):
        """(violated hard count, signed soft cost) for ``val`` under the
        neighbors' values (reference: mixeddsa.py:410-447)."""
        sign = sign_for_mode(self.mode)
        assignment = dict(self._neighbor_values)
        assignment[self.variable.name] = val
        violated = 0
        for c in self.hard_constraints:
            scope = c.scope_names
            if all(n in assignment for n in scope):
                cost = c(**{n: assignment[n] for n in scope})
                if _math.isinf(cost) or abs(cost) >= _HARD_THRESH:
                    violated += 1
        soft = sign * self.variable.cost_for_val(val)
        for c in self.soft_constraints:
            scope = c.scope_names
            if all(n in assignment for n in scope):
                soft += sign * c(**{n: assignment[n] for n in scope})
        return violated, soft

    def on_new_cycle(self, messages, cycle_id):
        for sender, (msg, _) in messages.items():
            self._neighbor_values[sender] = msg.value
        self.new_cycle()

        cur_violated, cur_soft = self._tier_cost(self.current_value)
        best_vals, best_violated, best_soft = [], None, None
        for v in self.variable.domain.values:
            violated, soft = self._tier_cost(v)
            if best_violated is None or violated < best_violated or (
                    violated == best_violated
                    and soft < best_soft - EPS):
                best_vals = [v]
                best_violated, best_soft = violated, soft
            elif violated == best_violated and \
                    abs(soft - best_soft) <= EPS:
                best_vals.append(v)

        delta_hard = cur_violated - best_violated
        delta_soft = cur_soft - best_soft
        sign = sign_for_mode(self.mode)
        if delta_hard > 0:
            if self._rnd.random() < self.proba_hard:
                self.value_selection(self._rnd.choice(best_vals),
                                     sign * best_soft)
        elif delta_hard == 0:
            if delta_soft > EPS:
                if self._rnd.random() < self.proba_soft:
                    self.value_selection(self._rnd.choice(best_vals),
                                         sign * best_soft)
            elif self.variant in ("B", "C") and cur_violated > 0 and \
                    len(best_vals) > 1:
                # stuck with conflicts: sideways move to escape
                # (reference: mixeddsa.py:320-341)
                others = [v for v in best_vals
                          if v != self.current_value]
                if others and self._rnd.random() < self.proba_hard:
                    self.value_selection(self._rnd.choice(others),
                                         sign * best_soft)
            elif self.variant == "C" and len(best_vals) > 1:
                others = [v for v in best_vals
                          if v != self.current_value]
                if others and self._rnd.random() < min(self.proba_hard,
                                                       self.proba_soft):
                    self.value_selection(self._rnd.choice(others),
                                         sign * best_soft)

        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()
            return
        self.post_to_all_neighbors(
            MixedDsaValueMessage(self.current_value), MSG_ALGO)


def build_computation(comp_def) -> MixedDsaMpComputation:
    return MixedDsaMpComputation(comp_def)

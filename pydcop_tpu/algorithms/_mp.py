"""Shared helpers for the message-passing (agent-fabric) backends.

In the reference *every* algorithm runs as message-passing computations
deployed on agents (maxsum.py:279-676, dsa.py:265-357, mgm.py:213-420).
In this framework the compiled engine is the data plane (one jitted step
per synchronous round); the classes built on these helpers are the same
algorithms' *distributed* execution path, running on the agent fabric in
thread / process / multi-machine mode so orchestrated runs exchange real
algorithm messages between agents, exactly like the reference.

Everything here is host-side control-plane code operating on one node's
local neighborhood — small dict/loop math, the compiled engine covers the
large regime.
"""

import random as _random
from typing import Any, Dict, Iterable, List, Optional, Tuple

EPS = 1e-9


def mp_rng(params: Dict[str, Any], name: str) -> _random.Random:
    """Per-computation RNG for the message-passing backends.

    With the ``seed`` algo param set, every computation derives its own
    deterministic stream from ``(seed, name)`` so distributed runs are
    reproducible and can be cross-checked against the compiled engine;
    without it the stream is OS-seeded, like the reference's bare
    ``random`` calls (reference: dsa.py:300, mgm.py:270)."""
    seed = params.get("seed")
    if seed is None:
        return _random.Random()
    return _random.Random(f"{seed}:{name}")


#: declarative ``seed`` parameter shared by the stochastic mp backends
def seed_param():
    from . import AlgoParameterDef

    return AlgoParameterDef("seed", "int", None, None)


#: params consumed only by the message-passing backends; the compiled
#: solvers take their seed from the engine's PRNG key instead
MP_ONLY_PARAMS = frozenset({"seed", "start_messages"})


def engine_params(params):
    """Filter out mp-only params before handing to a compiled solver."""
    return {k: v for k, v in (params or {}).items()
            if k not in MP_ONLY_PARAMS}


def sign_for_mode(mode: str) -> float:
    """min problems search smaller costs, max problems larger; all search
    logic below works in *signed* space (always minimizing)."""
    return 1.0 if mode != "max" else -1.0


def local_cost(variable, constraints, assignment: Dict[str, Any]) -> float:
    """Model cost of this variable's neighborhood under ``assignment``
    (unary variable cost + all fully-instantiated incident constraints)."""
    cost = variable.cost_for_val(assignment[variable.name])
    for c in constraints:
        scope = c.scope_names
        if all(n in assignment for n in scope):
            cost += c(**{n: assignment[n] for n in scope})
    return cost


def constraints_cost(constraints: Iterable,
                     assignment: Dict[str, Any]) -> float:
    """Sum of the fully-instantiated constraints under ``assignment``
    (no unary variable cost)."""
    cost = 0.0
    for c in constraints:
        scope = c.scope_names
        if all(n in assignment for n in scope):
            cost += c(**{n: assignment[n] for n in scope})
    return cost


def best_response(variable, constraints, neighbor_values: Dict[str, Any],
                  current_value, mode: str,
                  prefer_different: bool = False,
                  rnd=None) -> Tuple[Optional[float], Any, float]:
    """(current_cost, best_value, best_cost) for one variable given its
    neighbors' values (reference: dsa.py:407-466, mgm.py:213-420).

    Costs are model costs (caller-facing); the search itself minimizes
    signed cost.  With ``prefer_different`` a minimum other than the
    current value is preferred when several exist (reference DSA
    variant B/C move preference); ties beyond that break randomly when
    ``rnd`` is given, else by domain order.
    """
    sign = sign_for_mode(mode)
    best_vals: List[Any] = []
    best_signed = None
    current_signed = None
    for value in variable.domain.values:
        assignment = dict(neighbor_values)
        assignment[variable.name] = value
        signed = sign * local_cost(variable, constraints, assignment)
        if value == current_value:
            current_signed = signed
        if best_signed is None or signed < best_signed - EPS:
            best_vals, best_signed = [value], signed
        elif signed <= best_signed + EPS:
            best_vals.append(value)
    if prefer_different and len(best_vals) > 1:
        others = [v for v in best_vals if v != current_value]
        if others:
            best_vals = others
    best = rnd.choice(best_vals) if rnd is not None else best_vals[0]
    return (
        None if current_signed is None else sign * current_signed,
        best,
        sign * best_signed,
    )


def constraint_optima(constraints: Iterable, mode: str) -> Dict[str, float]:
    """Per-constraint best achievable *signed* cost, used by the
    "violated constraint" test (reference: dsa.py:450-466)."""
    sign = sign_for_mode(mode)
    optima: Dict[str, float] = {}
    for c in constraints:
        m = sign * c.to_matrix().matrix
        optima[c.name] = float(m.min())
    return optima


def has_violated_constraint(constraints, optima: Dict[str, float],
                            assignment: Dict[str, Any],
                            mode: str) -> bool:
    """True when some fully-instantiated incident constraint is not at
    its own optimum under ``assignment``."""
    sign = sign_for_mode(mode)
    for c in constraints:
        scope = c.scope_names
        if not all(n in assignment for n in scope):
            continue
        signed = sign * c(**{n: assignment[n] for n in scope})
        if signed > optima[c.name] + 1e-6:
            return True
    return False

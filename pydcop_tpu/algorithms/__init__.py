"""Algorithm plugin layer.

reference parity: pydcop/algorithms/__init__.py:99-614.  Each algorithm is
a module in this package declaring:

* ``GRAPH_TYPE`` — which computation graph it runs on,
* ``algo_params: List[AlgoParameterDef]`` — declarative parameters with
  types / allowed values / defaults, validated by ``prepare_algo_params``,
* ``build_solver(dcop, params, variables=None, constraints=None)`` — the
  TPU path: returns an engine solver whose ``step`` is one jitted cycle of
  the algorithm over the whole graph,
* ``computation_memory(node)`` / ``communication_load(node, target)`` —
  analytic footprint/load callbacks used by the distribution layer.

``load_algorithm_module`` injects defaults for the optional pieces, as the
reference does (algorithms/__init__.py:527-566).
"""

import pkgutil
from importlib import import_module
from typing import Any, Dict, Iterable, List, NamedTuple, Optional

from ..utils.simple_repr import SimpleRepr, from_repr, simple_repr


class AlgoParameterDef(NamedTuple):
    name: str
    type: str  # 'str' | 'int' | 'float' | 'bool'
    values: Optional[List[Any]] = None
    default: Any = None


class AlgoParameterException(Exception):
    pass


_CASTS = {
    "str": str,
    "int": int,
    "float": float,
    "bool": lambda v: v if isinstance(v, bool) else str(v).lower() in (
        "1", "true", "yes"),
}


def param_bool(value) -> bool:
    """THE bool-param truthiness rule (the ``_CASTS['bool']`` cast),
    shared with the CLIs: a feature echo or fuse-exclusion decision
    must never disagree with what the solver's own param parsing
    enabled."""
    return _CASTS["bool"](value)


def check_param_value(value: Any, param_def: AlgoParameterDef) -> Any:
    """Cast and validate one parameter value
    (reference: algorithms/__init__.py:446-505)."""
    if value is None:
        return param_def.default
    try:
        cast = _CASTS[param_def.type](value)
    except (KeyError, ValueError, TypeError):
        raise AlgoParameterException(
            f"Invalid value {value!r} for parameter {param_def.name} "
            f"of type {param_def.type}"
        )
    if param_def.values and cast not in param_def.values:
        raise AlgoParameterException(
            f"Value {cast!r} not allowed for parameter {param_def.name}: "
            f"must be one of {param_def.values}"
        )
    return cast


def prepare_algo_params(params: Dict[str, Any],
                        parameters_definitions: List[AlgoParameterDef]
                        ) -> Dict[str, Any]:
    """Validate given params and fill in defaults
    (reference: algorithms/__init__.py:99-137)."""
    defs = {p.name: p for p in parameters_definitions}
    unknown = set(params) - set(defs)
    if unknown:
        raise AlgoParameterException(
            f"Unknown parameter(s) {sorted(unknown)}; "
            f"allowed: {sorted(defs)}"
        )
    out = {}
    for name, p_def in defs.items():
        out[name] = check_param_value(params.get(name), p_def)
    return out


class AlgorithmDef(SimpleRepr):
    """An algorithm selection + parameter values + optimization mode
    (reference: algorithms/__init__.py:141-335)."""

    def __init__(self, algo: str, params: Dict[str, Any],
                 mode: str = "min"):
        self._algo = algo
        self._params = dict(params)
        self._mode = mode

    @classmethod
    def build_with_default_param(
            cls, algo: str, params: Optional[Dict[str, Any]] = None,
            mode: str = "min",
            parameters_definitions: Optional[List[AlgoParameterDef]] = None
    ) -> "AlgorithmDef":
        """Validate ``params`` against the definitions and fill defaults
        (reference doctest: algorithms/__init__.py:220-225).

        >>> algo = AlgorithmDef.build_with_default_param(
        ...     'dsa', {'variant': 'B'})
        >>> algo.param_value('variant')
        'B'
        >>> algo.param_value('probability')
        0.7
        """
        if parameters_definitions is None:
            parameters_definitions = load_algorithm_module(algo).algo_params
        return cls(
            algo,
            prepare_algo_params(params or {}, parameters_definitions),
            mode,
        )

    @property
    def algo(self) -> str:
        return self._algo

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def params(self) -> Dict[str, Any]:
        return dict(self._params)

    def param_names(self) -> Iterable[str]:
        return self._params.keys()

    def param_value(self, name: str) -> Any:
        return self._params[name]

    def __eq__(self, o):
        return (
            isinstance(o, AlgorithmDef)
            and self._algo == o._algo
            and self._params == o._params
            and self._mode == o._mode
        )

    def __repr__(self):
        return f"AlgorithmDef({self._algo!r}, {self._params}, {self._mode!r})"


class ComputationDef(SimpleRepr):
    """A computation node + the algorithm it runs
    (reference: algorithms/__init__.py:336-445)."""

    def __init__(self, node, algo: AlgorithmDef):
        self._node = node
        self._algo = algo

    @property
    def node(self):
        return self._node

    @property
    def algo(self) -> AlgorithmDef:
        return self._algo

    @property
    def name(self) -> str:
        return self._node.name

    def __eq__(self, o):
        return (
            isinstance(o, ComputationDef)
            and self._node == o._node
            and self._algo == o._algo
        )

    def __repr__(self):
        return f"ComputationDef({self.name}, {self._algo.algo})"


def list_available_algorithms() -> List[str]:
    """Discover algorithm modules in this package
    (reference: algorithms/__init__.py:508-526)."""
    out = []
    for _, name, ispkg in pkgutil.iter_modules(__path__):
        # "_"-prefixed modules are shared helpers, not algorithms
        if not ispkg and not name.startswith("_"):
            out.append(name)
    return sorted(out)


def _default_computation_memory(node, *args, **kwargs) -> float:
    return 0.0


def _default_communication_load(node, target, *args, **kwargs) -> float:
    return 0.0


def load_algorithm_module(algo_name: str):
    """Import an algorithm module and inject defaults for optional pieces
    (reference: algorithms/__init__.py:527-566)."""
    module = import_module(f"pydcop_tpu.algorithms.{algo_name}")
    if not hasattr(module, "algo_params"):
        module.algo_params = []
    if not hasattr(module, "computation_memory"):
        module.computation_memory = _default_computation_memory
    if not hasattr(module, "communication_load"):
        module.communication_load = _default_communication_load
    if not hasattr(module, "GRAPH_TYPE"):
        raise AttributeError(
            f"Algorithm module {algo_name} must declare GRAPH_TYPE"
        )
    return module

"""Minimal pedagogical DSA (reference: pydcop/algorithms/dsatuto.py,
126 LoC — the algorithm-implementation tutorial's example).

Equivalent to DSA-A with probability 0.5 and random initial values.
Kept as its own module so the tutorial workflow (``-a dsatuto``) works.
"""

from typing import Dict, Optional

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import hypergraph_footprints
from .dsa import DsaSolver

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("stop_cycle", "int", None, 0),
]


class DsaTutoSolver(DsaSolver):
    def __init__(self, arrays: HypergraphArrays, stop_cycle: int = 0):
        super().__init__(arrays, probability=0.5, variant="A",
                         stop_cycle=stop_cycle)


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DsaTutoSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return DsaTutoSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend — the reference's tutorial implementation
# shape (dsatuto.py:66-126): a VariableComputation using the
# synchronous-rounds mixin, exchanging value messages with neighbors on
# the agent fabric.  This is the control-plane path; the compiled
# DsaTutoSolver above is the data-plane path.
# ---------------------------------------------------------------------

import random as _random

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    SynchronousComputationMixin, VariableComputation, message_type,
    register)

DsaTutoValueMessage = message_type("dsa_value", ["value"])


class DsaTutoComputation(SynchronousComputationMixin,
                         VariableComputation):
    """Synchronous DSA-A with p=0.5 as a message-passing computation
    (reference: dsatuto.py:66-126)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        self.constraints = list(comp_def.node.constraints)
        self.stop_cycle = comp_def.algo.params.get("stop_cycle", 0)
        self.mode = comp_def.algo.mode

    def on_start(self):
        self.start_cycle()
        self.random_value_selection()
        self.post_to_all_neighbors(
            DsaTutoValueMessage(self.current_value), MSG_ALGO)

    @register("dsa_value")
    def _on_value_msg(self, sender, msg, t):
        # never called directly: the sync mixin intercepts on_message
        # and delivers whole rounds through on_new_cycle
        pass  # pragma: no cover

    def on_new_cycle(self, messages, cycle_id):
        neighbor_values = {
            sender: msg.value for sender, (msg, t) in messages.items()}
        self.new_cycle()
        current_cost, best_value, best_cost = self._evaluate(
            neighbor_values)
        if best_cost != current_cost and _random.random() < 0.5:
            self.value_selection(best_value, best_cost)
        # count processed rounds (not the mixin round id, which can jump
        # on fast-forward rejoin)
        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()
            return
        self.post_to_all_neighbors(
            DsaTutoValueMessage(self.current_value), MSG_ALGO)

    def _evaluate(self, neighbor_values):
        """(current model cost, best value, best model cost) given the
        neighbors' current values; "best" minimizes (mode=min) or
        maximizes (mode=max)."""
        from ..dcop.relations import assignment_cost

        sign = 1 if self.mode == "min" else -1
        best_value, best_signed, current_signed = None, None, None
        for value in self.variable.domain.values:
            assignment = dict(neighbor_values)
            assignment[self.variable.name] = value
            signed = sign * assignment_cost(
                assignment, [
                    c for c in self.constraints
                    if set(c.scope_names) <= set(assignment)])
            if value == self.current_value:
                current_signed = signed
            if best_signed is None or signed < best_signed:
                best_value, best_signed = value, signed
        return (None if current_signed is None else sign * current_signed,
                best_value, sign * best_signed)


def build_computation(comp_def) -> DsaTutoComputation:
    return DsaTutoComputation(comp_def)

"""Minimal pedagogical DSA (reference: pydcop/algorithms/dsatuto.py,
126 LoC — the algorithm-implementation tutorial's example).

Equivalent to DSA-A with probability 0.5 and random initial values.
Kept as its own module so the tutorial workflow (``-a dsatuto``) works.
"""

from typing import Dict, Optional

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import hypergraph_footprints
from .dsa import DsaSolver

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("stop_cycle", "int", None, 0),
]


class DsaTutoSolver(DsaSolver):
    def __init__(self, arrays: HypergraphArrays, stop_cycle: int = 0):
        super().__init__(arrays, probability=0.5, variant="A",
                         stop_cycle=stop_cycle)


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DsaTutoSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return DsaTutoSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

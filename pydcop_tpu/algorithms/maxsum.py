"""Synchronous MaxSum: loopy min-sum belief propagation on the factor
graph.

reference parity: pydcop/algorithms/maxsum.py (721 LoC).  Same math —
factor→variable min-marginals, variable→factor cost sums with
average-normalization, damping, stability-based convergence with
``SAME_COUNT`` stable cycles (maxsum.py:106,688) — but one cycle of the
*whole* factor graph is a single jitted XLA step over stacked arrays:

* factor update ↔ ``factor_costs_for_var`` (maxsum.py:382): the reference
  brute-forces the factor's joint assignment space in Python per neighbor;
  here it is one broadcast-add over the arity-bucketed cost hypercubes and
  an axis-min (``ops.factor_messages``).
* variable update ↔ ``costs_for_factor`` (maxsum.py:623-676): segment-sum
  of incoming messages + unary costs, minus the per-edge echo, normalized
  by the valid-domain mean.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..dcop.dcop import DCOP
from ..engine.solver import ArraySolver
from ..graphs.arrays import BIG, SENTINEL, FactorGraphArrays
from ..ops.kernels import (
    assignment_cost_device,
    belief_margins,
    build_pruned_plan,
    decimation_select,
    device_pruned_plan,
    factor_messages,
    factor_messages_pruned,
    masked_argmin,
)
from ..ops.precision import resolve as resolve_precision
from . import AlgoParameterDef

GRAPH_TYPE = "factor_graph"

#: cycles of stable costs+selection before declaring convergence
#: (reference: maxsum.py:106 SAME_COUNT = 4)
SAME_COUNT = 4

#: default decimation period (cycles between freeze events) when
#: ``decimation_p`` is set without an explicit ``decimation_every`` —
#: matches the mesh engine's default chunk (engine/mesh_engine.py
#: DEFAULT_CHUNK), so freeze events land exactly on the chunked
#: engines' existing sync boundaries: zero extra host round-trips,
#: like the PR 5 telemetry drain
DECIMATION_DEFAULT_EVERY = 32


def normalize_decimation(p, every):
    """Validate the decimation knobs; returns ``(p, enabled, every)``.
    ONE copy of the rule for the single-chip AND sharded families, so
    the schedule semantics can never drift between them."""
    p = float(p)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"decimation_p must be in [0, 1], got {p!r}")
    every = int(every) or DECIMATION_DEFAULT_EVERY
    if every < 1:
        raise ValueError(
            f"decimation_every must be >= 1, got {every!r}")
    return p, p > 0, every

HEADER_SIZE = 0
UNIT_SIZE = 1

algo_params = [
    AlgoParameterDef("damping", "float", None, 0.5),
    AlgoParameterDef("damping_nodes", "str",
                     ["vars", "factors", "both", "none"], "vars"),
    AlgoParameterDef("stability", "float", None, 0.1),
    # check the convergence delta on E-sized messages (default) or on
    # the ~degree-times-smaller V-sized beliefs
    AlgoParameterDef("delta_on", "str", ["messages", "beliefs"],
                     "messages"),
    AlgoParameterDef("noise", "float", None, 0.0),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    # lane_major puts edges in the 128-wide lane dim + uses the fused
    # pallas factor kernels on TPU (binary and small-n-ary buckets);
    # fused additionally var-sorts the edge slots so the cycle's only
    # irregular ops are static permutation gathers (one for binary-only
    # graphs; one per (arity, position) bucket + one assembly gather
    # for n-ary, zero scatters either way); auto picks lane_major when
    # every bucket's D**arity fits the fast-path threshold
    AlgoParameterDef("layout", "str",
                     ["auto", "edge_major", "lane_major", "fused"],
                     "auto"),
    # mixed-precision policy (ops/precision.py): bf16 stores the cost
    # planes (cubes + unary costs) at half the bytes; sums and the
    # recurrent message planes stay in f32, so integer-cost instances
    # reproduce the f32 selections and convergence cycles bit-exactly.
    # Default None defers to the PYDCOP_TPU_PRECISION environment
    # variable, then f32; auto = bf16 on TPU backends only.
    AlgoParameterDef("precision", "str", ["f32", "bf16", "auto"], None),
    # decimated Max-Sum (arXiv 1706.02209): every `decimation_every`
    # cycles, pin the top-`decimation_p` fraction of the most-confident
    # (largest belief-margin) unfrozen variables and clamp their
    # outgoing messages, so loopy instances settle instead of
    # oscillating.  0 (the default) disables decimation entirely — the
    # compiled step is byte-identical to the undecimated solver.
    AlgoParameterDef("decimation_p", "float", None, 0.0),
    # cycles between freeze events; 0 = the chunk-aligned default
    # (DECIMATION_DEFAULT_EVERY) when decimation_p > 0
    AlgoParameterDef("decimation_every", "int", None, 0),
    # branch-and-bound pruned factor reductions (arXiv 1906.06863):
    # arity >= 3 buckets big enough to pay for bound checks sweep
    # their hypercubes in build-time bound-sorted order and early-out
    # cells a per-factor suffix bound already excludes.  Messages stay
    # bit-exact with the full scan; off (the default) leaves every
    # kernel untouched.
    AlgoParameterDef("bnb", "bool", None, False),
]


class MaxSumSolver(ArraySolver):
    def __init__(self, arrays: FactorGraphArrays, damping: float = 0.5,
                 damping_nodes: str = "vars", stability: float = 0.1,
                 noise: float = 0.0, stop_cycle: int = 0,
                 delta_on: str = "messages", precision=None,
                 decimation_p: float = 0.0, decimation_every: int = 0,
                 bnb: bool = False):
        self.arrays = arrays
        self.var_names = arrays.var_names
        # mixed-precision policy: cost planes materialize on device in
        # store_dtype; the q/r message recurrence and every sum stay in
        # accum_dtype (f32) — see ops/precision.py for why min is safe
        # in bf16 and sums are not
        self.policy = resolve_precision(precision)
        self.damping = float(damping)
        self.damping_nodes = damping_nodes
        if delta_on not in ("messages", "beliefs"):
            raise ValueError(
                f"delta_on must be 'messages' or 'beliefs', "
                f"got {delta_on!r}")
        # "beliefs" checks the convergence delta on the (V-sized)
        # belief tables instead of the (E-sized) message arrays —
        # the r3 ablation priced the message max-reduce at ~1/3 of the
        # convergence-enabled step; the belief table is ~degree times
        # smaller.  Semantics: still SAME_COUNT stable cycles AND an
        # unchanged selection; only the "how much is still moving"
        # observable changes (precedent: the reference's approx_match
        # tolerance, maxsum.py:688, is itself an approximation).
        self.delta_on = delta_on
        # damping shrinks per-cycle message deltas by (1 - damping); scale
        # the stability threshold so convergence detection is
        # damping-invariant (total remaining change ~ delta / (1-damping))
        self.stability_param = float(stability)  # as the user gave it
        self.stability = float(stability)
        if damping_nodes in ("vars", "both") and 0 < damping < 1:
            self.stability *= (1 - float(damping))
        self.noise = float(noise)
        self.stop_cycle = int(stop_cycle)
        self._init_decimation(decimation_p, decimation_every)
        self.bnb = bool(bnb)
        # branch-and-bound reduction plans, built alongside the other
        # host-side layout work: one per arity >= 3 bucket big enough
        # to pay for the bound checks (ops/kernels.py BNB_MIN_CELLS);
        # None entries keep the full-scan kernels.  With bnb off the
        # list stays empty and every compiled program is untouched.
        self._bnb_plans_np = [
            build_pruned_plan(b.cubes) for b in arrays.buckets
        ] if self.bnb else []
        self._bnb_active = any(p is not None
                               for p in self._bnb_plans_np)
        self._bnb_cells_total = sum(
            p.n_blocks * p.block * b.cubes.shape[0]
            for p, b in zip(self._bnb_plans_np, arrays.buckets)
            if p is not None)

        # device constants are LAZY: materializing them eagerly would
        # initialize the accelerator backend (seconds through the
        # tunnel) even for tiny problems the host engine solves in
        # microseconds without ever touching a device
        self._dev_cache: Dict[str, object] = {}
        self.E = arrays.n_edges
        self.D = arrays.max_domain
        self.V = arrays.n_vars
        # Canonical factor-major edge layout (edge 2f/2f+1 = the two
        # endpoints of factor f, as the fast generators emit): the
        # per-bucket gather/scatter degenerates into reshapes, removing
        # the two most expensive irregular ops of the cycle on TPU.
        self._canonical = self._detect_canonical(arrays)

    _trace_fallback_warned = False

    @staticmethod
    def _tracing() -> bool:
        try:
            from jax._src.core import trace_state_clean

            return not trace_state_clean()
        except Exception:  # pragma: no cover - jax internals moved
            # fall back to a PUBLIC signal: a primitive bound under an
            # active trace yields a Tracer.  Loudly, once — the probe
            # array materializes on the backend when NOT under a trace,
            # so the fallback silently costs a backend init that the
            # lazy-constants design otherwise avoids.
            if not MaxSumSolver._trace_fallback_warned:
                MaxSumSolver._trace_fallback_warned = True
                import warnings

                warnings.warn(
                    "jax._src.core.trace_state_clean is gone in this "
                    "jax version; falling back to a Tracer-instance "
                    "probe for trace detection (device constants may "
                    "trigger an eager backend init)", RuntimeWarning)
            try:
                import jax

                return isinstance(jnp.zeros(()), jax.core.Tracer)
            except Exception:
                return True  # can't tell at all: never cache

    def _dev(self, name, build):
        out = self._dev_cache.get(name)
        if out is None:
            if self._tracing():
                # under a jit trace jnp.asarray yields jaxpr-constant
                # tracers: use them for this trace but never cache
                return build()
            out = self._dev_cache[name] = build()
        return out

    @property
    def var_costs(self):
        return self._dev(
            "var_costs",
            lambda: jnp.asarray(self.arrays.var_costs,
                                dtype=self.policy.store_dtype))

    @property
    def domain_mask(self):
        return self._dev("domain_mask",
                         lambda: jnp.asarray(self.arrays.domain_mask))

    @property
    def domain_size(self):
        return self._dev("domain_size",
                         lambda: jnp.asarray(self.arrays.domain_size))

    @property
    def edge_var(self):
        return self._dev("edge_var",
                         lambda: jnp.asarray(self.arrays.edge_var))

    @property
    def buckets(self):
        return self._dev("buckets", lambda: [
            (jnp.asarray(b.cubes, dtype=self.policy.store_dtype),
             jnp.asarray(b.edge_ids), jnp.asarray(b.var_ids))
            for b in self.arrays.buckets
        ])

    @buckets.setter
    def buckets(self, value):
        # BatchedMaxSum swaps per-instance cubes in under vmap
        self._dev_cache["buckets"] = value

    @staticmethod
    def _detect_canonical(arrays):
        from ..graphs.arrays import canonical_edge_layout

        return canonical_edge_layout(arrays)

    # -------------------------------------------- decimation plumbing

    def _init_decimation(self, p, every):
        """Validate and normalize the decimation knobs (shared with
        the sharded families, which call this from ``_init_params``)."""
        (self.decimation_p, self.decimation,
         self.decimation_every) = normalize_decimation(p, every)

    @property
    def bnb_plans(self):
        """Device-placed branch-and-bound plans, aligned with the
        bucket list (None = full scan); cube values ride the precision
        policy's store dtype like every other cost plane."""
        return self._dev("bnb_plans", lambda: [
            None if p is None
            else device_pruned_plan(p, self.policy.store_dtype)
            for p in self._bnb_plans_np
        ])

    def _pruned_fraction(self, runs):
        """Executed-block counts -> the cycle's pruned-cell fraction
        (over the planned buckets only; 0.0 when nothing qualified)."""
        if not runs or not self._bnb_cells_total:
            return jnp.float32(0)
        executed = jnp.float32(0)
        for br, cells_per_block in runs:
            executed = executed + br.astype(jnp.float32) \
                * jnp.float32(cells_per_block)
        return 1.0 - executed / jnp.float32(self._bnb_cells_total)

    def _init_extras_state(self, state):
        """Attach the decimation freeze plane / pin values and the
        pruned-fraction slot to a freshly built carry — no-ops (and
        byte-identical carries) when both features are off."""
        if self.decimation:
            state["frozen"] = jnp.zeros((self.V,), dtype=bool)
            state["pin"] = jnp.zeros((self.V,), dtype=jnp.int32)
        if self._bnb_active:
            state["pruned"] = jnp.float32(0)
        return state

    def _decim_eligible(self):
        """Freeze candidacy: variables with a real choice.  Phantom
        variables from ``pad_to`` expose exactly one valid slot, so
        ``domain_size > 1`` keeps them (and genuinely fixed variables)
        out of the freeze budget — per-instance fractions stay honest
        under the vmapped hetero runners, whose swapped-in
        ``domain_size`` plane this reads."""
        return self.domain_size > 1

    def _apply_decimation(self, s, belief, bmask, q_new, owner,
                          eligible, lane, select_fn):
        """One cycle's decimation work, shared by every layout: on
        event cycles (``(cycle + 1) % decimation_every == 0`` — the
        chunk-aligned schedule) freeze the top-p most-confident
        unfrozen variables at their current argmin; every cycle, clamp
        frozen variables' outgoing messages to a hard pin (0 at the
        pinned slot, BIG elsewhere).  ``owner`` maps message columns/
        rows to variable indices in this layout's variable order;
        ``lane`` flips the (D, E) vs (E, D) orientation.  The freeze
        computation itself rides a ``lax.cond``, so non-event cycles
        skip the sort entirely (under vmap it degrades to a select —
        still correct, just not free)."""
        frozen, pin = s["frozen"], s["pin"]
        do = ((s["cycle"] + 1) % self.decimation_every) == 0

        def _freeze(_):
            margins = belief_margins(belief, bmask,
                                     axis=0 if lane else -1)
            newly = decimation_select(margins, frozen, eligible,
                                      self.decimation_p)
            return newly, select_fn(belief)

        def _skip(_):
            return jnp.zeros_like(frozen), pin

        newly, sel_raw = jax.lax.cond(do, _freeze, _skip, None)
        frozen = jnp.logical_or(frozen, newly)
        pin = jnp.where(newly, sel_raw, pin)
        froz_e = frozen[owner]
        pin_e = pin[owner]
        if lane:
            clamp = jnp.where(
                jnp.arange(self.D)[:, None] == pin_e[None, :],
                0.0, BIG)
            q_new = jnp.where(froz_e[None, :],
                              clamp.astype(q_new.dtype), q_new)
        else:
            clamp = jnp.where(
                jnp.arange(self.D)[None, :] == pin_e[:, None],
                0.0, BIG)
            q_new = jnp.where(froz_e[:, None],
                              clamp.astype(q_new.dtype), q_new)
        return q_new, frozen, pin

    def _finish_step(self, s, key, q_new, new_r, selection, delta,
                     belief, frozen=None, pin=None, pruned=None):
        """The layout-shared step tail: pin frozen selections, run the
        convergence bookkeeping, re-attach feature carries."""
        if frozen is not None and self.stability > 0:
            selection = jnp.where(frozen, pin, selection)
        out = self._advance(s, key, q_new, new_r, selection, delta,
                            belief=belief)
        if frozen is not None:
            out["frozen"] = frozen
            out["pin"] = pin
        if pruned is not None:
            out["pruned"] = pruned
        return out

    def _pin_indices(self, s, idx):
        """Frozen variables keep their pinned value through any
        selection decode."""
        if self.decimation and "frozen" in s:
            return jnp.where(s["frozen"], s["pin"], idx)
        return idx

    def init_state(self, key):
        edge_mask = self.domain_mask[self.edge_var]
        zeros = jnp.where(edge_mask, 0.0, BIG)
        belief = self.var_costs
        state = {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "q": zeros,               # var -> factor messages (E, D)
            "r": jnp.zeros_like(zeros),  # factor -> var messages (E, D)
            "selection": masked_argmin(belief, self.domain_mask),
            "same": jnp.int32(0),
        }
        return self._init_belief_carry(
            self._init_extras_state(state), belief)

    def _cubes(self, s):
        """Per-bucket cost hypercubes.  Static solver constants here; the
        dynamic variant (maxsum_dynamic) stores them in the state pytree so
        the host can swap factor functions between steps."""
        return [cubes for cubes, _, _ in self.buckets]

    def _bucket_factor_messages(self, bi, cubes, q_in, pruned_runs):
        """One bucket's messages: the branch-and-bound sweep when a
        plan exists (recording its executed-block count), else the
        full-scan broadcast kernel — bit-exact either way."""
        plan = self.bnb_plans[bi] if self._bnb_active else None
        if plan is None:
            return factor_messages(cubes, q_in)
        msgs, blocks_run = factor_messages_pruned(plan, q_in)
        pruned_runs.append(
            (blocks_run, plan.block * cubes.shape[0]))
        return msgs

    def step(self, s):
        q, r = s["q"], s["r"]
        edge_mask = self.domain_mask[self.edge_var]

        # --- factor update: min-marginal messages per arity bucket -------
        pruned_runs = []
        if self._canonical is not None:
            # factor-major layout: slices + reshapes, no gather/scatter
            blocks = []
            for bi, (cubes, spec) in enumerate(
                    zip(self._cubes(s), self._canonical)):
                if spec is None:
                    continue
                offset, f, arity = spec
                q_blk = q[offset:offset + f * arity] \
                    .reshape(f, arity, self.D)
                q_in = [q_blk[:, p] for p in range(arity)]
                msgs = self._bucket_factor_messages(
                    bi, cubes, q_in, pruned_runs)
                blocks.append(jnp.stack(msgs, axis=1)
                              .reshape(f * arity, self.D))
            if not blocks:  # unary-only problem: no factor messages
                new_r = jnp.zeros((self.E, self.D), dtype=q.dtype)
            elif len(blocks) == 1:
                new_r = blocks[0]
            else:
                new_r = jnp.concatenate(blocks, axis=0)
        else:
            new_r = jnp.zeros((self.E, self.D), dtype=q.dtype)
            for bi, (cubes, (_, edge_ids, _)) in enumerate(
                    zip(self._cubes(s), self.buckets)):
                arity = cubes.ndim - 1
                if arity == 0:
                    continue
                q_in = [q[edge_ids[:, p]] for p in range(arity)]
                msgs = self._bucket_factor_messages(
                    bi, cubes, q_in, pruned_runs)
                for p in range(arity):
                    new_r = new_r.at[edge_ids[:, p]].set(msgs[p])
        if self.damping_nodes in ("factors", "both") and self.damping > 0:
            new_r = self.damping * r + (1 - self.damping) * new_r

        # --- variable update --------------------------------------------
        sum_r = jax.ops.segment_sum(new_r, self.edge_var,
                                    num_segments=self.V)
        belief = self.var_costs + sum_r
        q_new = belief[self.edge_var] - new_r
        # normalize by the average over valid slots (maxsum.py:623-676)
        mean = (jnp.sum(jnp.where(edge_mask, q_new, 0.0), axis=1)
                / self.domain_size[self.edge_var])
        q_new = q_new - mean[:, None]
        key = s["key"]
        if self.noise > 0:
            key, sub = jax.random.split(key)
            q_new = q_new + self.noise * jax.random.uniform(
                sub, q_new.shape)
        if self.damping_nodes in ("vars", "both") and self.damping > 0:
            q_new = self.damping * q + (1 - self.damping) * q_new
        q_new = jnp.where(edge_mask, q_new, BIG)

        # --- decimation: freeze events + frozen-message clamp -----------
        frozen = pin = None
        if self.decimation:
            q_new, frozen, pin = self._apply_decimation(
                s, belief, self.domain_mask, q_new, self.edge_var,
                self._decim_eligible(), lane=False,
                select_fn=lambda b: masked_argmin(b, self.domain_mask))

        # --- selection & convergence ------------------------------------
        # stability <= 0 disables convergence detection entirely: the
        # per-cycle argmin AND the delta max-reduce are dead compute in
        # the loop — carry the stale selection and recompute it from the
        # final messages in assignment_indices (dead-reduce elision)
        selection = masked_argmin(belief, self.domain_mask) \
            if self.stability > 0 else s["selection"]
        delta = self._convergence_delta(
            s, q, q_new, belief, edge_mask, self.domain_mask, self.E)
        return self._finish_step(
            s, key, q_new, new_r, selection, delta, belief=belief,
            frozen=frozen, pin=pin,
            pruned=self._pruned_fraction(pruned_runs)
            if self._bnb_active else None)

    def _init_belief_carry(self, state, belief):
        """Attach the delta_on=beliefs carry — COPIED: the initial
        belief aliases a cached device constant, and a donated state
        pytree would otherwise delete the cache out from under the
        next init_state.  Cast to the in-step belief dtype (store +
        accum promotion): under the bf16 policy the initial belief IS
        the bf16 cost plane while every stepped belief is an f32 sum,
        and a ``lax.while_loop`` carry must keep one dtype."""
        if self.stability > 0 and self.delta_on == "beliefs":
            accum = jnp.promote_types(belief.dtype,
                                      self.policy.accum_dtype)
            if belief.dtype != accum:
                belief = belief.astype(accum)
            state["belief"] = belief.copy()
        return state

    def _convergence_delta(self, s, q, q_new, belief, edge_mask,
                           belief_mask, n_edges):
        """The SAME_COUNT delta in the configured observable: E-sized
        messages (reference semantics) or V-sized beliefs (the cheap
        variant) — one copy for every state layout."""
        if not n_edges or self.stability <= 0:
            return jnp.float32(0)
        if self.delta_on == "beliefs":
            return jnp.max(jnp.where(
                belief_mask, jnp.abs(belief - s["belief"]), 0.0))
        return jnp.max(jnp.where(edge_mask, jnp.abs(q_new - q), 0.0))

    def _advance(self, s, key, q_new, new_r, selection, delta,
                 belief=None):
        """Shared convergence bookkeeping (SAME_COUNT stable cycles,
        stop_cycle cap) — one copy for every state layout."""
        cycle = s["cycle"] + 1
        if self.stability > 0:
            stable = jnp.logical_and(
                jnp.all(selection == s["selection"]),
                delta < self.stability)
            same = jnp.where(stable, s["same"] + 1, 0)
            finished = same >= SAME_COUNT
        else:
            # stability disabled: only stop_cycle / max_cycles end the
            # run, so the stable/same comparisons are dead compute
            same = s["same"]
            finished = jnp.bool_(False)
        if self.stop_cycle:
            finished = jnp.logical_or(finished, cycle >= self.stop_cycle)
        out = dict(s)  # preserve algorithm-private extras (e.g. dynamic
        # factor tables in maxsum_dynamic)
        out.update(
            cycle=cycle, finished=finished, key=key,
            q=q_new, r=new_r, selection=selection, same=same,
        )
        if "belief" in s:
            out["belief"] = belief
        return out

    def assignment_indices(self, s):
        if self.stability > 0:
            return self._pin_indices(s, s["selection"])
        # lazy selection (see step): rebuild beliefs from the final
        # factor->var messages, which is exactly the in-step belief
        belief = self.var_costs + jax.ops.segment_sum(
            s["r"], self.edge_var, num_segments=self.V)
        return self._pin_indices(
            s, masked_argmin(belief, self.domain_mask))

    # ---------------------------------------------------------- host path

    #: subclasses with device-only semantics (stochastic activation,
    #: dynamic factor swaps) opt out of the host engine
    host_path = True

    def host_cells(self) -> int:
        """Per-cycle work in table cells — the host/device dispatch
        metric for tiny problems (see SyncEngine)."""
        import numpy as np

        a = self.arrays
        return int(sum(np.asarray(b.cubes).size * max(1, b.cubes.ndim - 1)
                       for b in a.buckets)) + a.n_edges * a.max_domain

    def use_host_engine(self) -> bool:
        # decimation needs the compiled freeze plane; the numpy mirror
        # stays the plain-MaxSum oracle (bnb is output-identical, so it
        # simply doesn't apply on the host path)
        return self.host_path and self.noise == 0 \
            and not self.decimation

    def host_run(self, max_cycles: int, timeout=None,
                 collect_cost_every=None, variables=None):
        """Pure-numpy mirror of the compiled cycle for tiny problems:
        an XLA trace+compile costs seconds while a 10-variable solve is
        microseconds of arithmetic — the reference's CI-sized instances
        (tests/api/test_api_solve.py:36-93) must answer instantly, not
        after a compile.  Same math as :meth:`step` (damping, mean
        normalization, SAME_COUNT/stability convergence, argmin
        tie-to-first), so results match the device path for noise=0."""
        import time as _time

        import numpy as np

        from ..engine.solver import RunResult

        t0 = _time.perf_counter()
        a = self.arrays
        E, D, V = a.n_edges, a.max_domain, a.n_vars
        np_buckets = [
            (np.asarray(b.cubes, dtype=np.float32),
             np.asarray(b.edge_ids), np.asarray(b.var_ids))
            for b in a.buckets
        ]
        edge_var = np.asarray(a.edge_var)
        var_costs = np.asarray(a.var_costs, dtype=np.float32)
        domain_mask = np.asarray(a.domain_mask)
        dsize = np.asarray(a.domain_size, dtype=np.float32)
        emask = domain_mask[edge_var]

        def select(belief):
            return np.argmin(np.where(domain_mask, belief, SENTINEL),
                             axis=1)

        def total_cost(sel):
            cost = float(var_costs[np.arange(V), sel].sum())
            for cubes, _, var_ids in np_buckets:
                arity = cubes.ndim - 1
                idx = (np.arange(cubes.shape[0]),) + tuple(
                    sel[var_ids[:, p]] for p in range(arity))
                cost += float(cubes[idx].sum())
            return cost

        q = np.where(emask, 0.0, BIG).astype(np.float32)
        r = np.zeros_like(q)
        sel = select(var_costs)
        same, cycle, finished = 0, 0, False
        timed_out = False
        trace = []
        while cycle < max_cycles and not finished:
            if timeout is not None and \
                    _time.perf_counter() - t0 > timeout:
                timed_out = True
                break
            new_r = np.zeros_like(q)
            for cubes, edge_ids, _ in np_buckets:
                arity = cubes.ndim - 1
                if arity == 0:
                    continue
                shaped = []
                total = cubes
                for p in range(arity):
                    shp = [cubes.shape[0]] + [1] * arity
                    shp[p + 1] = D
                    s_p = q[edge_ids[:, p]].reshape(shp)
                    shaped.append(s_p)
                    total = total + s_p
                for p in range(arity):
                    axes = tuple(i + 1 for i in range(arity) if i != p)
                    msg = total - shaped[p]
                    new_r[edge_ids[:, p]] = \
                        msg.min(axis=axes) if axes else msg
            if self.damping_nodes in ("factors", "both") \
                    and self.damping > 0:
                new_r = self.damping * r + (1 - self.damping) * new_r
            sum_r = np.zeros((V, D), dtype=np.float32)
            np.add.at(sum_r, edge_var, new_r)
            belief = var_costs + sum_r
            q_new = belief[edge_var] - new_r
            mean = np.where(emask, q_new, 0.0).sum(axis=1) \
                / dsize[edge_var]
            q_new = q_new - mean[:, None]
            if self.damping_nodes in ("vars", "both") \
                    and self.damping > 0:
                q_new = self.damping * q + (1 - self.damping) * q_new
            q_new = np.where(emask, q_new, BIG).astype(np.float32)
            new_sel = select(belief)
            if self.stability > 0:
                delta = float(np.max(np.where(
                    emask, np.abs(q_new - q), 0.0))) if E else 0.0
                stable = np.array_equal(new_sel, sel) \
                    and delta < self.stability
                same = same + 1 if stable else 0
                finished = same >= SAME_COUNT
            q, r, sel = q_new, new_r, new_sel
            cycle += 1
            if self.stop_cycle and cycle >= self.stop_cycle:
                finished = True
            if collect_cost_every and cycle % collect_cost_every == 0:
                trace.append((cycle, total_cost(sel)))

        if variables is not None:
            by_name = {v.name: v for v in variables}
            assignment = {
                name: by_name[name].domain.values[int(i)]
                for name, i in zip(self.var_names, sel)}
        else:
            assignment = {name: int(i)
                          for name, i in zip(self.var_names, sel)}
        return RunResult(
            assignment=assignment, cycles=cycle, finished=finished,
            cost=total_cost(sel), violations=0,
            duration=_time.perf_counter() - t0,
            status="FINISHED" if finished
            else ("TIMEOUT" if timed_out else "MAX_CYCLES"),
            cost_trace=trace,
        )

    def cost(self, s):
        return assignment_cost_device(
            [(cubes, var_ids) for cubes, (_, _, var_ids)
             in zip(self._cubes(s), self.buckets)],
            self.var_costs, self.assignment_indices(s),
        )


class MaxSumLaneSolver(MaxSumSolver):
    """Lane-major MaxSum: state is ``(D, E)`` — edges ride the 128-wide
    lane dimension instead of the tiny domain axis (which pads to 128
    lanes in edge-major layout and wastes ~|D|/128 of every tile).

    Requires the canonical factor-major edge layout with every bucket's
    per-factor hypercube small enough to unroll
    (``D**arity <= NARY_FAST_MAX_CELLS``); ``build_solver`` falls back
    to :class:`MaxSumSolver` — the generic XLA path, kept as the
    correctness oracle — otherwise.  The factor update dispatches per
    arity bucket: on TPU binary and n-ary buckets each run as one fused
    pallas kernel (``ops/pallas_kernels.py``); elsewhere jnp fallbacks
    keep results identical.  Same message semantics and convergence
    rules as the base solver (messages equal up to float assoc).
    """

    @staticmethod
    def eligible(arrays: FactorGraphArrays) -> bool:
        """True when the graph supports lane-major layout: canonical
        factor-major edges, every bucket's hypercube under the
        fast-path unroll threshold (``ops.pallas_kernels.
        nary_fast_eligible`` — the ONE copy of that predicate)."""
        from ..ops.pallas_kernels import nary_fast_eligible

        layout = MaxSumSolver._detect_canonical(arrays)
        if layout is None:
            return False
        D = arrays.max_domain
        return all(spec is None or nary_fast_eligible(D, spec[2])
                   for spec in layout)

    def __init__(self, arrays: FactorGraphArrays, use_pallas=None,
                 **kwargs):
        super().__init__(arrays, **kwargs)
        if not self.eligible(arrays):
            from ..ops.pallas_kernels import NARY_FALLBACK_TEXT

            raise ValueError(
                "lane-major layout needs the canonical factor-major "
                "edge layout (build arrays with arity_sorted=True) "
                f"and {NARY_FALLBACK_TEXT} — use the generic "
                "edge_major layout for bigger factors")
        if use_pallas is None:
            # measured on-chip: the fused pallas kernel beats the jnp
            # factor update in isolation (0.81 vs 1.50 ms) but blocks
            # XLA from fusing the surrounding elementwise chain, so the
            # full step is faster all-jnp (96.7 vs 77.2 M msgs/s);
            # keep the kernel opt-in for larger domains/other chips
            use_pallas = False
        self.use_pallas = bool(use_pallas)
        # off-TPU the kernels run in pallas interpret mode so the
        # opt-in path stays testable on CPU (mirrors ShardedMaxSum)
        self._pallas_interpret = jax.default_backend() != "tpu"

    # transposed device constants, lazy like the base class's
    @property
    def var_costsT(self):
        return self._dev(
            "var_costsT",
            lambda: jnp.asarray(self.arrays.var_costs.T,
                                dtype=self.policy.store_dtype))

    @property
    def domain_maskT(self):
        return self._dev(
            "domain_maskT",
            lambda: jnp.asarray(self.arrays.domain_mask.T))

    @property
    def emaskT(self):
        return self._dev(
            "emaskT", lambda: self.domain_maskT[:, self.edge_var])

    @property
    def bucketsT(self):
        def build():
            return [
                None if spec is None
                else jnp.asarray(b.cubes_lane_major(),
                                 dtype=self.policy.store_dtype)
                for b, spec in zip(self.arrays.buckets, self._canonical)
            ]

        return self._dev("bucketsT", build)

    def init_state(self, key):
        zeros = jnp.where(self.emaskT, 0.0, BIG)
        belief = self.var_costsT
        state = {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "q": zeros,                    # (D, E)
            "r": jnp.zeros_like(zeros),
            "selection": self._select(belief),
            "same": jnp.int32(0),
        }
        return self._init_belief_carry(
            self._init_extras_state(state), belief)

    def _select(self, beliefT):
        """Masked argmin over the (sublane) domain axis — no transpose.
        The sentinel rides the beliefs' own dtype (bf16-safe ordering,
        see graphs/arrays.py SENTINEL)."""
        return jnp.argmin(
            jnp.where(self.domain_maskT, beliefT,
                      jnp.asarray(SENTINEL, beliefT.dtype)), axis=0)

    def assignment_indices(self, s):
        if self.stability > 0:
            return self._pin_indices(s, s["selection"])
        sum_r = jnp.zeros((self.D, self.V), dtype=s["r"].dtype) \
            .at[:, self.edge_var].add(s["r"])
        return self._pin_indices(
            s, self._select(self.var_costsT + sum_r))

    def _bucket_messages(self, cubesT, q_in, arity, plan=None):
        """One arity bucket's outgoing messages, lane-major — the
        shared per-bucket kernel dispatch (pallas kernels opt-in, jnp
        fallbacks by default; a branch-and-bound ``plan`` reroutes to
        the pruned bound-ordered sweep).  Returns ``(msgs,
        blocks_run-or-None)``."""
        from ..ops.pallas_kernels import factor_messages_lane_major

        out = factor_messages_lane_major(
            cubesT, q_in, arity, use_pallas=self.use_pallas,
            interpret=self._pallas_interpret, plan=plan)
        if plan is not None:
            return out
        return out, None

    def _factor_update(self, q):
        """Returns ``(new_r, pruned_runs)`` — the second entry feeds
        the pruned-cell telemetry and stays empty without bnb."""
        blocks = []
        pruned_runs = []
        for bi, (cubesT, spec) in enumerate(
                zip(self.bucketsT, self._canonical)):
            if spec is None:
                continue
            offset, f, arity = spec
            if arity == 1:
                # unary msg = the cost row, upcast to the message
                # (accum) dtype so mixed-arity concatenation never
                # demotes the f32 planes to the bf16 store dtype
                blocks.append(cubesT.astype(q.dtype))
                continue
            q_blk = q[:, offset:offset + arity * f]
            q_in = [q_blk[:, p::arity] for p in range(arity)]
            plan = self.bnb_plans[bi] if self._bnb_active else None
            msgs, blocks_run = self._bucket_messages(
                cubesT, q_in, arity, plan=plan)
            if blocks_run is not None:
                pruned_runs.append((blocks_run, plan.block * f))
            blocks.append(jnp.stack(msgs, axis=2)
                          .reshape(self.D, arity * f))
        if not blocks:
            return jnp.zeros((self.D, self.E)), pruned_runs
        if len(blocks) == 1:
            return blocks[0], pruned_runs
        return jnp.concatenate(blocks, axis=1), pruned_runs

    def step(self, s):
        q, r = s["q"], s["r"]
        new_r, pruned_runs = self._factor_update(q)
        if self.damping_nodes in ("factors", "both") and self.damping > 0:
            new_r = self.damping * r + (1 - self.damping) * new_r

        sum_r = jnp.zeros((self.D, self.V), dtype=q.dtype) \
            .at[:, self.edge_var].add(new_r)
        belief = self.var_costsT + sum_r
        q_new = belief[:, self.edge_var] - new_r
        mean = (jnp.sum(jnp.where(self.emaskT, q_new, 0.0), axis=0)
                / self.domain_size[self.edge_var])
        q_new = q_new - mean[None, :]
        key = s["key"]
        if self.noise > 0:
            key, sub = jax.random.split(key)
            q_new = q_new + self.noise * jax.random.uniform(
                sub, q_new.shape)
        if self.damping_nodes in ("vars", "both") and self.damping > 0:
            q_new = self.damping * q + (1 - self.damping) * q_new
        q_new = jnp.where(self.emaskT, q_new, BIG)

        frozen = pin = None
        if self.decimation:
            q_new, frozen, pin = self._apply_decimation(
                s, belief, self.domain_maskT, q_new, self.edge_var,
                self._decim_eligible(), lane=True,
                select_fn=self._select)

        # same dead-reduce elision as the base solver: with stability
        # disabled, neither the argmin nor the delta feeds anything
        selection = self._select(belief) if self.stability > 0 \
            else s["selection"]
        delta = self._convergence_delta(
            s, q, q_new, belief, self.emaskT, self.domain_maskT, self.E)
        return self._finish_step(
            s, key, q_new, new_r, selection, delta, belief=belief,
            frozen=frozen, pin=pin,
            pruned=self._pruned_fraction(pruned_runs)
            if self._bnb_active else None)


def degree_slot_layout(deg):
    """The fused layouts' shared variable bucketing: given per-variable
    slot demands ``deg``, bucket variables by the next power of two and
    lay out per-variable slot blocks.  Returns (var_order, var_pos,
    kbuckets, slot_base, n_slots) — ONE implementation so the
    single-chip and mesh fused solvers can never drift apart (their
    exact-equality contract depends on identical layouts)."""
    import numpy as np

    v = len(deg)
    kof = np.where(
        deg <= 1, 1,
        2 ** np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64))
    ks = sorted(set(int(k) for k in kof))
    var_order = np.concatenate(
        [np.where(kof == k)[0] for k in ks]).astype(np.int64) \
        if v else np.zeros(0, np.int64)
    var_pos = np.empty(v, dtype=np.int64)
    var_pos[var_order] = np.arange(v)
    kbuckets = []          # (slot_off, var_off, n_vars, K)
    slot_off = var_off = 0
    for k in ks:
        nv = int((kof == k).sum())
        kbuckets.append((slot_off, var_off, nv, k))
        slot_off += nv * k
        var_off += nv
    base_sorted = np.concatenate([
        off + np.arange(nv, dtype=np.int64) * k
        for off, _voff, nv, k in kbuckets]) if kbuckets else \
        np.zeros(0, dtype=np.int64)
    slot_base = np.empty(v, dtype=np.int64)
    slot_base[var_order] = base_sorted
    return var_order, var_pos, kbuckets, slot_base, slot_off


def _oriented_cube_slices(cubes, pos: int):
    """Stacked ``(f, D, D)`` binary cubes -> the ``(D, D, f)``
    oriented ``cube_slotT`` slices of one edge position: the fused
    update computes ``new_r[ds, s] = min_do cube_slotT[do, ds, s] +
    q_partner[do, s]``, so pos 0 receives over axis 1 (transpose)
    and pos 1 over axis 0 (as-is)."""
    import numpy as np

    return np.transpose(cubes, (2, 1, 0)) if pos == 0 \
        else np.transpose(cubes, (1, 2, 0))


def fused_cube_slot_table(arrays, canonical, slot_of_edge,
                          ep: int):
    """The full oriented per-slot cube table ``(D, D, E')`` of a
    binary-only fused layout, built from the CURRENT cube planes —
    one copy shared by the solver's layout build and the warm dynamic
    engine's cold re-materialization (whose planes may have been
    edited since construction)."""
    import numpy as np

    D = arrays.max_domain
    cube_slotT = np.zeros((D, D, ep), dtype=np.float32)
    for spec, b in zip(canonical, arrays.buckets):
        if spec is None:
            continue
        off, f, _arity = spec
        cubes = np.asarray(b.cubes)              # (f, D, D)
        for pos in range(2):
            es = off + 2 * np.arange(f) + pos
            cube_slotT[:, :, slot_of_edge[es]] = \
                _oriented_cube_slices(cubes, pos)
    return cube_slotT


def fused_cube_slot_writes(canonical, slot_of_edge, bucket_slots,
                           bucket_cubes):
    """One delta's binary cube edits as ``cube_slotT`` column writes:
    ``(slots, values)`` with values row-major ``(2k, D, D)`` — each
    edited factor contributes its two oriented slices.  The write-
    list twin of :func:`fused_cube_slot_table`
    (``dynamics/scatter.py`` pads and ships them)."""
    import numpy as np

    slots_out, vals_out = [], []
    for bi, spec in enumerate(canonical):
        if spec is None or not len(bucket_slots[bi]):
            continue
        off, _f, _arity = spec
        fsl = np.asarray(bucket_slots[bi], dtype=np.int64)
        cubes = np.asarray(bucket_cubes[bi], dtype=np.float32)
        for pos in range(2):
            slots_out.append(slot_of_edge[off + 2 * fsl + pos])
            vals_out.append(np.transpose(
                _oriented_cube_slices(cubes, pos), (2, 0, 1)))
    if not slots_out:
        D = 0
        return (np.zeros(0, dtype=np.int64),
                np.zeros((0, D, D), dtype=np.float32))
    return (np.concatenate(slots_out),
            np.concatenate(vals_out))


class MaxSumFusedSolver(MaxSumLaneSolver):
    """Var-sorted, degree-bucketed ``(D, E')`` layout: ONE irregular op
    per cycle.

    The lane solver's cycle carries two irregular ops — the
    ``.at[:, edge_var].add`` scatter building per-variable belief sums
    and the ``belief[:, edge_var]`` gather redistributing them — which
    the round-3 ablation measured at half the cycle (~0.58 ms of
    ~1.13 ms, benchmarks/PERF_NOTES.md).  This layout is the
    "var-sorted second edge ordering" that ablation proposed:

    * edge slots are grouped BY VARIABLE, each variable padded to a
      power-of-two slot count K and variables bucketed by K, so the
      segment-sum becomes a static ``reshape(D, nv, K).sum(2)`` and the
      belief redistribution a static broadcast — both fusable by XLA
      into the surrounding elementwise chain;
    * the factor update reads its partner messages through ONE static
      permutation gather (``q[:, partner_slot]``) and evaluates the
      per-slot oriented cube slice ``(D_other, D_self, E')`` with a
      broadcast-add + min-reduce — no per-bucket slicing, no scatter.

    Average padding overhead on random graphs is ~1.3-1.6x edge slots;
    the bet (per the PERF_NOTES per-kernel-floor measurement: op COUNT
    dominates FLOPs at these shapes) is that removing an irregular op
    and letting XLA fuse the entire post-gather chain beats the extra
    lanes.  Semantics are identical to :class:`MaxSumLaneSolver` up to
    float association (exact-selection equality is asserted in tests).

    N-ary graphs (the PEAV/SECP workload shapes) use arity-bucketed
    slot tables instead of the single slot-aligned cube: per (arity,
    position) bucket ONE static gather pulls that position's incoming
    messages out of slot space, the bucket's lane-major hypercube
    sweep produces all its outgoing messages (same per-bucket dispatch
    as the lane solver), and ONE static assembly permutation lays the
    canonical-edge-ordered results back into slots — so a mixed-arity
    cycle carries one gather per (arity, position) bucket plus the
    assembly gather, and ZERO scatters.  Binary-only graphs keep the
    single-partner-gather form above.

    Requires the canonical factor-major edge layout with factor
    arities >= 2 (fold unary constraints into variable costs via
    ``filter_dcop`` first — the fast generators already emit this
    form) and per-factor hypercubes under the unroll threshold
    (``D**arity <= NARY_FAST_MAX_CELLS``).
    """

    @staticmethod
    def eligible(arrays: FactorGraphArrays) -> bool:
        from ..ops.pallas_kernels import nary_fast_eligible

        layout = MaxSumSolver._detect_canonical(arrays)
        if layout is None or arrays.n_edges == 0:
            return False
        D = arrays.max_domain
        # binary buckets are unconditional (the slot-aligned path does
        # no hypercube unroll — any domain size); the shared cell gate
        # (ops/pallas_kernels.nary_fast_eligible) bounds only the
        # n-ary lane-major sweep
        return all(
            spec is None or (spec[2] >= 2
                             and nary_fast_eligible(D, spec[2]))
            for spec in layout)

    def __init__(self, arrays: FactorGraphArrays, **kwargs):
        if not MaxSumFusedSolver.eligible(arrays):
            from ..ops.pallas_kernels import NARY_FALLBACK_TEXT

            # raise OUR requirement, not the lane solver's (which a
            # unary-factor graph may well satisfy): the user's fix is
            # folding unary constraints into variable costs
            raise ValueError(
                "fused layout needs the canonical factor-major edge "
                "layout (arity_sorted=True arrays), factor arities "
                ">= 2 — fold unary constraints into variable costs "
                f"first (filter_dcop) — and {NARY_FALLBACK_TEXT}")
        kwargs.pop("use_pallas", None)  # no hand kernel on this path:
        # the whole point is letting XLA fuse the single-gather chain
        super().__init__(arrays, use_pallas=False, **kwargs)
        self._build_fused_layout()

    # ------------------------------------------------------ host layout

    def _build_fused_layout(self):
        import numpy as np

        arrays = self.arrays
        E, V = arrays.n_edges, self.V
        edge_var = np.asarray(arrays.edge_var)

        deg = np.bincount(edge_var, minlength=V)
        var_order, var_pos, kbuckets, slot_base, ep = \
            degree_slot_layout(deg)
        # slot table: per sorted variable, its incident edges then -1
        # padding up to its bucket's K — fully vectorized (no Python
        # loop over edges: million-edge instances build in milliseconds);
        # edges grouped by variable, each edge's rank within its group
        order = np.argsort(edge_var, kind="stable")
        run_start = np.concatenate([[0], np.cumsum(deg)[:-1]])
        rank = np.arange(E, dtype=np.int64) - np.repeat(run_start, deg)
        slot_edge = np.full(ep, -1, dtype=np.int64)
        slot_edge[slot_base[edge_var[order]] + rank] = order
        valid = slot_edge >= 0

        slot_of_edge = np.empty(E, dtype=np.int64)
        slot_of_edge[slot_edge[valid]] = np.where(valid)[0]
        slot_var_sorted = np.repeat(
            np.arange(V), np.concatenate(
                [[k] * nv for _off, _voff, nv, k in kbuckets]
                if kbuckets else [[]]).astype(np.int64))

        self._kbuckets = kbuckets
        self._np_fused = {
            "var_order": var_order,
            "var_pos": var_pos,
            "valid": valid,
            "slot_var_sorted": slot_var_sorted,
            # canonical edge id -> slot position: the renumbering the
            # warm dynamic engine maps touched-edge resets and cube
            # writes through (dynamics/scatter.py)
            "slot_of_edge": slot_of_edge,
        }
        self.EP = ep

        D = self.D
        self._all_binary = all(
            spec is None or spec[2] == 2 for spec in self._canonical)
        if not self._all_binary:
            # arity-bucketed slot tables: per (arity, position) bucket,
            # the var-sorted slots of that position's edges (ONE static
            # gather each pulls its incoming messages out of slot
            # space); results come back in canonical edge order, so the
            # assembly map is just slot -> edge id (E = the appended
            # zeros column for padding slots).  Zero scatters.
            self._np_fused["pos_slots"] = [
                None if spec is None else
                slot_of_edge[spec[0] + np.arange(spec[1] * spec[2])
                             .reshape(spec[1], spec[2])].T
                .astype(np.int32).copy()
                for spec in self._canonical
            ]
            self._np_fused["slot_src"] = np.where(
                valid, slot_edge, E).astype(np.int32)
            return

        # binary-only: the single slot-aligned table — canonical
        # partner: edges 2i / 2i+1 of a binary bucket are the two
        # endpoints of factor i
        partner = np.empty(E, dtype=np.int64)
        for spec in self._canonical:
            if spec is None:
                continue
            off, f, _arity = spec
            rel = np.arange(2 * f, dtype=np.int64)
            partner[off + rel] = off + (rel ^ 1)
        partner_slot = np.zeros(ep, dtype=np.int32)
        partner_slot[valid] = slot_of_edge[partner[slot_edge[valid]]]

        self._np_fused["partner_slot"] = partner_slot
        self._np_fused["cube_slotT"] = fused_cube_slot_table(
            arrays, self._canonical, slot_of_edge, ep)

    # ---------------------------------------------- device constants

    @property
    def partner_slot(self):
        return self._dev("partner_slot", lambda: jnp.asarray(
            self._np_fused["partner_slot"]))

    @property
    def cube_slotT(self):
        return self._dev("cube_slotT", lambda: jnp.asarray(
            self._np_fused["cube_slotT"],
            dtype=self.policy.store_dtype))

    @property
    def pos_slots(self):
        return self._dev("pos_slots", lambda: [
            None if ps is None else jnp.asarray(ps)
            for ps in self._np_fused["pos_slots"]
        ])

    @property
    def slot_src(self):
        return self._dev("slot_src", lambda: jnp.asarray(
            self._np_fused["slot_src"]))

    @property
    def var_costsT_sorted(self):
        return self._dev("var_costsT_sorted", lambda: jnp.asarray(
            self.arrays.var_costs.T[:, self._np_fused["var_order"]],
            dtype=self.policy.store_dtype))

    @property
    def domain_maskT_sorted(self):
        return self._dev("domain_maskT_sorted", lambda: jnp.asarray(
            self.arrays.domain_mask.T[:, self._np_fused["var_order"]]))

    @property
    def emaskT_fused(self):
        def build():
            import numpy as np

            nf = self._np_fused
            m = self.arrays.domain_mask.T[
                :, nf["var_order"]][:, nf["slot_var_sorted"]]
            return jnp.asarray(m & nf["valid"][None, :])

        return self._dev("emaskT_fused", build)

    @property
    def slot_dsize(self):
        def build():
            import numpy as np

            nf = self._np_fused
            ds = np.asarray(self.arrays.domain_size)[
                nf["var_order"]][nf["slot_var_sorted"]]
            return jnp.asarray(np.maximum(ds, 1).astype(np.float32))

        return self._dev("slot_dsize", build)

    @property
    def var_pos_dev(self):
        return self._dev("var_pos_dev", lambda: jnp.asarray(
            self._np_fused["var_pos"]))

    @property
    def slot_sorted_var(self):
        """Per-slot SORTED variable index — the decimation clamp's
        owner map in this layout's solve order."""
        return self._dev("slot_sorted_var", lambda: jnp.asarray(
            self._np_fused["slot_var_sorted"]))

    @property
    def dsize_sorted_vars(self):
        def build():
            import numpy as np

            return jnp.asarray(np.asarray(self.arrays.domain_size)[
                self._np_fused["var_order"]])

        return self._dev("dsize_sorted_vars", build)

    # ------------------------------------------------------------ state

    def init_state(self, key):
        zeros = jnp.where(self.emaskT_fused, 0.0, BIG)
        state = {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "q": zeros,                       # (D, E') var-sorted
            "r": jnp.zeros_like(zeros),
            "selection": self._select_sorted(self.var_costsT_sorted),
            "same": jnp.int32(0),
        }
        # the freeze plane (like the selection) lives in SORTED
        # variable order here; assignment_indices decodes both at once
        return self._init_belief_carry(
            self._init_extras_state(state), self.var_costsT_sorted)

    def _select_sorted(self, beliefT_sorted):
        return jnp.argmin(
            jnp.where(self.domain_maskT_sorted, beliefT_sorted,
                      jnp.asarray(SENTINEL, beliefT_sorted.dtype)),
            axis=0)

    def _variable_update(self, new_r):
        """Static belief/redistribution: per degree bucket, a reshape
        sum over the K slot axis and a broadcast subtract."""
        D = self.D
        belief_parts, q_parts = [], []
        for slot_off, var_off, nv, k in self._kbuckets:
            blk = new_r[:, slot_off:slot_off + nv * k] \
                .reshape(D, nv, k)
            belief_blk = self.var_costsT_sorted[
                :, var_off:var_off + nv] + blk.sum(axis=2)
            q_parts.append(
                (belief_blk[:, :, None] - blk).reshape(D, nv * k))
            belief_parts.append(belief_blk)
        belief = belief_parts[0] if len(belief_parts) == 1 else \
            jnp.concatenate(belief_parts, axis=1)
        q_new = q_parts[0] if len(q_parts) == 1 else \
            jnp.concatenate(q_parts, axis=1)
        return belief, q_new

    def _factor_update_slots(self, q):
        """N-ary factor update in slot space: one static gather per
        (arity, position) bucket (that position's incoming messages),
        the shared per-bucket lane-major kernel dispatch (or the
        branch-and-bound sweep when a plan exists), and one static
        assembly permutation from canonical edge order back to slots.
        Zero scatters.  Returns ``(new_r, pruned_runs)``."""
        blocks = []
        pruned_runs = []
        for bi, (cubesT, ps, spec) in enumerate(
                zip(self.bucketsT, self.pos_slots, self._canonical)):
            if spec is None:
                continue
            _off, f, arity = spec
            q_in = [q[:, ps[p]] for p in range(arity)]
            plan = self.bnb_plans[bi] if self._bnb_active else None
            msgs, blocks_run = self._bucket_messages(
                cubesT, q_in, arity, plan=plan)
            if blocks_run is not None:
                pruned_runs.append((blocks_run, plan.block * f))
            blocks.append(jnp.stack(msgs, axis=2)
                          .reshape(self.D, arity * f))
        msgs_all = blocks[0] if len(blocks) == 1 else \
            jnp.concatenate(blocks, axis=1)
        msgs_all = jnp.concatenate(
            [msgs_all, jnp.zeros((self.D, 1), msgs_all.dtype)], axis=1)
        return msgs_all[:, self.slot_src], pruned_runs

    def step(self, s):
        q, r = s["q"], s["r"]
        pruned_runs = []
        if self._all_binary:
            # the cycle's ONE irregular op: partner permutation
            q_part = q[:, self.partner_slot]
            new_r = jnp.min(self.cube_slotT + q_part[:, None, :], axis=0)
        else:
            new_r, pruned_runs = self._factor_update_slots(q)
        new_r = jnp.where(self.emaskT_fused, new_r, 0.0)
        if self.damping_nodes in ("factors", "both") and self.damping > 0:
            new_r = self.damping * r + (1 - self.damping) * new_r

        belief, q_new = self._variable_update(new_r)
        mean = (jnp.sum(jnp.where(self.emaskT_fused, q_new, 0.0),
                        axis=0) / self.slot_dsize)
        q_new = q_new - mean[None, :]
        key = s["key"]
        if self.noise > 0:
            key, sub = jax.random.split(key)
            q_new = q_new + self.noise * jax.random.uniform(
                sub, q_new.shape)
        if self.damping_nodes in ("vars", "both") and self.damping > 0:
            q_new = self.damping * q + (1 - self.damping) * q_new
        q_new = jnp.where(self.emaskT_fused, q_new, BIG)

        frozen = pin = None
        if self.decimation:
            # everything (beliefs, owner map, eligibility) in SORTED
            # variable order — the pin rides the sorted selection and
            # decodes through var_pos with it
            q_new, frozen, pin = self._apply_decimation(
                s, belief, self.domain_maskT_sorted, q_new,
                self.slot_sorted_var, self.dsize_sorted_vars > 1,
                lane=True, select_fn=self._select_sorted)

        selection = self._select_sorted(belief) if self.stability > 0 \
            else s["selection"]
        delta = self._convergence_delta(
            s, q, q_new, belief, self.emaskT_fused,
            self.domain_maskT_sorted, self.EP)
        return self._finish_step(
            s, key, q_new, new_r, selection, delta, belief=belief,
            frozen=frozen, pin=pin,
            pruned=self._pruned_fraction(pruned_runs)
            if self._bnb_active else None)

    def assignment_indices(self, s):
        if self.stability > 0:
            sel_sorted = s["selection"]
        else:
            belief, _ = self._variable_update(
                jnp.where(self.emaskT_fused, s["r"], 0.0))
            sel_sorted = self._select_sorted(belief)
        # state order is degree-sorted; decode to original variables
        # (the freeze pin lives in the same sorted order)
        return self._pin_indices(s, sel_sorted)[self.var_pos_dev]


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> MaxSumSolver:
    params = dict(params) if params else {}
    layout = params.pop("layout", "auto")
    # the fast layouts need the canonical factor-major edge layout;
    # arity-sorting the constraints produces it for ANY model (mixed
    # arities included), so n-ary PEAV/SECP instances reach the fast
    # paths instead of silently degrading to gather/scatter.  Explicit
    # edge_major keeps the model's own order — the untouched generic
    # oracle.
    arrays = FactorGraphArrays.build(
        dcop, variables, constraints,
        arity_sorted=layout != "edge_major",
        precision=params.get("precision"))
    if layout == "fused":
        return MaxSumFusedSolver(arrays, **params)
    if layout == "lane_major" or (
            layout == "auto" and MaxSumLaneSolver.eligible(arrays)):
        return MaxSumLaneSolver(arrays, **params)
    return MaxSumSolver(arrays, **params)


def computation_memory(node) -> float:
    """Footprint in cost units (reference: maxsum.py computation_memory —
    proportional to domain sizes of the node's neighborhood)."""
    from ..graphs.factor_graph import FactorComputationNode

    if isinstance(node, FactorComputationNode):
        return UNIT_SIZE * sum(len(v.domain) for v in node.variables)
    # variable node: one message per neighbor factor
    return UNIT_SIZE * len(node.variable.domain) * max(
        1, len(node.neighbors))


def communication_load(node, target: str) -> float:
    """Per-message size towards ``target``
    (reference: maxsum.py communication_load)."""
    from ..graphs.factor_graph import FactorComputationNode

    if isinstance(node, FactorComputationNode):
        for v in node.variables:
            if v.name == target:
                return HEADER_SIZE + UNIT_SIZE * len(v.domain)
        raise ValueError(f"{target} is not a neighbor of {node.name}")
    return HEADER_SIZE + UNIT_SIZE * len(node.variable.domain)


# ---------------------------------------------------------------------
# Message-passing backend: MaxSum running ON the agent fabric, one
# computation per factor-graph node, exchanging real cost messages in
# thread / process / multi-machine mode (reference: maxsum.py:279-676
# MaxSumFactorComputation / MaxSumVariableComputation).  The compiled
# solvers above are the data plane; this is the distributed path used by
# orchestrated runs.
# ---------------------------------------------------------------------

import numpy as _np

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    DcopComputation, SynchronousComputationMixin, VariableComputation,
    message_type, register)
from ._mp import sign_for_mode

#: costs: list of floats aligned to the *target* variable's domain order
#: (a list, not a value-keyed dict: JSON would silently stringify
#: non-string domain values used as dict keys across processes)
MaxSumCostsMessage = message_type("maxsum_costs", ["costs"])


class MaxSumVariableMpComputation(SynchronousComputationMixin,
                                  VariableComputation):
    """One variable node of the factor graph on the agent fabric
    (reference: maxsum.py:450-676)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.damping = float(params.get("damping", 0.5))
        self.damping_nodes = params.get("damping_nodes", "vars")
        self.stability = float(params.get("stability", 0.1))
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.factor_names = list(comp_def.node.neighbors)
        sign = sign_for_mode(self.mode)
        self._own_costs = _np.array(
            [sign * self.variable.cost_for_val(v)
             for v in self.variable.domain.values])
        # factor -> costs last received / last q sent (signed space)
        self._r: Dict[str, _np.ndarray] = {}
        self._q_sent: Dict[str, _np.ndarray] = {}
        self._same = 0

    def on_start(self):
        self.start_cycle()
        self._select_and_send()
        if not self.factor_names:
            # unconstrained variable: nothing to exchange, done
            self.finished()

    def on_fast_forward(self, cycle_id):
        # rejoin after repair re-deploy: re-announce for the new round
        self._send_costs()

    @register("maxsum_costs")
    def _on_costs(self, sender, msg, t):  # pragma: no cover
        pass  # sync mixin delivers whole rounds via on_new_cycle

    def on_new_cycle(self, messages, cycle_id):
        prev_selection = self.current_value
        for sender, (msg, _) in messages.items():
            self._r[sender] = _np.asarray(msg.costs, dtype=float)
        self.new_cycle()
        delta = self._select_and_send()
        # convergence: stable selection + message change below the
        # stability threshold for SAME_COUNT cycles (maxsum.py:106,688)
        if self.current_value == prev_selection and \
                delta < self.stability:
            self._same += 1
        else:
            self._same = 0
        if self._same >= SAME_COUNT or (
                self.stop_cycle
                and self._cycle_count >= self.stop_cycle):
            self.finished()

    # ------------------------------------------------------------ internals

    def _belief(self) -> _np.ndarray:
        belief = self._own_costs.copy()
        for r in self._r.values():
            belief = belief + r
        return belief

    def _select_and_send(self) -> float:
        belief = self._belief()
        idx = int(_np.argmin(belief))
        sign = sign_for_mode(self.mode)
        self.value_selection(self.variable.domain.values[idx],
                             sign * float(belief[idx]))
        return self._send_costs(belief)

    def _send_costs(self, belief: Optional[_np.ndarray] = None) -> float:
        """Send q = belief - echo to every factor, normalized by the
        average (maxsum.py:623-676), damped (maxsum.py:679)."""
        if belief is None:
            belief = self._belief()
        delta = 0.0
        for f in self.factor_names:
            q = belief - self._r.get(f, 0.0)
            q = q - q.mean()
            prev = self._q_sent.get(f)
            if prev is not None and \
                    self.damping_nodes in ("vars", "both") and \
                    0 < self.damping < 1:
                q = self.damping * prev + (1 - self.damping) * q
            if prev is not None:
                delta = max(delta, float(_np.abs(q - prev).max()))
            self._q_sent[f] = q
            self.post_msg(f, MaxSumCostsMessage(q.tolist()), MSG_ALGO)
        return delta


class MaxSumFactorMpComputation(SynchronousComputationMixin,
                                DcopComputation):
    """One factor node of the factor graph on the agent fabric
    (reference: maxsum.py:279-449).  The reference brute-forces the
    joint assignment space in Python loops; here the factor's cost
    hypercube is materialized once and each neighbor's message is a
    numpy broadcast-add + axis-min."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.name, comp_def)
        self.mode = comp_def.algo.mode
        params = comp_def.algo.params
        self.damping = float(params.get("damping", 0.5))
        self.damping_nodes = params.get("damping_nodes", "vars")
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        factor = comp_def.node.factor
        self.variables = list(factor.dimensions)
        sign = sign_for_mode(self.mode)
        self._cube = sign * factor.to_matrix().matrix.astype(float)
        self._axis = {v.name: i for i, v in enumerate(self.variables)}
        self._q: Dict[str, _np.ndarray] = {}
        self._r_sent: Dict[str, _np.ndarray] = {}

    def on_start(self):
        self.start_cycle()

    def on_fast_forward(self, cycle_id):
        self._send_marginals()

    @register("maxsum_costs")
    def _on_costs(self, sender, msg, t):  # pragma: no cover
        pass

    def on_new_cycle(self, messages, cycle_id):
        for sender, (msg, _) in messages.items():
            self._q[sender] = _np.asarray(msg.costs, dtype=float)
        self.new_cycle()
        self._send_marginals()
        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()

    def _send_marginals(self):
        """r_{f->v}[d] = min over assignments of the other variables of
        (factor cost + sum of their q messages) — maxsum.py:382-447 as a
        broadcast-add + min-reduction."""
        n = self._cube.ndim
        total = self._cube
        for name, q in self._q.items():
            axis = self._axis.get(name)
            if axis is None:
                continue
            shape = [1] * n
            shape[axis] = q.shape[0]
            total = total + q.reshape(shape)
        for v in self.variables:
            axis = self._axis[v.name]
            other_axes = tuple(i for i in range(n) if i != axis)
            marg = total.min(axis=other_axes) if other_axes \
                else total.copy()
            q_v = self._q.get(v.name)
            if q_v is not None:
                marg = marg - q_v  # remove the target's own echo
            prev = self._r_sent.get(v.name)
            if prev is not None and \
                    self.damping_nodes in ("factors", "both") and \
                    0 < self.damping < 1:
                marg = self.damping * prev + (1 - self.damping) * marg
            self._r_sent[v.name] = marg
            self.post_msg(v.name, MaxSumCostsMessage(marg.tolist()),
                          MSG_ALGO)


def build_computation(comp_def):
    """Agent-fabric computation for one factor-graph node
    (reference: maxsum.py:118-123 dispatches the same way)."""
    if hasattr(comp_def.node, "variable"):
        return MaxSumVariableMpComputation(comp_def)
    return MaxSumFactorMpComputation(comp_def)

"""Synchronous DSA (Distributed Stochastic Algorithm), variants A/B/C.

reference parity: pydcop/algorithms/dsa.py (431 LoC).  Exact semantics of
the variants (dsa.py:359-405):

* A — change (with probability p) only on strictly positive gain,
* B — also on zero gain if some incident constraint is not at its own
  optimum ("violated", dsa.py:450-466), preferring a different value,
* C — also on zero gain unconditionally, preferring a different value.

``p_mode = arity`` re-derives the probability per variable as
``1.2 / sum(arity - 1)`` over its constraints (dsa.py:256-263).

One cycle for *all* variables = one jitted step; the manual current/next
cycle barrier of the reference (dsa.py:265-357) is unnecessary.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("probability", "float", None, 0.7),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("p_mode", "str", ["fixed", "arity"], "fixed"),
]


class DsaSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, probability: float = 0.7,
                 variant: str = "B", stop_cycle: int = 0,
                 p_mode: str = "fixed"):
        super().__init__(arrays, stop_cycle)
        self.variant = variant
        if p_mode == "arity":
            # per-variable threshold 1.2 / sum(arity-1) (dsa.py:256-263)
            n_count = np.zeros(arrays.n_vars, dtype=np.float64)
            for b in arrays.buckets:
                for p in range(b.arity):
                    np.add.at(n_count, b.var_ids[:, p], b.arity - 1)
            with np.errstate(divide="ignore"):
                prob = np.where(n_count > 0, 1.2 / n_count, 1.0)
            self.probability = jnp.asarray(
                np.clip(prob, 0.0, 1.0), dtype=jnp.float32)
        else:
            self.probability = jnp.float32(probability)

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_prob = jax.random.split(s["key"], 3)
        x = s["x"]
        _, cur, best_cost, best_val = self.best_response(k_best, x)
        delta = cur - best_cost

        improve = delta > 1e-9
        equal = jnp.abs(delta) <= 1e-9
        if self.variant == "A":
            want = improve
        elif self.variant == "B":
            want = improve | (equal & self.var_has_violated_constraint(x))
        else:  # C
            want = improve | equal

        lucky = jax.random.uniform(k_prob, (self.V,)) < self.probability
        change = want & lucky
        x_new = jnp.where(change, best_val, x)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DsaSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return DsaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

"""Synchronous DSA (Distributed Stochastic Algorithm), variants A/B/C.

reference parity: pydcop/algorithms/dsa.py (431 LoC).  Exact semantics of
the variants (dsa.py:359-405):

* A — change (with probability p) only on strictly positive gain,
* B — also on zero gain if some incident constraint is not at its own
  optimum ("violated", dsa.py:450-466), preferring a different value,
* C — also on zero gain unconditionally, preferring a different value.

``p_mode = arity`` re-derives the probability per variable as
``1.2 / sum(arity - 1)`` over its constraints (dsa.py:256-263).

One cycle for *all* variables = one jitted step; the manual current/next
cycle barrier of the reference (dsa.py:265-357) is unnecessary.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("probability", "float", None, 0.7),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("p_mode", "str", ["fixed", "arity"], "fixed"),
    # mixed-precision policy (ops/precision.py): bf16 cost planes with
    # f32 accumulation; None defers to PYDCOP_TPU_PRECISION, then f32
    AlgoParameterDef("precision", "str", ["f32", "bf16", "auto"], None),
]


def arity_probability(arrays: HypergraphArrays) -> np.ndarray:
    """``p_mode=arity``'s per-variable activation threshold
    ``1.2 / sum(arity - 1)`` over the variable's constraints
    (dsa.py:256-263).  Module-level so the batched hetero-campaign
    runner can re-derive each padded instance's own vector."""
    n_count = np.zeros(arrays.n_vars, dtype=np.float64)
    for b in arrays.buckets:
        for p in range(b.arity):
            np.add.at(n_count, b.var_ids[:, p], b.arity - 1)
    with np.errstate(divide="ignore"):
        prob = np.where(n_count > 0, 1.2 / n_count, 1.0)
    return np.clip(prob, 0.0, 1.0).astype(np.float32)


class DsaSolver(LocalSearchSolver):
    # pad-stable per-variable draws: a shape-padded fused campaign row
    # must reproduce its unpadded subprocess solve bit-exactly
    pad_stable_rng = True

    def __init__(self, arrays: HypergraphArrays, probability: float = 0.7,
                 variant: str = "B", stop_cycle: int = 0,
                 p_mode: str = "fixed", precision=None):
        super().__init__(arrays, stop_cycle, precision=precision)
        self.variant = variant
        self.p_mode = p_mode
        if p_mode == "arity":
            self.probability = jnp.asarray(arity_probability(arrays))
        else:
            self.probability = jnp.float32(probability)

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
        }

    def step(self, s):
        key, k_best, k_prob = jax.random.split(s["key"], 3)
        x = s["x"]
        _, cur, best_cost, best_val = self.best_response(k_best, x)
        delta = cur - best_cost

        improve = delta > 1e-9
        equal = jnp.abs(delta) <= 1e-9
        if self.variant == "A":
            want = improve
        elif self.variant == "B":
            want = improve | (equal & self.var_has_violated_constraint(x))
        else:  # C
            want = improve | equal

        lucky = self.uniform_v(k_prob) < self.probability
        change = want & lucky
        x_new = jnp.where(change, best_val, x)
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._finish(cycle),
            "key": key,
            "x": x_new,
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DsaSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints,
                                    precision=params.get("precision"))
    return DsaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend: DSA running ON the agent fabric
# (reference: dsa.py:265-405).  One computation per variable, value
# messages between hypergraph neighbors, variant A/B/C semantics as in
# the compiled solver above.  Used by orchestrated (thread / process /
# multi-machine) runs; the compiled solver is the data plane.
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    SynchronousComputationMixin, VariableComputation, message_type,
    register)
from ._mp import EPS, best_response, constraint_optima, \
    has_violated_constraint, mp_rng, seed_param, sign_for_mode

algo_params = algo_params + [seed_param()]

DsaValueMessage = message_type("dsa_value", ["value"])


class DsaMpComputation(SynchronousComputationMixin, VariableComputation):
    """Synchronous DSA on the agent fabric (reference: dsa.py:265-405).
    The reference's manual current/next-cycle barrier (dsa.py:265-357)
    is the sync mixin here."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.variant = params.get("variant", "B")
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.constraints = list(comp_def.node.constraints)
        if params.get("p_mode", "fixed") == "arity":
            # per-variable threshold 1.2 / sum(arity - 1)
            # (reference: dsa.py:256-263)
            n = sum(len(c.dimensions) - 1 for c in self.constraints)
            self.probability = min(1.0, 1.2 / n) if n > 0 else 1.0
        else:
            self.probability = float(params.get("probability", 0.7))
        self._optima = constraint_optima(self.constraints, self.mode) \
            if self.variant == "B" else {}
        self._neighbor_values: Dict[str, object] = {}
        self._rnd = mp_rng(params, self.name)

    def on_start(self):
        self.start_cycle()
        self.value_selection(
            self._rnd.choice(list(self.variable.domain.values)))
        self.post_to_all_neighbors(
            DsaValueMessage(self.current_value), MSG_ALGO)
        if not self.neighbors:
            self.finished()

    def on_fast_forward(self, cycle_id):
        self.post_to_all_neighbors(
            DsaValueMessage(self.current_value), MSG_ALGO)

    @register("dsa_value")
    def _on_value(self, sender, msg, t):  # pragma: no cover
        pass  # rounds are delivered through on_new_cycle

    def on_new_cycle(self, messages, cycle_id):
        for sender, (msg, _) in messages.items():
            self._neighbor_values[sender] = msg.value
        self.new_cycle()
        cur, best_val, best_cost = best_response(
            self.variable, self.constraints, self._neighbor_values,
            self.current_value, self.mode,
            prefer_different=self.variant in ("B", "C"), rnd=self._rnd)
        sign = sign_for_mode(self.mode)
        delta = sign * (cur - best_cost) if cur is not None else 0.0
        improve = delta > EPS
        if self.variant == "A":
            want = improve
        elif self.variant == "B":
            assignment = dict(self._neighbor_values)
            assignment[self.variable.name] = self.current_value
            want = improve or (
                abs(delta) <= EPS and best_val != self.current_value
                and has_violated_constraint(
                    self.constraints, self._optima, assignment,
                    self.mode))
        else:  # C
            want = improve or (abs(delta) <= EPS
                               and best_val != self.current_value)
        if want and self._rnd.random() < self.probability:
            self.value_selection(best_val, best_cost)
        # count rounds actually processed (self._cycle_count), not the
        # mixin's round id, which can jump on fast-forward rejoin
        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()
            return
        self.post_to_all_neighbors(
            DsaValueMessage(self.current_value), MSG_ALGO)


def build_computation(comp_def) -> DsaMpComputation:
    return DsaMpComputation(comp_def)

"""A-DSA: asynchronous DSA driven by periodic activation.

reference parity: pydcop/algorithms/adsa.py (392 LoC).  In the reference,
each variable re-evaluates on a wall-clock timer with a random phase
(adsa.py:157-221) instead of in synchronous cycles.  In a compiled engine
the faithful model is *stochastic activation* (SURVEY.md §7 hard part 3):
each engine cycle, every variable independently activates with probability
``activation`` and applies the DSA variant rule against the latest known
neighbor values.  With activation < 1 this reproduces A-DSA's key property
— neighbors rarely move simultaneously, avoiding the oscillation
synchronous DSA can show.  The wall-clock ``period`` parameter is kept for
API parity: activation rates scale *relative* to the default period, i.e.
``activation = clip(0.5 * (0.5 / period), 0, 1)`` unless ``activation`` is
set explicitly (halving the reference period doubles the per-cycle
activation probability, preserving relative re-evaluation rates).
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import hypergraph_footprints
from .dsa import DsaSolver

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("probability", "float", None, 0.7),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("period", "float", None, 0.5),
    # -1 means "derive from period"
    AlgoParameterDef("activation", "float", None, -1.0),
    AlgoParameterDef("stop_cycle", "int", None, 0),
]


class ADsaSolver(DsaSolver):
    def __init__(self, arrays: HypergraphArrays, probability: float = 0.7,
                 variant: str = "B", period: float = 0.5,
                 activation: float = -1.0, stop_cycle: int = 0):
        super().__init__(arrays, probability=probability, variant=variant,
                         stop_cycle=stop_cycle)
        if activation < 0:
            activation = min(1.0, max(0.0, 0.5 * (0.5 / float(period))))
        self.activation = float(activation)

    def step(self, s):
        key, k_act = jax.random.split(s["key"])
        active = jax.random.uniform(k_act, (self.V,)) < self.activation
        s2 = dict(s)
        s2["key"] = key
        out = super().step(s2)
        # inactive variables keep their value this cycle
        out["x"] = jnp.where(active, out["x"], s["x"])
        return out


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> ADsaSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return ADsaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

"""A-DSA: asynchronous DSA driven by periodic activation.

reference parity: pydcop/algorithms/adsa.py (392 LoC).  In the reference,
each variable re-evaluates on a wall-clock timer with a random phase
(adsa.py:157-221) instead of in synchronous cycles.  In a compiled engine
the faithful model is *stochastic activation* (SURVEY.md §7 hard part 3):
each engine cycle, every variable independently activates with probability
``activation`` and applies the DSA variant rule against the latest known
neighbor values.  With activation < 1 this reproduces A-DSA's key property
— neighbors rarely move simultaneously, avoiding the oscillation
synchronous DSA can show.  The wall-clock ``period`` parameter is kept for
API parity: activation rates scale *relative* to the default period, i.e.
``activation = clip(0.5 * (0.5 / period), 0, 1)`` unless ``activation`` is
set explicitly (halving the reference period doubles the per-cycle
activation probability, preserving relative re-evaluation rates).
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import hypergraph_footprints
from .dsa import DsaSolver

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("probability", "float", None, 0.7),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("period", "float", None, 0.5),
    # -1 means "derive from period"
    AlgoParameterDef("activation", "float", None, -1.0),
    AlgoParameterDef("stop_cycle", "int", None, 0),
]


class ADsaSolver(DsaSolver):
    def __init__(self, arrays: HypergraphArrays, probability: float = 0.7,
                 variant: str = "B", period: float = 0.5,
                 activation: float = -1.0, stop_cycle: int = 0):
        super().__init__(arrays, probability=probability, variant=variant,
                         stop_cycle=stop_cycle)
        if activation < 0:
            activation = min(1.0, max(0.0, 0.5 * (0.5 / float(period))))
        self.activation = float(activation)

    def step(self, s):
        key, k_act = jax.random.split(s["key"])
        active = jax.random.uniform(k_act, (self.V,)) < self.activation
        s2 = dict(s)
        s2["key"] = key
        out = super().step(s2)
        # inactive variables keep their value this cycle
        out["x"] = jnp.where(active, out["x"], s["x"])
        return out


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> ADsaSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return ADsaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend: A-DSA running ON the agent fabric
# (reference: adsa.py:131-392).  Fully asynchronous: value messages
# update the local view whenever they arrive, and the DSA decision runs
# on the hosting agent's timer wheel every ``period`` seconds (with a
# random start delay) — the one algorithm exercising the fabric's
# periodic-action path.
# ---------------------------------------------------------------------

from typing import Dict as _DictT

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    VariableComputation, message_type, register)
from ._mp import EPS, best_response, constraint_optima, \
    has_violated_constraint, mp_rng, seed_param, sign_for_mode

algo_params = algo_params + [seed_param()]

ADsaValueMessage = message_type("adsa_value", ["value"])


class ADsaMpComputation(VariableComputation):
    """A-DSA on the agent fabric (reference: adsa.py:131-392).

    ``stop_cycle`` bounds the number of periodic activations (the
    reference's A-DSA never terminates on its own and relies on the
    orchestrator timeout; a bound makes orchestrated runs finish)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.variant = params.get("variant", "B")
        self.probability = float(params.get("probability", 0.7))
        self.period = float(params.get("period", 0.5))
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.constraints = list(comp_def.node.constraints)
        self._rnd = mp_rng(params, self.name)
        self._optima = constraint_optima(self.constraints, self.mode) \
            if self.variant == "B" else {}
        self._neighbor_values: _DictT[str, object] = {}
        self._start_handle = None
        self._tick_handle = None

    def on_start(self):
        # random start delay desynchronizes the fleet
        # (reference: adsa.py:158-161)
        delay = self._rnd.random() * self.period or self.period
        self._start_handle = self.add_periodic_action(
            delay, self._delayed_start)

    def on_stop(self):
        if self._start_handle is not None:
            self.remove_periodic_action(self._start_handle)
            self._start_handle = None
        if self._tick_handle is not None:
            self.remove_periodic_action(self._tick_handle)
            self._tick_handle = None

    def _delayed_start(self):
        if self._start_handle is not None:
            self.remove_periodic_action(self._start_handle)
            self._start_handle = None
        if not self.neighbors:
            _, best, cost = best_response(
                self.variable, self.constraints, {}, None, self.mode,
                rnd=self._rnd)
            self.value_selection(best, cost)
            self.finished()
            return
        self.value_selection(
            self._rnd.choice(list(self.variable.domain.values)))
        self.post_to_all_neighbors(
            ADsaValueMessage(self.current_value), MSG_ALGO)
        self._tick_handle = self.add_periodic_action(
            self.period, self._tick)

    @register("adsa_value")
    def _on_value(self, sender, msg, t):
        self._neighbor_values[sender] = msg.value

    def _tick(self):
        """One asynchronous DSA activation (reference: adsa.py:222-260).
        """
        if self.is_paused or not self.is_running:
            return
        if len(self._neighbor_values) < len(self.neighbors):
            return  # still waiting for the first full view
        self.new_cycle()
        cur, best_val, best_cost = best_response(
            self.variable, self.constraints, self._neighbor_values,
            self.current_value, self.mode,
            prefer_different=self.variant in ("B", "C"), rnd=self._rnd)
        sign = sign_for_mode(self.mode)
        delta = sign * (cur - best_cost) if cur is not None else 0.0
        improve = delta > EPS
        if self.variant == "A":
            want = improve
        elif self.variant == "B":
            assignment = dict(self._neighbor_values)
            assignment[self.variable.name] = self.current_value
            want = improve or (
                abs(delta) <= EPS and best_val != self.current_value
                and has_violated_constraint(
                    self.constraints, self._optima, assignment,
                    self.mode))
        else:  # C
            want = improve or (abs(delta) <= EPS
                               and best_val != self.current_value)
        if want and self._rnd.random() < self.probability:
            self.value_selection(best_val, best_cost)
            self.post_to_all_neighbors(
                ADsaValueMessage(self.current_value), MSG_ALGO)
        if self.stop_cycle and self._cycle_count >= self.stop_cycle:
            self.finished()


def build_computation(comp_def) -> ADsaMpComputation:
    return ADsaMpComputation(comp_def)

"""DBA: Distributed Breakout Algorithm (for constraint *satisfaction*).

reference parity: pydcop/algorithms/dba.py (597 LoC).  ok?/improve message
waves become one jitted step: per-variable improvement on the
*weighted-violation* objective, neighborhood-max winner moves, and every
variable stuck in a quasi-local minimum raises the weight of its violated
constraints (the "breakout", dba.py:272+).

Deviations (documented):
* constraint weights are global, not per-agent copies — the reference lets
  each agent hold its own (eventually equal) copy of the weight of a
  shared constraint; a shared array is the natural compiled form,
* termination: the reference detects a solution with a distance-bounded
  propagation wave (``max_distance``); here the global violation count is
  directly readable on device each cycle, which is the same predicate
  computed exactly.  ``infinity`` marks the hard-cost value (the array
  compiler already clips ``inf`` to HARD).
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import BIG, HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("infinity", "int", None, 10000),
    AlgoParameterDef("max_distance", "int", None, 50),
]


class DbaSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, infinity: int = 10000,
                 max_distance: int = 50):
        super().__init__(arrays, stop_cycle=0)
        self.infinity = infinity
        self.max_distance = max_distance
        # violation indicator cubes: nonzero base cost = violated (CSP
        # semantics; padding excluded)
        self.viol_cubes = [
            (jnp.asarray(((b.cubes > 1e-9) & (b.cubes < BIG * 0.5))
                         .astype(np.float32)),
             jnp.asarray(b.var_ids))
            for b in arrays.buckets
        ]
        self.n_cons = [b.var_ids.shape[0] for b in arrays.buckets]
        self.lexic_priority = -jnp.arange(self.V, dtype=jnp.float32)

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
            "weights": tuple(
                jnp.ones((n,), dtype=jnp.float32) for n in self.n_cons
            ),
        }

    def weighted_eval(self, x, weights):
        """(V, D) weighted violation count per candidate value."""
        from ..ops.kernels import candidate_costs

        total = jnp.zeros((self.V, self.D))
        for (ind, var_ids), w in zip(self.viol_cubes, weights):
            # weight the indicator cube per constraint
            shape = (ind.shape[0],) + (1,) * (ind.ndim - 1)
            total = total + candidate_costs(
                ind * w.reshape(shape), var_ids, x, self.V)
        return total

    def step(self, s):
        key, k_best = jax.random.split(s["key"])
        x, weights = s["x"], s["weights"]
        ar = jnp.arange(self.V)

        from ..ops.kernels import masked_min, random_argmin

        ev = self.weighted_eval(x, weights)
        cur = jnp.where(self.domain_mask, ev, BIG)[ar, x]
        best = masked_min(ev, self.domain_mask)
        best_val = random_argmin(k_best, ev, self.domain_mask)
        improve = cur - best

        nbr_max = self.neighbor_max_gain(improve)
        wins = self.wins_tie(improve, nbr_max, self.lexic_priority)
        move = (improve > 1e-9) & wins
        x_new = jnp.where(move, best_val, x)

        # quasi-local minimum: violated but nobody in the neighborhood
        # (incl. itself) can improve -> breakout
        qlm = (improve <= 1e-9) & (cur > 1e-9) & (nbr_max <= 1e-9)
        new_weights = []
        total_violations = jnp.float32(0)
        for (ind, var_ids), w in zip(self.viol_cubes, weights):
            from ..ops.kernels import bucket_cost

            violated = bucket_cost(ind, var_ids, x) > 0.5  # (C,)
            any_qlm = jnp.zeros(var_ids.shape[0], dtype=bool)
            for p in range(var_ids.shape[1]):
                any_qlm = any_qlm | qlm[var_ids[:, p]]
            new_weights.append(
                w + jnp.where(violated & any_qlm, 1.0, 0.0))
            # count violations under the *new* assignment for termination
            total_violations = total_violations + jnp.sum(
                bucket_cost(ind, var_ids, x_new))
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": total_violations < 0.5,
            "key": key,
            "x": x_new,
            "weights": tuple(new_weights),
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DbaSolver:
    params = params or {}
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return DbaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()

"""DBA: Distributed Breakout Algorithm (for constraint *satisfaction*).

reference parity: pydcop/algorithms/dba.py (597 LoC).  ok?/improve message
waves become one jitted step: per-variable improvement on the
*weighted-violation* objective, neighborhood-max winner moves, and every
variable stuck in a quasi-local minimum raises the weight of its violated
constraints (the "breakout", dba.py:272+).

Deviations (documented):
* constraint weights are global, not per-agent copies — the reference lets
  each agent hold its own (eventually equal) copy of the weight of a
  shared constraint; a shared array is the natural compiled form,
* termination: the reference detects a solution with a distance-bounded
  propagation wave (``max_distance``); here the global violation count is
  directly readable on device each cycle, which is the same predicate
  computed exactly.  ``infinity`` marks the hard-cost value (the array
  compiler already clips ``inf`` to HARD).
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP, filter_dcop
from ..graphs.arrays import BIG, HypergraphArrays
from . import AlgoParameterDef
from ._localsearch import LocalSearchSolver, hypergraph_footprints

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("infinity", "int", None, 10000),
    AlgoParameterDef("max_distance", "int", None, 50),
]


class DbaSolver(LocalSearchSolver):
    def __init__(self, arrays: HypergraphArrays, infinity: int = 10000,
                 max_distance: int = 50):
        super().__init__(arrays, stop_cycle=0)
        self.infinity = infinity
        self.max_distance = max_distance
        # violation indicator cubes: nonzero base cost = violated (CSP
        # semantics; padding excluded)
        self.viol_cubes = [
            (jnp.asarray(((b.cubes > 1e-9) & (b.cubes < BIG * 0.5))
                         .astype(np.float32)),
             jnp.asarray(b.var_ids))
            for b in arrays.buckets
        ]
        self.n_cons = [b.var_ids.shape[0] for b in arrays.buckets]
        self.lexic_priority = -jnp.arange(self.V, dtype=jnp.float32)

    def init_state(self, key):
        key, sub = jax.random.split(key)
        return {
            "cycle": jnp.int32(0),
            "finished": jnp.bool_(False),
            "key": key,
            "x": self.random_values(sub),
            "weights": tuple(
                jnp.ones((n,), dtype=jnp.float32) for n in self.n_cons
            ),
        }

    def weighted_eval(self, x, weights):
        """(V, D) weighted violation count per candidate value."""
        from ..ops.kernels import candidate_costs

        total = jnp.zeros((self.V, self.D))
        for (ind, var_ids), w in zip(self.viol_cubes, weights):
            # weight the indicator cube per constraint
            shape = (ind.shape[0],) + (1,) * (ind.ndim - 1)
            total = total + candidate_costs(
                ind * w.reshape(shape), var_ids, x, self.V)
        return self._reduce_vplane(total)

    def step(self, s):
        key, k_best = jax.random.split(s["key"])
        x, weights = s["x"], s["weights"]
        ar = jnp.arange(self.V)

        from ..ops.kernels import masked_min, random_argmin

        ev = self.weighted_eval(x, weights)
        cur = jnp.where(self.domain_mask, ev, BIG)[ar, x]
        best = masked_min(ev, self.domain_mask)
        best_val = random_argmin(k_best, ev, self.domain_mask)
        improve = cur - best

        nbr_max = self.neighbor_max_gain(improve)
        wins = self.wins_tie(improve, nbr_max, self.lexic_priority)
        move = (improve > 1e-9) & wins
        x_new = jnp.where(move, best_val, x)

        # quasi-local minimum: violated but nobody in the neighborhood
        # (incl. itself) can improve -> breakout
        qlm = (improve <= 1e-9) & (cur > 1e-9) & (nbr_max <= 1e-9)
        new_weights = []
        total_violations = jnp.float32(0)
        for (ind, var_ids), w in zip(self.viol_cubes, weights):
            from ..ops.kernels import bucket_cost

            violated = bucket_cost(ind, var_ids, x) > 0.5  # (C,)
            any_qlm = jnp.zeros(var_ids.shape[0], dtype=bool)
            for p in range(var_ids.shape[1]):
                any_qlm = any_qlm | qlm[var_ids[:, p]]
            new_weights.append(
                w + jnp.where(violated & any_qlm, 1.0, 0.0))
            # count violations under the *new* assignment for termination
            total_violations = total_violations + jnp.sum(
                bucket_cost(ind, var_ids, x_new))
        cycle = s["cycle"] + 1
        return {
            "cycle": cycle,
            "finished": self._reduce_scalar(total_violations) < 0.5,
            "key": key,
            "x": x_new,
            "weights": tuple(new_weights),
        }


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DbaSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = HypergraphArrays.build(filter_dcop(dcop), variables,
                                    constraints)
    return DbaSolver(arrays, **params)


computation_memory, communication_load = hypergraph_footprints()


# ---------------------------------------------------------------------
# Message-passing backend: DBA running ON the agent fabric
# (reference: dba.py:272-597).  The reference's wait_ok / wait_improve
# modes with postponed-message queues become two sync-mixin sub-cycles
# (even = ok?, odd = improve); the asynchronous termination broadcast
# (dba_end, reference dba.py:568-581) bypasses the round barrier.
# ---------------------------------------------------------------------

from typing import Dict

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    SynchronousComputationMixin, VariableComputation, message_type,
    register)
from ._mp import mp_rng, seed_param

algo_params = algo_params + [seed_param()]

DbaOkMessage = message_type("dba_ok", ["value"])
DbaImproveMessage = message_type(
    "dba_improve", ["improve", "current_eval", "termination_counter"])
DbaEndMessage = message_type("dba_end", [])


class DbaMpComputation(SynchronousComputationMixin, VariableComputation):
    """Distributed Breakout on the agent fabric (reference:
    dba.py:272-597).  A constraint is violated when its cost reaches the
    ``infinity`` marker; the eval value is the weighted count of violated
    constraints, and weights grow at quasi-local-minima (the breakout)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        if comp_def.algo.mode != "min":
            raise ValueError("DBA is a constraint satisfaction algorithm "
                             "and only supports minimization")
        self.infinity = float(params.get("infinity", 10000))
        self.max_distance = int(params.get("max_distance", 50))
        self.constraints = list(comp_def.node.constraints)
        self._weights = [1.0 for _ in self.constraints]
        self._rnd = mp_rng(params, self.name)
        self._neighbor_values: Dict[str, object] = {}
        self._termination_counter = 0
        self._consistent = False
        self._can_move = False
        self._quasi_local_minimum = False
        self._my_improve = 0.0
        self._new_value = None
        self._current_eval = 0.0
        self._violated = []

    def on_start(self):
        self.start_cycle()
        self.value_selection(
            self._rnd.choice(list(self.variable.domain.values)))
        if not self.neighbors:
            self.finished()
            return
        self.post_to_all_neighbors(
            DbaOkMessage(self.current_value), MSG_ALGO)

    def on_fast_forward(self, cycle_id):
        if cycle_id % 2 == 0:
            self.post_to_all_neighbors(
                DbaOkMessage(self.current_value), MSG_ALGO)
        else:
            self.post_to_all_neighbors(
                DbaImproveMessage(0.0, self._current_eval,
                                  self._termination_counter), MSG_ALGO)

    def on_message(self, sender, msg, t):
        # termination is asynchronous in the reference (dba.py:568-581):
        # handle it outside the round barrier so a finished neighbor
        # cannot deadlock our cycle
        if msg.type == "dba_end":
            self._on_end()
            return
        super().on_message(sender, msg, t)

    def _on_end(self):
        if self.is_running:
            self.post_to_all_neighbors(DbaEndMessage(), MSG_ALGO)
            self.finished()
            self.stop()

    @register("dba_ok")
    def _on_ok(self, sender, msg, t):  # pragma: no cover
        pass  # rounds are delivered through on_new_cycle

    @register("dba_improve")
    def _on_improve(self, sender, msg, t):  # pragma: no cover
        pass

    @register("dba_end")
    def _on_end_msg(self, sender, msg, t):  # pragma: no cover
        pass  # handled in on_message, outside the round barrier

    def on_new_cycle(self, messages, cycle_id):
        if cycle_id % 2 == 0:
            self._ok_phase(messages)
        else:
            self._improve_phase(messages)

    # ---------------------------------------------------------- phases

    def _eval_value(self, val):
        """(weighted violation count, violated constraint indices) for
        ``val`` under the neighbors' values (reference: dba.py:450-476).
        """
        assignment = dict(self._neighbor_values)
        assignment[self.variable.name] = val
        total, violated = 0.0, []
        for i, c in enumerate(self.constraints):
            scope = c.scope_names
            if not all(n in assignment for n in scope):
                continue
            if c(**{n: assignment[n] for n in scope}) >= self.infinity:
                violated.append(i)
                total += self._weights[i]
        return total, violated

    def _ok_phase(self, messages):
        """Collect values, compute best weighted-violation improvement,
        announce it (reference: dba.py:352-442)."""
        for sender, (msg, _) in messages.items():
            self._neighbor_values[sender] = msg.value
        self._current_eval, self._violated = self._eval_value(
            self.current_value)
        best_vals, best_eval = [], None
        for v in self.variable.domain.values:
            ev, _ = self._eval_value(v)
            if best_eval is None or ev < best_eval - 1e-9:
                best_vals, best_eval = [v], ev
            elif ev <= best_eval + 1e-9:
                best_vals.append(v)

        if self._current_eval == 0:
            self._consistent = True
        else:
            self._consistent = False
            self._termination_counter = 0
        self._my_improve = self._current_eval - best_eval
        if self._my_improve > 1e-9:
            self._can_move = True
            self._quasi_local_minimum = False
            self._new_value = self._rnd.choice(best_vals)
        else:
            self._can_move = False
            self._quasi_local_minimum = True
        self.post_to_all_neighbors(DbaImproveMessage(
            self._my_improve, self._current_eval,
            self._termination_counter), MSG_ALGO)

    def _improve_phase(self, messages):
        """The strictly-best improver moves (lower name wins ties); at a
        quasi-local-minimum the violated constraints' weights grow
        (reference: dba.py:489-567)."""
        for sender, (msg, _) in messages.items():
            self._termination_counter = min(
                int(msg.termination_counter), self._termination_counter)
            if msg.improve > self._my_improve + 1e-9:
                self._can_move = False
                self._quasi_local_minimum = False
            elif abs(msg.improve - self._my_improve) <= 1e-9 \
                    and self.name > sender:
                self._can_move = False
            if msg.current_eval > 0:
                self._consistent = False

        self.new_cycle()
        if self._consistent:
            self._termination_counter += 1
            if self._termination_counter >= self.max_distance:
                self._on_end()
                return
        if self._quasi_local_minimum:
            for i in self._violated:
                self._weights[i] += 1.0
        if self._can_move:
            self.value_selection(
                self._new_value, self._current_eval - self._my_improve)
        self._neighbor_values.clear()
        self._violated = []
        self.post_to_all_neighbors(
            DbaOkMessage(self.current_value), MSG_ALGO)


def build_computation(comp_def) -> DbaMpComputation:
    return DbaMpComputation(comp_def)

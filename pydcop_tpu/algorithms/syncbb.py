"""SyncBB: complete synchronous branch & bound over a variable ordering.

reference parity: pydcop/algorithms/syncbb.py (512 LoC).  The reference
walks a Current Partial Assignment token up and down the ordered chain
(syncbb.py:235-415); a token protocol is inherently sequential — one
message in flight — so it gains nothing from an array engine (SURVEY.md
§7.5).  We therefore run the same search host-side, with two upgrades the
token protocol cannot do:

* at each level the cost increment of *all* candidate values is computed
  at once (constraint tables pre-lifted to numpy, sliced vectorized), and
  values are explored best-first for earlier pruning,
* pruning uses an admissible suffix lower bound (sum over deeper levels of
  each level's minimum achievable increment), which stays correct with
  negative costs — the reference prunes on the raw partial cost.

The result is exact for min and max objectives.
"""

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..dcop.dcop import DCOP
from ..engine.solver import RunResult
from ..graphs import ordered_graph

GRAPH_TYPE = "ordered_graph"

algo_params = []


def computation_memory(node) -> float:
    return len(node.variable.domain)


def communication_load(node, target: str) -> float:
    # the CPA token carries one (value, cost) pair per variable
    return 1.0


def _compile(dcop: DCOP, sign: float):
    g = ordered_graph.build_computation_graph(dcop)
    nodes = g.ordered_nodes
    pos = {n.name: i for i, n in enumerate(nodes)}
    doms = [list(n.variable.domain.values) for n in nodes]
    per_level = []
    level_min = np.zeros(len(nodes))
    for i, node in enumerate(nodes):
        tables: List[Tuple[np.ndarray, List[int]]] = []
        for c in node.constraints:
            m = c.to_matrix()
            arr = np.asarray(m.matrix, dtype=np.float64) * sign
            tables.append((arr, [pos[v.name] for v in m.dimensions]))
        var_costs = sign * np.array(
            [node.variable.cost_for_val(v) for v in doms[i]],
            dtype=np.float64)
        per_level.append((tables, var_costs))
        level_min[i] = var_costs.min() + sum(
            t.min() for t, _ in tables)
    # suffix_lb[i] = minimum achievable cost of levels i..end
    suffix_lb = np.concatenate(
        [np.cumsum(level_min[::-1])[::-1], [0.0]])
    return nodes, doms, per_level, suffix_lb


def _increments(level: int, x_idx: List[int], per_level, n_values: int
                ) -> np.ndarray:
    """Cost increment of each candidate value at ``level`` given the
    partial assignment — one vectorized slice per constraint."""
    tables, var_costs = per_level[level]
    inc = var_costs.copy()
    for arr, positions in tables:
        # index: ancestors fixed, this level's variable is the free axis
        idx = tuple(
            slice(None) if p == level else x_idx[p] for p in positions
        )
        inc = inc + arr[idx]
    return inc[:n_values]


def solve_direct(dcop: DCOP, params: Optional[Dict] = None,
                 timeout: Optional[float] = None,
                 **_kwargs) -> RunResult:
    t0 = time.perf_counter()
    sign = 1.0 if dcop.objective == "min" else -1.0
    nodes, doms, per_level, suffix_lb = _compile(dcop, sign)
    n = len(nodes)
    if n == 0:
        return RunResult({}, 0, True, 0.0, 0, 0.0)

    best_cost = np.inf
    best: Optional[List[int]] = None
    x_idx = [0] * n
    # per-level exploration state: (ordered candidate indices, pointer,
    # increments)
    stack: List[Tuple[np.ndarray, int, np.ndarray]] = []

    def push(level: int, cost_so_far: float):
        inc = _increments(level, x_idx, per_level, len(doms[level]))
        order = np.argsort(inc, kind="stable")
        stack.append([order, 0, inc, cost_so_far])

    push(0, 0.0)
    msg_count = 0
    outer_iter = 0
    status = "FINISHED"
    while stack:
        outer_iter += 1
        if timeout is not None and outer_iter % 1024 == 0 \
                and time.perf_counter() - t0 > timeout:
            status = "TIMEOUT"  # anytime: keep the best found so far
            break
        order, ptr, inc, cost_so_far = stack[-1]
        level = len(stack) - 1
        advanced = False
        while ptr < len(order):
            vi = int(order[ptr])
            ptr += 1
            c = cost_so_far + inc[vi]
            # admissible bound: best-first order makes further values at
            # this level no better, so prune the whole level
            if c + suffix_lb[level + 1] >= best_cost:
                ptr = len(order)
                break
            x_idx[level] = vi
            msg_count += 1
            if level == n - 1:
                if c < best_cost:
                    best_cost = c
                    best = list(x_idx)
                continue
            stack[-1][1] = ptr
            push(level + 1, c)
            advanced = True
            break
        if not advanced:
            stack.pop()
        else:
            continue

    assignment = {
        nodes[i].name: doms[i][best[i]] for i in range(n)
    } if best is not None else {}
    cost, violations = dcop.solution_cost(assignment) if assignment else (
        np.inf, 0)
    return RunResult(
        assignment=assignment,
        cycles=msg_count,
        finished=status == "FINISHED",
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status=status,
        metrics={"msg_count": msg_count},
    )

"""SyncBB: complete synchronous branch & bound over a variable ordering.

reference parity: pydcop/algorithms/syncbb.py (512 LoC).  The reference
walks a Current Partial Assignment token up and down the ordered chain
(syncbb.py:235-415); a token protocol is inherently sequential — one
message in flight — so it gains nothing from an array engine (SURVEY.md
§7.5).  We therefore run the same search host-side, with two upgrades the
token protocol cannot do:

* at each level the cost increment of *all* candidate values is computed
  at once (constraint tables pre-lifted to numpy, sliced vectorized), and
  values are explored best-first for earlier pruning,
* pruning uses an admissible suffix lower bound (sum over deeper levels of
  each level's minimum achievable increment), which stays correct with
  negative costs — the reference prunes on the raw partial cost.

The result is exact for min and max objectives.
"""

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..dcop.dcop import DCOP
from ..engine.solver import RunResult
from ..graphs import ordered_graph

GRAPH_TYPE = "ordered_graph"

algo_params = []


def computation_memory(node) -> float:
    return len(node.variable.domain)


def communication_load(node, target: str) -> float:
    # the CPA token carries one (value, cost) pair per variable
    return 1.0


def _compile(dcop: DCOP, sign: float):
    g = ordered_graph.build_computation_graph(dcop)
    nodes = g.ordered_nodes
    pos = {n.name: i for i, n in enumerate(nodes)}
    doms = [list(n.variable.domain.values) for n in nodes]
    per_level = []
    level_min = np.zeros(len(nodes))
    for i, node in enumerate(nodes):
        tables: List[Tuple[np.ndarray, List[int]]] = []
        for c in node.constraints:
            m = c.to_matrix()
            arr = np.asarray(m.matrix, dtype=np.float64) * sign
            tables.append((arr, [pos[v.name] for v in m.dimensions]))
        var_costs = sign * np.array(
            [node.variable.cost_for_val(v) for v in doms[i]],
            dtype=np.float64)
        per_level.append((tables, var_costs))
        level_min[i] = var_costs.min() + sum(
            t.min() for t, _ in tables)
    # suffix_lb[i] = minimum achievable cost of levels i..end
    suffix_lb = np.concatenate(
        [np.cumsum(level_min[::-1])[::-1], [0.0]])
    return nodes, doms, per_level, suffix_lb


def _increments(level: int, x_idx: List[int], per_level, n_values: int
                ) -> np.ndarray:
    """Cost increment of each candidate value at ``level`` given the
    partial assignment — one vectorized slice per constraint."""
    tables, var_costs = per_level[level]
    inc = var_costs.copy()
    for arr, positions in tables:
        # index: ancestors fixed, this level's variable is the free axis
        idx = tuple(
            slice(None) if p == level else x_idx[p] for p in positions
        )
        inc = inc + arr[idx]
    return inc[:n_values]


def solve_direct(dcop: DCOP, params: Optional[Dict] = None,
                 timeout: Optional[float] = None,
                 **_kwargs) -> RunResult:
    t0 = time.perf_counter()
    sign = 1.0 if dcop.objective == "min" else -1.0
    nodes, doms, per_level, suffix_lb = _compile(dcop, sign)
    n = len(nodes)
    if n == 0:
        return RunResult({}, 0, True, 0.0, 0, 0.0)

    best_cost = np.inf
    best: Optional[List[int]] = None
    x_idx = [0] * n
    # per-level exploration state: (ordered candidate indices, pointer,
    # increments)
    stack: List[Tuple[np.ndarray, int, np.ndarray]] = []

    def push(level: int, cost_so_far: float):
        inc = _increments(level, x_idx, per_level, len(doms[level]))
        order = np.argsort(inc, kind="stable")
        stack.append([order, 0, inc, cost_so_far])

    push(0, 0.0)
    msg_count = 0
    outer_iter = 0
    status = "FINISHED"
    while stack:
        outer_iter += 1
        if timeout is not None and outer_iter % 1024 == 0 \
                and time.perf_counter() - t0 > timeout:
            status = "TIMEOUT"  # anytime: keep the best found so far
            break
        order, ptr, inc, cost_so_far = stack[-1]
        level = len(stack) - 1
        advanced = False
        while ptr < len(order):
            vi = int(order[ptr])
            ptr += 1
            c = cost_so_far + inc[vi]
            # admissible bound: best-first order makes further values at
            # this level no better, so prune the whole level
            if c + suffix_lb[level + 1] >= best_cost:
                ptr = len(order)
                break
            x_idx[level] = vi
            msg_count += 1
            if level == n - 1:
                if c < best_cost:
                    best_cost = c
                    best = list(x_idx)
                continue
            stack[-1][1] = ptr
            push(level + 1, c)
            advanced = True
            break
        if not advanced:
            stack.pop()
        else:
            continue

    assignment = {
        nodes[i].name: doms[i][best[i]] for i in range(n)
    } if best is not None else {}
    cost, violations = dcop.solution_cost(assignment) if assignment else (
        np.inf, 0)
    return RunResult(
        assignment=assignment,
        cycles=msg_count,
        finished=status == "FINISHED",
        cost=cost,
        violations=violations,
        duration=time.perf_counter() - t0,
        status=status,
        metrics={"msg_count": msg_count},
    )


# ---------------------------------------------------------------------
# Message-passing backend: SyncBB running ON the agent fabric
# (reference: syncbb.py:150-512).  A Current Partial Assignment token
# walks the variable chain: forward messages extend it, backward
# messages backtrack, terminate carries the optimum to every node.
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    VariableComputation, message_type, register)

INFINITY = float("inf")


def _wire_ub(ub: float):
    """inf is not JSON-compliant (the HTTP transport rejects it): an
    unset upper bound travels as None."""
    return None if ub == INFINITY or ub == -INFINITY else ub


def _unwire_ub(ub) -> float:
    return INFINITY if ub is None else float(ub)


#: current_path: [[var, value, cost], ...]
SyncBBForwardMessage = message_type("syncbb_forward",
                                    ["current_path", "ub"])
#: best: [[var, value], ...] full assignment achieving ub (the
#: reference's backward carries only the bound, syncbb.py:355-370, and
#: leaves middle variables on stale values at termination)
SyncBBBackwardMessage = message_type("syncbb_backward",
                                     ["current_path", "ub", "best"])
#: assignment: [[var, value], ...] of the best full path found (the
#: reference's terminate message carries nothing and leaves middle
#: variables on their last backward-improved value, syncbb.py:211-229;
#: carrying the optimum assigns every variable exactly)
SyncBBTerminateMessage = message_type("syncbb_terminate",
                                      ["assignment", "ub"])


class SyncBBMpComputation(VariableComputation):
    """One variable of the SyncBB chain (reference: syncbb.py:175-415).
    Works in signed (minimizing) space: max problems negate costs."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        node = comp_def.node
        self.mode = comp_def.algo.mode
        self.constraints = list(node.constraints)
        self.next_var = node.next_node
        self.previous_var = node.previous_node
        self.upper_bound = INFINITY
        self._best_assignment = None
        self._sign = 1.0 if self.mode != "max" else -1.0

    def on_start(self):
        if self.previous_var is None:
            if self.next_var is None:
                # single-variable problem: optimize locally
                best_val, best_cost = None, INFINITY
                for v in self.variable.domain.values:
                    cost = self._sign * self.variable.cost_for_val(v)
                    if cost < best_cost:
                        best_val, best_cost = v, cost
                self.value_selection(best_val, self._sign * best_cost)
                self.finished()
                return
            first = self.variable.domain.values[0]
            # include our unary cost so the path bound stays exact (the
            # reference seeds with 0, syncbb.py:203, and loses unary
            # costs of the first variable)
            path = [[self.name, first,
                     self._path_cost_for(first, [])]]
            self.post_msg(self.next_var,
                          SyncBBForwardMessage(path, None), MSG_ALGO)

    # ------------------------------------------------------- helpers

    def _path_cost_for(self, candidate, current_path):
        """Signed cost this variable adds to the path by taking
        ``candidate`` (reference: syncbb.py:420-474), with upper-bound
        pruning."""
        assignment = {var: val for var, val, _ in current_path}
        assignment[self.name] = candidate
        cost = self._sign * self.variable.cost_for_val(candidate)
        for c in self.constraints:
            scope = c.scope_names
            if all(n in assignment for n in scope):
                cost += self._sign * c(
                    **{n: assignment[n] for n in scope})
        return cost

    def _next_assignment(self, current_value, current_path):
        """First domain value after ``current_value`` whose path cost
        keeps the partial assignment under the upper bound."""
        values = list(self.variable.domain.values)
        if current_value is not None:
            idx = values.index(current_value) + 1
            values = values[idx:]
        path_bound = sum(c for _, _, c in current_path)
        for candidate in values:
            cost = self._path_cost_for(candidate, current_path)
            if path_bound + cost < self.upper_bound:
                return candidate, cost
        return None

    def _terminate(self):
        assignment = self._best_assignment or []
        for var, val in assignment:
            if var == self.name:
                self.value_selection(val, self._sign * self.upper_bound)
        if self.next_var is not None:
            self.post_msg(self.next_var, SyncBBTerminateMessage(
                assignment, _wire_ub(self.upper_bound)), MSG_ALGO)
        self.finished()

    # ------------------------------------------------------ handlers

    @register("syncbb_terminate")
    def _on_terminate(self, sender, msg, t):
        self.upper_bound = _unwire_ub(msg.ub)
        self._best_assignment = msg.assignment
        self._terminate()

    @register("syncbb_forward")
    def _on_forward(self, sender, msg, t):
        current_path = [list(e) for e in msg.current_path]
        if msg.ub is not None and float(msg.ub) < self.upper_bound:
            self.upper_bound = float(msg.ub)
        nxt = self._next_assignment(None, current_path)
        if nxt is None:
            if self.previous_var is None:
                self._terminate()
            else:
                self.post_msg(self.previous_var, SyncBBBackwardMessage(
                    current_path, _wire_ub(self.upper_bound),
                    self._best_assignment), MSG_ALGO)
            self.new_cycle()
            return
        if self.next_var is None:
            # last in the chain: sweep the whole domain for new bounds
            # (reference: syncbb.py:283-330)
            path_bound = sum(c for _, _, c in current_path)
            value, cost = nxt
            while True:
                if path_bound + cost < self.upper_bound:
                    self.upper_bound = path_bound + cost
                    self._best_assignment = [
                        [var, val] for var, val, _ in current_path
                    ] + [[self.name, value]]
                    self.value_selection(value,
                                         self._sign * self.upper_bound)
                nxt = self._next_assignment(value, current_path)
                if nxt is None:
                    break
                value, cost = nxt
            self.post_msg(self.previous_var, SyncBBBackwardMessage(
                current_path, _wire_ub(self.upper_bound),
                self._best_assignment), MSG_ALGO)
        else:
            value, cost = nxt
            new_path = current_path + [[self.name, value, cost]]
            self.post_msg(self.next_var, SyncBBForwardMessage(
                new_path, _wire_ub(self.upper_bound)), MSG_ALGO)
        self.new_cycle()

    @register("syncbb_backward")
    def _on_backward(self, sender, msg, t):
        current_path = [list(e) for e in msg.current_path]
        ub = _unwire_ub(msg.ub)
        if ub < self.upper_bound or (
                ub == self.upper_bound
                and self._best_assignment is None):
            self.upper_bound = ub
            if msg.best is not None:
                self._best_assignment = msg.best
        var, val, _ = current_path[-1]
        nxt = self._next_assignment(val, current_path[:-1])
        if nxt is not None:
            new_val, new_cost = nxt
            new_path = current_path[:-1] + [[self.name, new_val,
                                             new_cost]]
            self.post_msg(self.next_var, SyncBBForwardMessage(
                new_path, _wire_ub(self.upper_bound)), MSG_ALGO)
        elif self.previous_var is None:
            self._terminate()
        else:
            self.post_msg(self.previous_var, SyncBBBackwardMessage(
                current_path[:-1], _wire_ub(self.upper_bound),
                self._best_assignment), MSG_ALGO)
        self.new_cycle()


def build_computation(comp_def) -> SyncBBMpComputation:
    return SyncBBMpComputation(comp_def)

"""Dynamic MaxSum: factors whose cost functions change at runtime.

reference parity: pydcop/algorithms/maxsum_dynamic.py (405 LoC):

* ``DynamicFunctionFactorComputation`` (:40) — a factor whose function can
  be swapped mid-run (``change_factor_function``), dimensions unchanged.
* ``FactorWithReadOnlyVariables`` (:113) — a factor conditioned on
  external (sensor) variables; on an external value change the factor is
  re-sliced over the remaining decision variables.
* ``DynamicFactorComputation`` (:188) — a factor whose *dimensions* can
  change; neighbor variables re-subscribe (:352).

TPU-first design: the factor cost hypercubes are moved from solver
constants into the **state pytree**, so swapping a factor's function is a
host-side ``state.at[row].set(new_cube)`` between jitted steps — same
shapes, zero recompilation.  Dimension changes do force new shapes, so
they take the rebuild path: compile new arrays and migrate message state
for every (variable, factor) edge that survives, exactly the information
the reference preserves across re-subscription.
"""

from typing import Dict, Iterable, Optional

import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP
from ..dcop.relations import Constraint
from ..graphs.arrays import FactorGraphArrays, _padded_cube
from . import AlgoParameterDef
from .amaxsum import AMaxSumSolver
from .maxsum import HEADER_SIZE, UNIT_SIZE  # noqa: F401
from .maxsum import communication_load, computation_memory  # noqa: F401

GRAPH_TYPE = "factor_graph"

algo_params = [
    AlgoParameterDef("damping", "float", None, 0.5),
    AlgoParameterDef("damping_nodes", "str",
                     ["vars", "factors", "both", "none"], "vars"),
    AlgoParameterDef("stability", "float", None, 0.1),
    AlgoParameterDef("noise", "float", None, 0.0),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("activation", "float", None, 1.0),
]


class DynamicMaxSumSolver(AMaxSumSolver):
    """A-MaxSum whose factor tables live in the state pytree.

    ``activation`` defaults to 1.0 (synchronous); lower it for the
    asynchronous behavior of the reference's A-MaxSum base.
    """

    def __init__(self, arrays: FactorGraphArrays, **kwargs):
        kwargs.setdefault("activation", 1.0)
        super().__init__(arrays, **kwargs)
        # factor name -> (bucket index, row in bucket)
        self._factor_pos: Dict[str, tuple] = {}
        for b_idx, bucket in enumerate(arrays.buckets):
            for row, f_id in enumerate(bucket.factor_ids):
                self._factor_pos[arrays.factor_names[int(f_id)]] = (
                    b_idx, row)

    def init_state(self, key):
        s = super().init_state(key)
        s["cubes"] = tuple(cubes for cubes, _, _ in self.buckets)
        return s

    def _cubes(self, s):
        return list(s["cubes"])

    # ------------------------------------------------------------------ #
    # host-side dynamics (called between steps, never traced)            #
    # ------------------------------------------------------------------ #

    def change_factor_function(self, state, factor_name: str,
                               constraint: Constraint):
        """Swap one factor's cost function, dimensions unchanged
        (reference: maxsum_dynamic.py:40-110 ``change_factor_function``).

        Returns a new state; the jitted step is reused as-is.
        """
        b_idx, row = self._factor_pos[factor_name]
        bucket = self.arrays.buckets[b_idx]
        if constraint.arity != bucket.arity:
            raise ValueError(
                f"change_factor_function: factor {factor_name!r} has "
                f"arity {bucket.arity}, new constraint has "
                f"{constraint.arity}; dimension changes need rebuild()"
            )
        expect = [self.arrays.var_names[int(v)]
                  for v in bucket.var_ids[row]]
        got = [v.name for v in constraint.dimensions]
        if expect != got:
            raise ValueError(
                f"change_factor_function: factor {factor_name!r} scope is "
                f"{expect}, new constraint scope is {got}; dimension "
                f"changes need rebuild()"
            )
        cube = _padded_cube(constraint, self.arrays.max_domain,
                            self.arrays.sign)
        cubes = list(state["cubes"])
        cubes[b_idx] = jnp.asarray(cubes[b_idx]).at[row].set(
            jnp.asarray(cube))
        out = dict(state)
        out["cubes"] = tuple(cubes)
        # a changed factor invalidates convergence history
        out["same"] = jnp.int32(0)
        out["finished"] = jnp.bool_(False)
        return out

    def set_externals(self, state, factor_name: str,
                      base_constraint: Constraint,
                      external_values: Dict[str, object]):
        """Re-slice a factor conditioned on external (read-only) variables
        at their new values (reference: maxsum_dynamic.py:113-186
        ``FactorWithReadOnlyVariables.on_external_var_change``)."""
        b_idx, row = self._factor_pos[factor_name]
        bucket = self.arrays.buckets[b_idx]
        scope = {self.arrays.var_names[int(v)]
                 for v in bucket.var_ids[row]}
        externals = [v.name for v in base_constraint.dimensions
                     if v.name not in scope]
        missing = [n for n in externals if n not in external_values]
        if missing:
            raise ValueError(
                f"set_externals: factor {factor_name!r} needs values for "
                f"external variables {missing}"
            )
        fixed = {n: external_values[n] for n in externals}
        sliced = base_constraint.slice(fixed) if fixed else base_constraint
        return self.change_factor_function(state, factor_name, sliced)


def rebuild(dcop: DCOP, solver: DynamicMaxSumSolver, state,
            variables=None, constraints=None,
            params: Optional[Dict] = None):
    """Dimension-changing rebuild
    (reference: maxsum_dynamic.py:188-352 ``DynamicFactorComputation`` +
    variable re-subscription).

    Compiles fresh arrays for the updated problem and migrates the q/r
    message rows of every (variable, factor) edge present in both the old
    and new graphs — new edges start from the neutral zero message, exactly
    as a freshly subscribed variable does in the reference.  Returns
    ``(new_solver, new_state)``; the next ``step`` call triggers one
    recompile for the new shapes.
    """
    params = dict(params or {})
    params.setdefault("damping", solver.damping)
    params.setdefault("damping_nodes", solver.damping_nodes)
    params.setdefault("stability", solver.stability_param)
    params.setdefault("noise", solver.noise)
    params.setdefault("stop_cycle", solver.stop_cycle)
    params.setdefault("activation", solver.activation)
    new_arrays = FactorGraphArrays.build(dcop, variables, constraints)
    new_solver = DynamicMaxSumSolver(new_arrays, **params)
    new_state = new_solver.init_state(state["key"])

    # factors whose scope survived keep their *current* (possibly
    # runtime-swapped) table from the old state, not the DCOP's original —
    # the reference's DynamicFunctionFactorComputation keeps its current
    # function across re-subscription
    if solver.arrays.max_domain == new_arrays.max_domain:
        new_cubes = [np.array(c) for c in new_state["cubes"]]
        old_cubes = [np.asarray(c) for c in state["cubes"]]
        for fname, (ob, orow) in solver._factor_pos.items():
            pos = new_solver._factor_pos.get(fname)
            if pos is None:
                continue
            nb, nrow = pos
            old_bucket = solver.arrays.buckets[ob]
            new_bucket = new_arrays.buckets[nb]
            if old_bucket.arity != new_bucket.arity:
                continue
            old_scope = [solver.arrays.var_names[int(v)]
                         for v in old_bucket.var_ids[orow]]
            new_scope = [new_arrays.var_names[int(v)]
                         for v in new_bucket.var_ids[nrow]]
            if old_scope == new_scope:
                new_cubes[nb][nrow] = old_cubes[ob][orow]
        new_state["cubes"] = tuple(jnp.asarray(c) for c in new_cubes)

    old_a, new_a = solver.arrays, new_arrays
    old_edge = {
        (old_a.var_names[int(old_a.edge_var[e])],
         old_a.factor_names[int(old_a.edge_factor[e])]): e
        for e in range(old_a.n_edges)
    }
    q = np.array(new_state["q"])
    r = np.array(new_state["r"])
    old_q = np.asarray(state["q"])
    old_r = np.asarray(state["r"])
    d = min(old_a.max_domain, new_a.max_domain)
    for e in range(new_a.n_edges):
        key = (new_a.var_names[int(new_a.edge_var[e])],
               new_a.factor_names[int(new_a.edge_factor[e])])
        oe = old_edge.get(key)
        if oe is not None:
            q[e, :d] = old_q[oe, :d]
            r[e, :d] = old_r[oe, :d]
    new_state["q"] = jnp.asarray(q)
    new_state["r"] = jnp.asarray(r)
    new_state["cycle"] = state["cycle"]
    return new_solver, new_state


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DynamicMaxSumSolver:
    params = params or {}
    arrays = FactorGraphArrays.build(dcop, variables, constraints)
    return DynamicMaxSumSolver(arrays, **params)

"""Dynamic MaxSum: factors whose cost functions change at runtime.

reference parity: pydcop/algorithms/maxsum_dynamic.py (405 LoC):

* ``DynamicFunctionFactorComputation`` (:40) — a factor whose function can
  be swapped mid-run (``change_factor_function``), dimensions unchanged.
* ``FactorWithReadOnlyVariables`` (:113) — a factor conditioned on
  external (sensor) variables; on an external value change the factor is
  re-sliced over the remaining decision variables.
* ``DynamicFactorComputation`` (:188) — a factor whose *dimensions* can
  change; neighbor variables re-subscribe (:352).

TPU-first design: the factor cost hypercubes are moved from solver
constants into the **state pytree**, so swapping a factor's function is a
host-side ``state.at[row].set(new_cube)`` between jitted steps — same
shapes, zero recompilation.  Dimension changes do force new shapes, so
they take the rebuild path: compile new arrays and migrate message state
for every (variable, factor) edge that survives, exactly the information
the reference preserves across re-subscription.
"""

from typing import Dict, Iterable, Optional

import jax.numpy as jnp
import numpy as np

from ..dcop.dcop import DCOP
from ..dcop.relations import Constraint
from ..graphs.arrays import FactorGraphArrays, _padded_cube
from . import AlgoParameterDef
from .amaxsum import AMaxSumSolver
from .maxsum import HEADER_SIZE, UNIT_SIZE  # noqa: F401
from .maxsum import communication_load, computation_memory  # noqa: F401

GRAPH_TYPE = "factor_graph"

algo_params = [
    AlgoParameterDef("damping", "float", None, 0.5),
    AlgoParameterDef("damping_nodes", "str",
                     ["vars", "factors", "both", "none"], "vars"),
    AlgoParameterDef("stability", "float", None, 0.1),
    AlgoParameterDef("noise", "float", None, 0.0),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("activation", "float", None, 1.0),
]


class DynamicMaxSumSolver(AMaxSumSolver):
    """A-MaxSum whose factor tables live in the state pytree.

    ``activation`` defaults to 1.0 (synchronous); lower it for the
    asynchronous behavior of the reference's A-MaxSum base.
    """

    def __init__(self, arrays: FactorGraphArrays, **kwargs):
        kwargs.setdefault("activation", 1.0)
        if kwargs.get("bnb"):
            # loud rejection: bnb plans are build-time constants of the
            # cube CONTENTS (sorted cell order + suffix bounds), and
            # this solver swaps cubes through the state pytree between
            # steps — a swap would leave the plans silently stale
            raise ValueError(
                "maxsum_dynamic does not support bnb: pruned-reduction "
                "plans are build-time cube constants and factor tables "
                "are host-swappable here; use the static maxsum solver")
        super().__init__(arrays, **kwargs)
        # factor name -> (bucket index, row in bucket)
        self._factor_pos: Dict[str, tuple] = {}
        for b_idx, bucket in enumerate(arrays.buckets):
            for row, f_id in enumerate(bucket.factor_ids):
                self._factor_pos[arrays.factor_names[int(f_id)]] = (
                    b_idx, row)

    def init_state(self, key):
        s = super().init_state(key)
        s["cubes"] = tuple(cubes for cubes, _, _ in self.buckets)
        return s

    def _cubes(self, s):
        return list(s["cubes"])

    # ------------------------------------------------------------------ #
    # host-side dynamics (called between steps, never traced)            #
    # ------------------------------------------------------------------ #

    def change_factor_function(self, state, factor_name: str,
                               constraint: Constraint):
        """Swap one factor's cost function, dimensions unchanged
        (reference: maxsum_dynamic.py:40-110 ``change_factor_function``).

        Returns a new state; the jitted step is reused as-is.
        """
        b_idx, row = self._factor_pos[factor_name]
        bucket = self.arrays.buckets[b_idx]
        if constraint.arity != bucket.arity:
            raise ValueError(
                f"change_factor_function: factor {factor_name!r} has "
                f"arity {bucket.arity}, new constraint has "
                f"{constraint.arity}; dimension changes need rebuild()"
            )
        expect = [self.arrays.var_names[int(v)]
                  for v in bucket.var_ids[row]]
        got = [v.name for v in constraint.dimensions]
        if expect != got:
            raise ValueError(
                f"change_factor_function: factor {factor_name!r} scope is "
                f"{expect}, new constraint scope is {got}; dimension "
                f"changes need rebuild()"
            )
        cube = _padded_cube(constraint, self.arrays.max_domain,
                            self.arrays.sign)
        cubes = list(state["cubes"])
        cubes[b_idx] = jnp.asarray(cubes[b_idx]).at[row].set(
            jnp.asarray(cube))
        out = dict(state)
        out["cubes"] = tuple(cubes)
        # a changed factor invalidates convergence history
        out["same"] = jnp.int32(0)
        out["finished"] = jnp.bool_(False)
        return out

    def set_externals(self, state, factor_name: str,
                      base_constraint: Constraint,
                      external_values: Dict[str, object]):
        """Re-slice a factor conditioned on external (read-only) variables
        at their new values (reference: maxsum_dynamic.py:113-186
        ``FactorWithReadOnlyVariables.on_external_var_change``)."""
        b_idx, row = self._factor_pos[factor_name]
        bucket = self.arrays.buckets[b_idx]
        scope = {self.arrays.var_names[int(v)]
                 for v in bucket.var_ids[row]}
        externals = [v.name for v in base_constraint.dimensions
                     if v.name not in scope]
        missing = [n for n in externals if n not in external_values]
        if missing:
            raise ValueError(
                f"set_externals: factor {factor_name!r} needs values for "
                f"external variables {missing}"
            )
        fixed = {n: external_values[n] for n in externals}
        sliced = base_constraint.slice(fixed) if fixed else base_constraint
        return self.change_factor_function(state, factor_name, sliced)


def rebuild(dcop: DCOP, solver: DynamicMaxSumSolver, state,
            variables=None, constraints=None,
            params: Optional[Dict] = None):
    """Dimension-changing rebuild
    (reference: maxsum_dynamic.py:188-352 ``DynamicFactorComputation`` +
    variable re-subscription).

    Compiles fresh arrays for the updated problem and migrates the q/r
    message rows of every (variable, factor) edge present in both the old
    and new graphs — new edges start from the neutral zero message, exactly
    as a freshly subscribed variable does in the reference.  Returns
    ``(new_solver, new_state)``; the next ``step`` call triggers one
    recompile for the new shapes.
    """
    params = dict(params or {})
    params.setdefault("damping", solver.damping)
    params.setdefault("damping_nodes", solver.damping_nodes)
    params.setdefault("stability", solver.stability_param)
    params.setdefault("noise", solver.noise)
    params.setdefault("stop_cycle", solver.stop_cycle)
    params.setdefault("activation", solver.activation)
    new_arrays = FactorGraphArrays.build(dcop, variables, constraints)
    new_solver = DynamicMaxSumSolver(new_arrays, **params)
    new_state = new_solver.init_state(state["key"])

    # factors whose scope survived keep their *current* (possibly
    # runtime-swapped) table from the old state, not the DCOP's original —
    # the reference's DynamicFunctionFactorComputation keeps its current
    # function across re-subscription
    if solver.arrays.max_domain == new_arrays.max_domain:
        new_cubes = [np.array(c) for c in new_state["cubes"]]
        old_cubes = [np.asarray(c) for c in state["cubes"]]
        for fname, (ob, orow) in solver._factor_pos.items():
            pos = new_solver._factor_pos.get(fname)
            if pos is None:
                continue
            nb, nrow = pos
            old_bucket = solver.arrays.buckets[ob]
            new_bucket = new_arrays.buckets[nb]
            if old_bucket.arity != new_bucket.arity:
                continue
            old_scope = [solver.arrays.var_names[int(v)]
                         for v in old_bucket.var_ids[orow]]
            new_scope = [new_arrays.var_names[int(v)]
                         for v in new_bucket.var_ids[nrow]]
            if old_scope == new_scope:
                new_cubes[nb][nrow] = old_cubes[ob][orow]
        new_state["cubes"] = tuple(jnp.asarray(c) for c in new_cubes)

    old_a, new_a = solver.arrays, new_arrays
    old_edge = {
        (old_a.var_names[int(old_a.edge_var[e])],
         old_a.factor_names[int(old_a.edge_factor[e])]): e
        for e in range(old_a.n_edges)
    }
    q = np.array(new_state["q"])
    r = np.array(new_state["r"])
    old_q = np.asarray(state["q"])
    old_r = np.asarray(state["r"])
    d = min(old_a.max_domain, new_a.max_domain)
    for e in range(new_a.n_edges):
        key = (new_a.var_names[int(new_a.edge_var[e])],
               new_a.factor_names[int(new_a.edge_factor[e])])
        oe = old_edge.get(key)
        if oe is not None:
            q[e, :d] = old_q[oe, :d]
            r[e, :d] = old_r[oe, :d]
    new_state["q"] = jnp.asarray(q)
    new_state["r"] = jnp.asarray(r)
    new_state["cycle"] = state["cycle"]
    return new_solver, new_state


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> DynamicMaxSumSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = FactorGraphArrays.build(dcop, variables, constraints)
    return DynamicMaxSumSolver(arrays, **params)


# ---------------------------------------------------------------------
# Message-passing backend: dynamic MaxSum computations ON the agent
# fabric (reference: maxsum_dynamic.py:40-405).  The reference ships
# three factor computation classes meant to be subclassed by
# applications; their fabric equivalents here build on the asynchronous
# amaxsum backend so a deployed dynamic system exchanges the same
# amaxsum_costs messages, plus the dynamic control messages
# (VARIABLE_VALUE / ADD / REMOVE).
# ---------------------------------------------------------------------

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import Message, register
from .amaxsum import AMaxSumFactorMpComputation, \
    AMaxSumVariableMpComputation


class DynamicFunctionFactorMpComputation(AMaxSumFactorMpComputation):
    """Factor whose cost function can be swapped mid-run, dimensions
    unchanged (reference: maxsum_dynamic.py:40-110)."""

    def change_factor_function(self, factor):
        """Swap in a new factor with identical dimensions and replay the
        marginals (reference: maxsum_dynamic.py:80-105)."""
        old_names = [v.name for v in self.variables]
        new_names = [v.name for v in factor.dimensions]
        if set(old_names) != set(new_names):
            raise ValueError(
                f"change_factor_function requires identical dimensions; "
                f"got {new_names}, had {old_names}")
        self.factor = factor
        self.variables = list(factor.dimensions)
        self._load_cube()
        # previous send history no longer describes the new function
        self._r_sent.clear()
        self._same_sent.clear()
        if self.is_running:
            self._send_marginals()


class FactorWithReadOnlyVariableMpComputation(
        DynamicFunctionFactorMpComputation):
    """Factor conditioned on external (sensor) variables: subscribes to
    their publishing computations and re-slices its cube on every
    VARIABLE_VALUE publication (reference: maxsum_dynamic.py:113-187)."""

    def __init__(self, comp_def, read_only_variables=()):
        super().__init__(comp_def)
        self.read_only_variables = list(read_only_variables)
        self._external_values = {}
        self._full_factor = self.factor
        # decision variables = dimensions minus the read-only ones
        ro_names = {v.name for v in self.read_only_variables}
        self.variables = [v for v in self.factor.dimensions
                          if v.name not in ro_names]

    def on_start(self):
        for v in self.read_only_variables:
            self.post_msg(v.name, Message("SUBSCRIBE", self.name),
                          MSG_ALGO)
        super().on_start()

    @register("VARIABLE_VALUE")
    def _on_variable_value(self, sender, msg, t):
        self._external_values[sender] = msg.content
        if len(self._external_values) < len(self.read_only_variables):
            return
        sliced = self._full_factor.slice(dict(self._external_values))
        self.factor = sliced
        self.variables = list(sliced.dimensions)
        self._load_cube()
        self._r_sent.clear()
        self._same_sent.clear()
        if self.is_running:
            self._send_marginals()


class DynamicFactorMpComputation(DynamicFunctionFactorMpComputation):
    """Factor whose *dimensions* may change: on a function swap with a
    different scope, departed variables get REMOVE, joining ones ADD
    (reference: maxsum_dynamic.py:188-350)."""

    def change_factor_function(self, factor):
        old = {v.name for v in self.variables}
        new = {v.name for v in factor.dimensions}
        self.factor = factor
        self.variables = list(factor.dimensions)
        self._load_cube()
        self._q = {k: v for k, v in self._q.items() if k in new}
        self._r_sent.clear()
        self._same_sent.clear()
        for name in sorted(old - new):
            self.post_msg(name, Message("REMOVE", self.name), MSG_ALGO)
        for name in sorted(new - old):
            self.post_msg(name, Message("ADD", self.name), MSG_ALGO)
        if self.is_running:
            self._send_marginals()


class DynamicFactorVariableMpComputation(AMaxSumVariableMpComputation):
    """Variable that tracks factor ADD/REMOVE notifications
    (reference: maxsum_dynamic.py:352-405)."""

    @register("REMOVE")
    def _on_remove(self, sender, msg, t):
        if sender in self.factor_names:
            self.factor_names.remove(sender)
        self._r.pop(sender, None)
        self._q_sent.pop(sender, None)
        self._same_sent.pop(sender, None)
        if self.is_running:
            self._select()

    @register("ADD")
    def _on_add(self, sender, msg, t):
        if sender not in self.factor_names:
            self.factor_names.append(sender)
        if self.is_running:
            self._send_all()


def build_computation(comp_def):
    """Deploy dynamic-capable computations: amaxsum messaging plus the
    dynamic control protocol (the reference's classes are meant to be
    subclassed by applications; these are directly deployable)."""
    if hasattr(comp_def.node, "variable"):
        return DynamicFactorVariableMpComputation(comp_def)
    return DynamicFactorMpComputation(comp_def)

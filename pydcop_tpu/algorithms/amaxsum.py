"""A-MaxSum: asynchronous MaxSum.

reference parity: pydcop/algorithms/amaxsum.py (424 LoC).  The reference
reuses MaxSum's math but sends messages on every receipt with no cycle
barrier (amaxsum.py:108-251).  In the compiled engine the faithful model
(SURVEY.md §7 hard part 3) is *stochastic activation*: each cycle an
independent random subset of edges refreshes its messages while the rest
keep their previous values — reproducing the reference's property that
updates propagate asynchronously through a loopy graph, which often damps
oscillations that bite synchronous MaxSum.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..dcop.dcop import DCOP
from ..graphs.arrays import FactorGraphArrays
from . import AlgoParameterDef
from .maxsum import HEADER_SIZE, UNIT_SIZE, MaxSumSolver
from .maxsum import communication_load, computation_memory  # noqa: F401

GRAPH_TYPE = "factor_graph"

algo_params = [
    AlgoParameterDef("damping", "float", None, 0.5),
    AlgoParameterDef("damping_nodes", "str",
                     ["vars", "factors", "both", "none"], "vars"),
    AlgoParameterDef("stability", "float", None, 0.1),
    AlgoParameterDef("noise", "float", None, 0.0),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("activation", "float", None, 0.7),
]


class AMaxSumSolver(MaxSumSolver):
    def __init__(self, arrays: FactorGraphArrays, activation: float = 0.7,
                 **kwargs):
        super().__init__(arrays, **kwargs)
        self.activation = float(activation)

    def step(self, s):
        key, k_act_q, k_act_r = jax.random.split(s["key"], 3)
        s2 = dict(s)
        s2["key"] = key
        out = super().step(s2)
        # only a random subset of edges refreshes its messages this cycle
        act_q = jax.random.uniform(
            k_act_q, (self.E, 1)) < self.activation
        act_r = jax.random.uniform(
            k_act_r, (self.E, 1)) < self.activation
        out["q"] = jnp.where(act_q, out["q"], s["q"])
        out["r"] = jnp.where(act_r, out["r"], s["r"])
        return out


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> AMaxSumSolver:
    params = params or {}
    arrays = FactorGraphArrays.build(dcop, variables, constraints)
    return AMaxSumSolver(arrays, **params)

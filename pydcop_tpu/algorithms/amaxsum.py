"""A-MaxSum: asynchronous MaxSum.

reference parity: pydcop/algorithms/amaxsum.py (424 LoC).  The reference
reuses MaxSum's math but sends messages on every receipt with no cycle
barrier (amaxsum.py:108-251).  In the compiled engine the faithful model
(SURVEY.md §7 hard part 3) is *stochastic activation*: each cycle an
independent random subset of edges refreshes its messages while the rest
keep their previous values — reproducing the reference's property that
updates propagate asynchronously through a loopy graph, which often damps
oscillations that bite synchronous MaxSum.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..dcop.dcop import DCOP
from ..graphs.arrays import FactorGraphArrays
from . import AlgoParameterDef
from .maxsum import HEADER_SIZE, UNIT_SIZE, MaxSumSolver
from .maxsum import communication_load, computation_memory  # noqa: F401

GRAPH_TYPE = "factor_graph"

algo_params = [
    AlgoParameterDef("damping", "float", None, 0.5),
    AlgoParameterDef("damping_nodes", "str",
                     ["vars", "factors", "both", "none"], "vars"),
    AlgoParameterDef("stability", "float", None, 0.1),
    AlgoParameterDef("noise", "float", None, 0.0),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("activation", "float", None, 0.7),
    # mixed-precision policy (ops/precision.py), inherited from the
    # MaxSum solver family: bf16 cost planes, f32 accumulation
    AlgoParameterDef("precision", "str", ["f32", "bf16", "auto"], None),
]


class AMaxSumSolver(MaxSumSolver):
    #: stochastic edge activation draws from the jax PRNG stream —
    #: a numpy mirror could not reproduce it, so no host engine
    host_path = False

    def __init__(self, arrays: FactorGraphArrays, activation: float = 0.7,
                 **kwargs):
        if float(kwargs.get("decimation_p", 0) or 0) != 0:
            # loud rejection, never a silent downgrade: the stochastic
            # activation mask below re-admits PRE-freeze messages on
            # non-activated edges, which would quietly undo the freeze
            # clamp decimation depends on
            raise ValueError(
                "amaxsum does not support decimation: stochastic edge "
                "activation re-admits pre-freeze messages, undoing the "
                "frozen-variable clamp; use maxsum for decimated runs")
        super().__init__(arrays, **kwargs)
        self.activation = float(activation)

    def step(self, s):
        key, k_act_q, k_act_r = jax.random.split(s["key"], 3)
        s2 = dict(s)
        s2["key"] = key
        out = super().step(s2)
        # only a random subset of edges refreshes its messages this cycle
        act_q = jax.random.uniform(
            k_act_q, (self.E, 1)) < self.activation
        act_r = jax.random.uniform(
            k_act_r, (self.E, 1)) < self.activation
        out["q"] = jnp.where(act_q, out["q"], s["q"])
        out["r"] = jnp.where(act_r, out["r"], s["r"])
        return out


def build_solver(dcop: DCOP, params: Optional[Dict] = None,
                 variables=None, constraints=None) -> AMaxSumSolver:
    from ._mp import engine_params

    params = engine_params(params)
    arrays = FactorGraphArrays.build(dcop, variables, constraints)
    return AMaxSumSolver(arrays, **params)


# ---------------------------------------------------------------------
# Message-passing backend: A-MaxSum running ON the agent fabric
# (reference: amaxsum.py:108-424).  Truly asynchronous: every node
# recomputes and re-sends on receipt, no round barrier; messages are
# suppressed once they stop changing (approx-match + SAME_COUNT,
# reference amaxsum.py:186-229).
# ---------------------------------------------------------------------

import numpy as _np

from ..infrastructure.communication import MSG_ALGO
from ..infrastructure.computations import (
    DcopComputation, VariableComputation, message_type, register)
from ._mp import mp_rng, seed_param, sign_for_mode
from .maxsum import SAME_COUNT

algo_params = algo_params + [
    AlgoParameterDef("start_messages", "str",
                     ["leafs", "leafs_vars", "vars", "all"],
                     "leafs_vars"),
    seed_param(),
]

#: costs aligned to the target variable's domain order (list, not dict:
#: JSON stringifies non-string keys across processes)
AMaxSumCostsMessage = message_type("amaxsum_costs", ["costs"])


def _approx_match(a, b, stability) -> bool:
    if b is None:
        return False
    return bool(_np.max(_np.abs(a - b)) <= stability)


class AMaxSumVariableMpComputation(VariableComputation):
    """Variable node of asynchronous MaxSum (reference:
    amaxsum.py:253-424).  Terminates once its outgoing messages and
    selection have been stable SAME_COUNT receipts in a row (the
    reference never self-terminates and leans on the orchestrator
    timeout)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.damping = float(params.get("damping", 0.5))
        self.damping_nodes = params.get("damping_nodes", "vars")
        self.stability = float(params.get("stability", 0.1))
        self.stop_cycle = int(params.get("stop_cycle", 0) or 0)
        self.start_messages = params.get("start_messages", "leafs_vars")
        self.factor_names = list(comp_def.node.neighbors)
        sign = sign_for_mode(self.mode)
        self._own_costs = _np.array(
            [sign * self.variable.cost_for_val(v)
             for v in self.variable.domain.values])
        self._r: Dict[str, _np.ndarray] = {}
        self._q_sent: Dict[str, _np.ndarray] = {}
        self._same_sent: Dict[str, int] = {}
        self._stable = 0
        self._last_receipt = 0.0
        self._quiet_handle = None

    def on_start(self):
        import time as _time

        if not self.factor_names:
            idx = int(_np.argmin(self._own_costs))
            sign = sign_for_mode(self.mode)
            self.value_selection(self.variable.domain.values[idx],
                                 sign * float(self._own_costs[idx]))
            self.finished()
            return
        self._select()
        if self.start_messages in ("leafs_vars", "vars", "all"):
            self._send_all()
        # quiescence detector: asynchronous message suppression can
        # leave the whole graph silent before the stability counter
        # trips; a silent second with a value selected = converged
        self._last_receipt = _time.perf_counter()
        self._quiet_handle = self.add_periodic_action(
            0.5, self._check_quiescence)

    def _check_quiescence(self):
        import time as _time

        # only after real message exchange: a slow-starting neighborhood
        # must not be mistaken for a converged one (with no traffic at
        # all the orchestrator timeout applies, as in the reference)
        if self._r and self.current_value is not None and \
                _time.perf_counter() - self._last_receipt > 2.0:
            self.finished()

    def on_stop(self):
        if self._quiet_handle is not None:
            self.remove_periodic_action(self._quiet_handle)
            self._quiet_handle = None

    def _belief(self):
        belief = self._own_costs.copy()
        for r in self._r.values():
            belief = belief + r
        return belief

    def _select(self):
        belief = self._belief()
        idx = int(_np.argmin(belief))
        sign = sign_for_mode(self.mode)
        prev = self.current_value
        self.value_selection(self.variable.domain.values[idx],
                             sign * float(belief[idx]))
        return prev == self.current_value

    def _send_all(self):
        belief = self._belief()
        for f in self.factor_names:
            q = belief - self._r.get(f, 0.0)
            q = q - q.mean()
            prev = self._q_sent.get(f)
            if prev is not None and \
                    self.damping_nodes in ("vars", "both") and \
                    0 < self.damping < 1:
                q = self.damping * prev + (1 - self.damping) * q
            if _approx_match(q, prev, self.stability):
                count = self._same_sent.get(f, 0)
                if count >= SAME_COUNT:
                    continue  # suppressed: stable enough, stop chatting
                self._same_sent[f] = count + 1
            else:
                self._same_sent[f] = 0
            self._q_sent[f] = q
            self.post_msg(f, AMaxSumCostsMessage(q.tolist()), MSG_ALGO)

    @register("amaxsum_costs")
    def _on_costs(self, sender, msg, t):
        import time as _time

        self._last_receipt = _time.perf_counter()
        self._r[sender] = _np.asarray(msg.costs, dtype=float)
        self.new_cycle()
        stable_sel = self._select()
        self._send_all()
        # all outgoing suppressed + selection unchanged = converged
        all_suppressed = all(
            self._same_sent.get(f, 0) >= SAME_COUNT
            for f in self.factor_names)
        self._stable = self._stable + 1 \
            if (stable_sel and all_suppressed) else 0
        if self._stable >= SAME_COUNT or (
                self.stop_cycle
                and self._cycle_count >= self.stop_cycle):
            self.finished()


class AMaxSumFactorMpComputation(DcopComputation):
    """Factor node of asynchronous MaxSum (reference: amaxsum.py:108-251).
    Recomputes marginals on every receipt once all variables reported;
    the cost hypercube lives as one ndarray and each marginal is a
    broadcast-add + axis-min (the reference brute-forces assignments in
    Python loops)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.name, comp_def)
        params = comp_def.algo.params
        self.mode = comp_def.algo.mode
        self.damping = float(params.get("damping", 0.5))
        self.damping_nodes = params.get("damping_nodes", "vars")
        self.stability = float(params.get("stability", 0.1))
        self.start_messages = params.get("start_messages", "leafs_vars")
        factor = comp_def.node.factor
        self.factor = factor
        self.variables = list(factor.dimensions)
        self._load_cube()
        self._q: Dict[str, _np.ndarray] = {}
        self._r_sent: Dict[str, _np.ndarray] = {}
        self._same_sent: Dict[str, int] = {}

    def _load_cube(self):
        sign = sign_for_mode(self.mode)
        self._cube = sign * self.factor.to_matrix().matrix.astype(float)
        self._axis = {v.name: i
                      for i, v in enumerate(self.variables)}

    def on_start(self):
        is_leaf = len(self.variables) == 1
        if (is_leaf and self.start_messages in ("leafs", "leafs_vars")) \
                or self.start_messages == "all":
            self._send_marginals()

    def _send_marginals(self, exclude: Optional[str] = None):
        n = self._cube.ndim
        total = self._cube
        for name, q in self._q.items():
            axis = self._axis.get(name)
            if axis is None:
                continue
            shape = [1] * n
            shape[axis] = q.shape[0]
            total = total + q.reshape(shape)
        for v in self.variables:
            if exclude is not None and v.name == exclude:
                continue
            axis = self._axis[v.name]
            other_axes = tuple(i for i in range(n) if i != axis)
            marg = total.min(axis=other_axes) if other_axes \
                else total.copy()
            q_v = self._q.get(v.name)
            if q_v is not None:
                marg = marg - q_v
            prev = self._r_sent.get(v.name)
            if prev is not None and \
                    self.damping_nodes in ("factors", "both") and \
                    0 < self.damping < 1:
                marg = self.damping * prev + (1 - self.damping) * marg
            if _approx_match(marg, prev, self.stability):
                count = self._same_sent.get(v.name, 0)
                if count >= SAME_COUNT:
                    continue
                self._same_sent[v.name] = count + 1
            else:
                self._same_sent[v.name] = 0
            self._r_sent[v.name] = marg
            self.post_msg(v.name, AMaxSumCostsMessage(marg.tolist()),
                          MSG_ALGO)

    @register("amaxsum_costs")
    def _on_costs(self, sender, msg, t):
        self._q[sender] = _np.asarray(msg.costs, dtype=float)
        self.new_cycle()
        # wait for the full view before the first send, then re-send to
        # everyone but the sender (reference: amaxsum.py:186-229)
        if len(self._q) == len(self.variables):
            self._send_marginals(exclude=sender
                                 if len(self.variables) > 1 else None)


def build_computation(comp_def):
    """Agent-fabric computation for one factor-graph node
    (reference: amaxsum.py:89-95)."""
    if hasattr(comp_def.node, "variable"):
        return AMaxSumVariableMpComputation(comp_def)
    return AMaxSumFactorMpComputation(comp_def)

"""Per-rung offline autotuning.

The knob pile the perf rounds accumulated — step layout, precision
policy, engine chunk size, warm-budget schedule, n-ary cell ceiling,
branch-and-bound pruning — is rung-dependent: fused beats edge-major
1.76x on the warm mesh ladder, bf16 admits 2x rungs per byte budget,
bnb prunes 87.5% on PEAV and 12.2% on SECP.  Because the program
universe is bounded by the pow2 rung ladder (``parallel/bucketing``),
an offline search over (rung × knob grid) is tractable and its
results are durable artifacts:

* :mod:`space` — the declarative knob space with per-rung validity
  predicates mirroring the existing loud-rejection rules;
* :mod:`autotune` — the measurement loop (warmup + best-of-N
  medians through the real runners, successive-halving pruning)
  behind ``pydcop autotune``;
* :mod:`store` — the :class:`TunedConfigStore`: JSON sidecars beside
  the executable cache, keyed by rung-signature × algorithm, carrying
  the winning config and the measured ms/cycle table, fingerprinted
  like checkpoint manifests (drift refuses the sidecar with a
  structured error) and consumed by ``runner_for_rung`` / ``solve`` /
  ``batch --fuse-hetero`` / the serve dispatcher whenever a knob was
  not pinned explicitly.  Explicit flags always win; the resolved
  source of every knob (``explicit``/``tuned``/``default``) is echoed
  in result blocks and telemetry (schema minor 9).
"""

from .space import (CONTEXTS, KNOBS, TUNING_SOURCES, config_label,
                    enumerate_configs, invalid_reason, knob_domain)
from .store import (STORE_VERSION, TunedConfigStore, TuningError,
                    check_tuning_fingerprint, default_store,
                    resolve_knobs, tuning_fingerprint)

__all__ = [
    "CONTEXTS", "KNOBS", "TUNING_SOURCES", "config_label",
    "enumerate_configs", "invalid_reason", "knob_domain",
    "STORE_VERSION", "TunedConfigStore", "TuningError",
    "check_tuning_fingerprint", "default_store", "resolve_knobs",
    "tuning_fingerprint",
]

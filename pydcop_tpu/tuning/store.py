"""The persisted tuned-config store.

One JSON sidecar per (algo × rung-signature), living beside the
executable cache (``default_cache_dir("tuned")`` next to
``"executables"``), carrying the measured-fastest config, the full
ms/cycle table for every candidate the search ran, and the
environment fingerprint it was measured under.  Three policies are
deliberately inherited, not re-invented:

* **fingerprinting** follows the checkpoint manifests
  (``robustness/checkpoint.py``): a sidecar measured under a
  different jax version / backend / machine arch / device count is
  REFUSED with a structured :class:`TuningError` naming every drifted
  field — timings from another environment are not merely stale, they
  can invert (the bnb prune rate flips between PEAV and SECP; fused
  wins on mesh and loses on host CPU).  Unlike the executable cache
  (which folds the fingerprint into the key so a drifted environment
  just misses), the sidecar is keyed WITHOUT the fingerprint: a
  drifted environment *finds* the file and gets the loud refusal,
  so the operator learns their tuning is void instead of silently
  running defaults forever.
* **corruption** reuses ``engine/_cache.quarantine_file``: a torn or
  bit-rotted sidecar moves aside to ``*.corrupt``, counts, and reads
  as a miss — never a crash, never re-read forever.
* **writes** go through ``robustness/checkpoint.atomic_write``
  (write-temp → fsync → rename): a kill mid-store leaves the previous
  sidecar intact.

Consumption (:func:`resolve_knobs`) enforces the precedence contract:
``explicit`` (caller pinned the knob) beats ``tuned`` (store supplied
it) beats ``default`` (runner's own default).  The resolved source of
every applicable knob is returned beside the resolved params so every
dispatch path — solve result blocks, batch records, serve dispatch
records — can echo exactly where each knob came from.
"""

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..engine._cache import (cache_disabled, default_cache_dir,
                             quarantine_file)
from .space import KNOBS, invalid_reason, knob_domain

logger = logging.getLogger(__name__)

#: bump on any incompatible sidecar layout change; readers refuse
#: newer-versioned sidecars loudly instead of misparsing them
STORE_VERSION = 1

#: sidecar file suffix — distinguishable from the ``.jaxexe`` entries
#: sharing the cache root
SIDECAR_SUFFIX = ".tuned.json"


class TuningError(ValueError):
    """A sidecar that must NOT be consumed: measured under a drifted
    environment fingerprint, or written by a newer store format.
    ``kind`` classifies (``fingerprint`` | ``store``), ``details``
    names every mismatched field with the (saved, current) pair —
    the same structured-refusal shape as ``CheckpointError``."""

    def __init__(self, msg: str, kind: str = "fingerprint",
                 **details):
        super().__init__(msg)
        self.kind = str(kind)
        self.details = dict(details)


def tuning_fingerprint() -> Dict[str, Any]:
    """The environment identity a measurement is only valid under —
    the same four fields ``ExecutableCache._fingerprint`` keys on,
    as a named dict so a mismatch can say WHICH field drifted."""
    import platform

    import jax

    return {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "arch": platform.machine(),
        "devices": jax.device_count(),
    }


def check_tuning_fingerprint(saved: Dict[str, Any],
                             current: Dict[str, Any]):
    """Field-by-field comparison; raises :class:`TuningError` naming
    EVERY drifted field (the whole diff at once, like the checkpoint
    manifests — an operator re-tuning wants to know if it was a jax
    upgrade, a backend switch, or both)."""
    mismatched = {}
    for field in sorted(set(saved) | set(current)):
        if saved.get(field) != current.get(field):
            mismatched[field] = (saved.get(field), current.get(field))
    if mismatched:
        diff = ", ".join(
            f"{k}: tuned={s!r} current={c!r}"
            for k, (s, c) in sorted(mismatched.items()))
        raise TuningError(
            f"tuned-config fingerprint mismatch ({diff}); refusing "
            f"the sidecar — timings from another environment can "
            f"invert, re-run `pydcop autotune` on this "
            f"{'/'.join(sorted(mismatched))}",
            kind="fingerprint", **mismatched)


def _norm_sig(sig) -> Tuple:
    """Rung signatures roundtrip through JSON (sidecars, telemetry
    records) as nested lists; normalize to nested tuples so every
    spelling of one rung keys the same sidecar."""
    if isinstance(sig, (list, tuple)):
        return tuple(_norm_sig(s) for s in sig)
    return sig


class TunedConfigStore:
    """Disk-persisted winning configs, one sidecar per
    (algo × rung-signature).

    Like the executable cache it sits beside: opt-out via
    ``PYDCOP_TPU_NO_CACHE=1`` or ``enabled=False``, relocate via
    ``PYDCOP_TPU_CACHE_DIR``, unavailable directories degrade to
    warn-once + all-miss, and ``stats`` feeds the ops plane
    (``pydcop_tuning_hits_total`` / ``..._misses_total``).
    """

    def __init__(self, path: Optional[str] = None,
                 enabled: Optional[bool] = None):
        self.path = path or default_cache_dir("tuned")
        if enabled is None:
            enabled = not cache_disabled()
        self.enabled = bool(enabled)
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "stores": 0, "corrupt": 0,
            "refused": 0}
        self._warned = False
        if self.enabled:
            try:
                os.makedirs(self.path, exist_ok=True)
            except OSError as e:
                self.enabled = False
                logger.warning(
                    "tuned-config store unavailable at %s (%s); "
                    "dispatch runs defaults", self.path, e)

    # ------------------------------------------------------------ keys

    def _file_for(self, algo: str, rung_signature) -> str:
        digest = hashlib.sha256(
            repr((str(algo), _norm_sig(rung_signature))).encode()
        ).hexdigest()
        return os.path.join(self.path, digest + SIDECAR_SUFFIX)

    # ------------------------------------------------------------- i/o

    def load(self, algo: str, rung_signature) -> Optional[Dict]:
        """The sidecar entry for (algo, rung), or None on a miss.

        A malformed sidecar is quarantined (``quarantine_file``) and
        reads as a miss.  A WELL-FORMED sidecar whose fingerprint or
        store version doesn't match this process raises
        :class:`TuningError` — the refusal is the point; callers that
        must survive it (dispatch) catch it in :func:`resolve_knobs`.
        """
        if not self.enabled:
            return None
        path = self._file_for(algo, rung_signature)
        try:
            with open(path) as f:
                entry = json.load(f)
            if not isinstance(entry, dict) or \
                    not isinstance(entry.get("best"), dict):
                raise ValueError("sidecar is not a tuned-config entry")
        except FileNotFoundError:
            self.stats["misses"] += 1
            return None
        except Exception as e:
            self.stats["corrupt"] += 1
            self.stats["misses"] += 1
            self._warn_once(
                f"corrupt tuned sidecar {path}: {e} "
                f"({quarantine_file(path)})")
            return None
        version = entry.get("store_version")
        if version != STORE_VERSION:
            self.stats["refused"] += 1
            raise TuningError(
                f"tuned sidecar {os.path.basename(path)} has store "
                f"version {version!r}, this build reads "
                f"{STORE_VERSION}; re-run `pydcop autotune`",
                kind="store",
                store_version=(version, STORE_VERSION))
        try:
            check_tuning_fingerprint(entry.get("fingerprint") or {},
                                     tuning_fingerprint())
        except TuningError:
            self.stats["refused"] += 1
            raise
        self.stats["hits"] += 1
        return entry

    def store(self, algo: str, rung_signature, best: Dict,
              table: List[Dict],
              rung_label: Optional[str] = None) -> str:
        """Persist the winning ``best`` config and the full measured
        ``table`` (one row per candidate: label, config, ms/cycle
        stages) for (algo, rung).  Atomic; returns the sidecar path.
        """
        from ..robustness.checkpoint import atomic_write

        entry = {
            "store_version": STORE_VERSION,
            "fingerprint": tuning_fingerprint(),
            "algo": str(algo),
            "rung": _to_jsonable(_norm_sig(rung_signature)),
            "rung_label": rung_label,
            "best": dict(best),
            "table": list(table),
            "created_at": time.time(),
        }
        path = self._file_for(algo, rung_signature)
        atomic_write(path, json.dumps(entry, indent=1, sort_keys=True))
        self.stats["stores"] += 1
        return path

    # ------------------------------------------------------ surfacing

    def entries(self) -> List[Dict]:
        """Every readable sidecar in the store directory (skipping
        corrupt/foreign files silently — this is the ops-plane
        inventory scan, not a dispatch path)."""
        if not self.enabled:
            return []
        out = []
        try:
            names = sorted(os.listdir(self.path))
        except OSError:
            return []
        for name in names:
            if not name.endswith(SIDECAR_SUFFIX):
                continue
            try:
                with open(os.path.join(self.path, name)) as f:
                    entry = json.load(f)
                if isinstance(entry, dict) and \
                        isinstance(entry.get("best"), dict):
                    out.append(entry)
            except Exception:
                continue
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The serve-surfacing view: stats plus a compact per-sidecar
        inventory (algo, rung label, winning config, age) — what
        heartbeat records and ``serve-status`` render."""
        now = time.time()
        return {
            "path": self.path,
            "enabled": self.enabled,
            "stats": dict(self.stats),
            "entries": [
                {
                    "algo": e.get("algo"),
                    "rung_label": e.get("rung_label"),
                    "best": e.get("best"),
                    "age_s": round(
                        max(0.0, now - float(e.get("created_at") or
                                             now)), 3),
                }
                for e in self.entries()
            ],
        }

    def _warn_once(self, msg: str):
        if not self._warned:
            self._warned = True
            logger.warning(
                "tuned-config store degraded (%s); affected rungs "
                "run defaults", msg)


def _to_jsonable(value):
    """Nested tuples → nested lists for JSON (rung signatures)."""
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    return value


def default_store(enabled: Optional[bool] = None) -> TunedConfigStore:
    """The store at the canonical location beside the executable
    cache — what every dispatch path constructs unless pointed
    elsewhere."""
    return TunedConfigStore(enabled=enabled)


def resolve_knobs(algo: str, params: Dict, rung_signature,
                  store: Optional[TunedConfigStore],
                  context: str = "batched"
                  ) -> Tuple[Dict, Dict[str, str]]:
    """Fold tuned knobs into ``params`` under the precedence contract
    **explicit > tuned > default**, returning
    ``(resolved_params, sources)``.

    ``sources`` maps every knob applicable to (algo, context) to how
    its value was decided: ``explicit`` (the caller pinned it — never
    overridden), ``tuned`` (adopted from the sidecar), ``default``
    (no sidecar, no pin, or the tuned value is invalid for this
    dispatch surface).  A fingerprint/store-version refusal from the
    sidecar is warned once and degrades to all-default — dispatch
    must not die because the daemon host got a jax upgrade — but the
    refusal stays structured in the store's ``refused`` counter.
    """
    params = dict(params or {})
    sources: Dict[str, str] = {}
    for knob in KNOBS:
        if knob in params:
            sources[knob] = "explicit"
        elif knob_domain(knob, algo, context):
            sources[knob] = "default"
    if store is None or rung_signature is None:
        return params, sources
    try:
        entry = store.load(algo, rung_signature)
    except TuningError as e:
        store._warn_once(str(e))
        return params, sources
    if not entry:
        return params, sources
    for knob in KNOBS:
        if knob not in entry["best"] or knob in params:
            continue
        value = entry["best"][knob]
        if invalid_reason(algo, {knob: value}, context) is not None:
            # tuned under another context (e.g. an engine-only knob
            # consulted by a batched dispatch): not an error, the
            # knob simply doesn't exist here
            continue
        params[knob] = value
        sources[knob] = "tuned"
    return params, sources

"""The offline measurement loop behind ``pydcop autotune``.

The program universe is bounded: the pow2 rung ladder
(``parallel/bucketing``) quantizes every instance shape into a small
set of compiled programs, so an offline search over (rung × knob
grid) is tractable and its winners are durable artifacts (PGMax makes
the same observation for its bounded factor-shape universe).  The
loop here:

1. **Rung acquisition** — three spellings of "which rungs matter":
   explicit labels (:func:`parse_rung_label`, the exact inverse of
   ``bucketing.rung_label``), a corpus of DCOP files grouped by their
   ``home_rung`` (:func:`rungs_from_corpus` — the same
   build-arrays → profile → rung path the fused campaign runner
   walks), or a serve telemetry JSONL replayed for the rungs the
   daemon actually dispatched (:func:`rungs_from_telemetry`).
2. **Measurement** — every candidate runs through the REAL dispatch
   path (``runner_for_rung`` + optional ``ExecutableCache``), so
   compile cost is paid once per (rung, config) and the measured
   program is byte-identical to what production dispatch will run.
   Warmup run first (compiles), then best-of-N timed repeats;
   ms/cycle divides by the cycles the batch actually executed.
3. **Successive halving** — the full grid runs one SHORT stage
   (quarter cycle budget, single repeat), the bottom half is pruned,
   survivors re-measure at full budget.  The default config is never
   pruned: the final argmin must always contain the default's
   full-budget measurement, which is what makes the never-slower
   contract an arithmetic identity rather than a hope.

The winner and the complete measured table persist through
:class:`~pydcop_tpu.tuning.store.TunedConfigStore` — dispatch reads
them back via ``resolve_knobs``.
"""

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .space import config_label, enumerate_configs, invalid_reason
from .store import TunedConfigStore

logger = logging.getLogger(__name__)

#: algo family -> instance-array kind, mirroring
#: ``commands/batch.FUSABLE_ALGOS`` for the batched runner families
ALGO_KIND = {"maxsum": "factor", "dsa": "hyper", "mgm": "hyper"}


# ------------------------------------------------------ rung parsing


def parse_rung_label(label: str) -> Tuple:
    """The inverse of ``bucketing.rung_label``:
    ``factor:d3:v17:a2x32`` (optionally ``:pN`` for hyper pairs) back
    into the ``Rung.signature`` tuple.  Malformed labels die loudly
    with the expected grammar — an autotune run over a typo'd rung
    would persist a sidecar no dispatch ever reads."""
    parts = [p for p in str(label).split(":") if p]
    try:
        kind = parts[0]
        if kind not in ("factor", "hyper"):
            raise ValueError(f"kind {kind!r}")
        if not (parts[1].startswith("d") and parts[2].startswith("v")):
            raise ValueError("missing d/v fields")
        max_domain = int(parts[1][1:])
        n_vars = int(parts[2][1:])
        slots = []
        n_pairs = 0
        for part in parts[3:]:
            if part.startswith("a") and "x" in part:
                arity, count = part[1:].split("x")
                slots.append((int(arity), int(count)))
            elif part.startswith("p"):
                n_pairs = int(part[1:])
            else:
                raise ValueError(f"field {part!r}")
        return (kind, max_domain, n_vars, tuple(sorted(slots)),
                n_pairs)
    except (IndexError, ValueError) as e:
        raise ValueError(
            f"rung label {label!r} does not parse ({e}); expected "
            f"the rung_label grammar, e.g. factor:d3:v17:a2x32 or "
            f"hyper:d3:v33:a2x64:p128")


def _rung_from_signature(signature):
    from ..parallel.bucketing import Rung

    kind, max_domain, n_vars, slots, n_pairs = signature
    return Rung(kind=str(kind), max_domain=int(max_domain),
                n_vars=int(n_vars),
                bucket_slots={int(a): int(c) for a, c in slots},
                n_pairs=int(n_pairs))


# ------------------------------------------- synthetic rung instances


def synthetic_instances(signature, algo: str, batch: int = 4,
                        seed: int = 0) -> List:
    """A batch of synthetic instances padded to ``signature``'s shape
    — what label/telemetry-mode autotune measures on when no corpus
    supplies real instances.  Coloring-family generators sized just
    under the rung capacity, one seed per batch row, padded through
    the SAME ``Rung.pad`` path the fused campaign uses (``pad_to``
    emits the canonical layout the hetero runners require)."""
    from ..generators.fast import (coloring_factor_arrays,
                                   coloring_hypergraph_arrays,
                                   nary_factor_arrays)
    from ..parallel.bucketing import ShapeProfile

    kind = ALGO_KIND.get(algo)
    if kind is None:
        raise ValueError(
            f"{algo} has no batched runner to autotune (families: "
            f"{', '.join(sorted(ALGO_KIND))})")
    rung = _rung_from_signature(signature)
    if rung.kind != kind:
        raise ValueError(
            f"rung {signature} is {rung.kind}-kind but {algo} "
            f"runs on {kind} instances")
    # the rung's own sink row means real instances stay strictly
    # under the padded variable count
    nv = max(2, rung.n_vars - 1)
    max_edges = nv * (nv - 1) // 2
    slots = dict(rung.bucket_slots)
    out = []
    for i in range(int(batch)):
        if kind == "hyper":
            n_edges = max(1, min(slots.get(2, 1),
                                 rung.n_pairs // 2 or 1, max_edges))
            arrays = coloring_hypergraph_arrays(
                nv, n_edges, n_colors=rung.max_domain, seed=seed + i)
        elif set(slots) <= {2}:
            n_edges = max(1, min(slots.get(2, 1), max_edges))
            arrays = coloring_factor_arrays(
                nv, n_edges, n_colors=rung.max_domain, seed=seed + i)
        else:
            arrays = nary_factor_arrays(
                nv, {a: max(1, c) for a, c in slots.items()},
                n_values=rung.max_domain, seed=seed + i)
        profile = ShapeProfile.of(arrays)
        if not rung.covers(profile):
            raise ValueError(
                f"synthetic instance {profile} escaped rung "
                f"{signature}; cannot measure this rung without a "
                f"corpus instance that fits it")
        out.append(rung.pad(arrays))
    return out


# ------------------------------------------------------- measurement


def measure_ms_per_cycle(algo: str, instances, params: Dict,
                         rung_signature, cycles: int = 32,
                         repeats: int = 3, exec_cache=None) -> float:
    """Best-of-``repeats`` ms/cycle of one (rung, config) through the
    real batched dispatch path.  The warmup run pays the compile; the
    timed runs measure exactly the program production dispatch reuses
    (same ``runner_for_rung`` cache key, same executable)."""
    from ..parallel.batch import runner_for_rung

    runner = runner_for_rung(algo, instances, dict(params),
                             rung_signature=rung_signature,
                             exec_cache=exec_cache)
    runner.run(seed=0, max_cycles=int(cycles))          # warmup
    best = float("inf")
    for _ in range(max(1, int(repeats))):
        t0 = time.perf_counter()
        _sel, cyc, _fin = runner.run(seed=0, max_cycles=int(cycles))
        elapsed = time.perf_counter() - t0
        executed = float(np.mean(np.asarray(cyc)))
        best = min(best, elapsed * 1e3 / max(executed, 1.0))
    return best


def autotune_rung(algo: str, instances, rung_signature,
                  cycles: int = 32, repeats: int = 3,
                  pinned: Optional[Dict] = None,
                  context: str = "batched", exec_cache=None,
                  progress=None) -> Dict:
    """Search the valid candidate grid for one rung and return the
    result block: winning config, full measured table, halving
    stats.  ``pinned`` knobs are excluded from the search (explicit
    always wins at dispatch, so their alternatives are unreachable).
    """
    pinned = dict(pinned or {})
    candidates = enumerate_configs(algo, context, pinned=pinned)
    say = progress or (lambda msg: None)

    def run(config, budget, reps):
        return measure_ms_per_cycle(
            algo, instances, dict(pinned, **config), rung_signature,
            cycles=budget, repeats=reps, exec_cache=exec_cache)

    # stage 1: the whole grid at a quarter budget, one repeat
    short = max(4, int(cycles) // 4)
    stage1 = []
    for config in candidates:
        ms = run(config, short, 1)
        stage1.append((ms, config))
        say(f"  stage1 {config_label(config)}: {ms:.3f} ms/cycle")
    # keep the top half; the default ({}) is NEVER pruned — the final
    # argmin must contain its full-budget measurement (never-slower)
    keep = max(1, (len(stage1) + 1) // 2)
    ranked = sorted(stage1, key=lambda t: t[0])
    survivors = [c for _ms, c in ranked[:keep]]
    if {} not in survivors:
        survivors.insert(0, {})
    stage1_ms = {config_label(c): ms for ms, c in stage1}

    # stage 2: survivors at full budget, best-of-N
    table = []
    for config in candidates:
        label = config_label(config)
        row = {"label": label, "config": config,
               "stage1_ms_per_cycle": round(stage1_ms[label], 4),
               "pruned": config not in survivors,
               "ms_per_cycle": None}
        if config in survivors:
            ms = run(config, int(cycles), repeats)
            row["ms_per_cycle"] = round(ms, 4)
            say(f"  full   {label}: {ms:.3f} ms/cycle")
        table.append(row)
    finals = [r for r in table if r["ms_per_cycle"] is not None]
    best_row = min(finals, key=lambda r: r["ms_per_cycle"])
    default_row = next(r for r in finals if not r["config"])
    return {
        "algo": algo,
        "context": context,
        "best": dict(best_row["config"]),
        "best_label": best_row["label"],
        "best_ms_per_cycle": best_row["ms_per_cycle"],
        "default_ms_per_cycle": default_row["ms_per_cycle"],
        "speedup_vs_default": round(
            default_row["ms_per_cycle"]
            / max(best_row["ms_per_cycle"], 1e-9), 3),
        "candidates": len(candidates),
        "pruned": sum(r["pruned"] for r in table),
        "cycles": int(cycles),
        "repeats": int(repeats),
        "table": table,
    }


# -------------------------------------------------- rung acquisition


def rungs_from_corpus(paths: Sequence[str], algo: str,
                      reserve=None) -> List[Tuple]:
    """(rung, padded member instances) per distinct home rung of a
    DCOP-file corpus — the exact build-arrays → profile → home-rung
    walk the fused campaign and serve admission use, so autotune
    measures the rungs those paths will dispatch."""
    from ..dcop.dcop import filter_dcop
    from ..dcop.yamldcop import load_dcop_from_file
    from ..graphs.arrays import FactorGraphArrays, HypergraphArrays
    from ..parallel.bucketing import ShapeProfile, home_rung

    kind = ALGO_KIND.get(algo)
    if kind is None:
        raise ValueError(
            f"{algo} has no batched runner to autotune (families: "
            f"{', '.join(sorted(ALGO_KIND))})")
    arrays_list = []
    for path in paths:
        dcop = load_dcop_from_file(path)
        if kind == "factor":
            arrays_list.append(
                FactorGraphArrays.build(dcop, arity_sorted=True))
        else:
            arrays_list.append(
                HypergraphArrays.build(filter_dcop(dcop)))
    by_sig: Dict[Tuple, Tuple] = {}
    for arrays in arrays_list:
        rung = home_rung(ShapeProfile.of(arrays), reserve=reserve)
        sig = rung.signature
        if sig not in by_sig:
            by_sig[sig] = (rung, [])
        by_sig[sig][1].append(rung.pad(arrays))
    return [(rung, members) for rung, members in by_sig.values()]


def rungs_from_telemetry(path: str,
                         algo: Optional[str] = None) -> List[Tuple]:
    """(algo, rung signature) pairs replayed from a serve telemetry
    JSONL — the rungs (and algorithms) a daemon actually dispatched,
    read from the ``rung`` field its dispatch/summary records carry.
    ``algo`` filters to one family; unparseable lines are skipped
    (telemetry files interleave many record kinds), but a file
    yielding NO rungs is an error, not an empty tune."""
    import json

    seen, out = set(), []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            rung = rec.get("rung")
            rec_algo = rec.get("algo")
            if not rung or not rec_algo:
                continue
            if algo is not None and rec_algo != algo:
                continue
            try:
                sig = _norm(rung)
                if len(sig) != 5:
                    continue
            except TypeError:
                continue
            key = (rec_algo, sig)
            if key not in seen:
                seen.add(key)
                out.append(key)
    if not out:
        raise ValueError(
            f"{path} carries no dispatch records with rung "
            f"signatures"
            + (f" for algo {algo}" if algo else "")
            + "; is it a serve telemetry file?")
    return out


def _norm(sig):
    if isinstance(sig, (list, tuple)):
        return tuple(_norm(s) for s in sig)
    return sig


# ------------------------------------------------------------ driver


def autotune(rung_sets: List[Tuple], cycles: int = 32,
             repeats: int = 3, pinned: Optional[Dict] = None,
             context: str = "batched",
             store: Optional[TunedConfigStore] = None,
             exec_cache=None, progress=None) -> List[Dict]:
    """Tune every (algo, rung, instances) triple in ``rung_sets`` and
    persist each winner (plus its full measured table) to ``store``.
    Invalid pins die up front — one loud error beats a whole
    measurement campaign of unreachable configs."""
    from ..parallel.bucketing import rung_label

    say = progress or (lambda msg: None)
    pinned = dict(pinned or {})
    results = []
    for algo, rung_signature, instances in rung_sets:
        reason = invalid_reason(algo, pinned, context)
        if reason is not None:
            raise ValueError(
                f"pinned params invalid for {algo}/{context}: "
                f"{reason}")
        label = rung_label(rung_signature)
        say(f"[autotune] {algo} {label} "
            f"(batch {len(instances)}, {cycles} cycles)")
        result = autotune_rung(
            algo, instances, rung_signature, cycles=cycles,
            repeats=repeats, pinned=pinned, context=context,
            exec_cache=exec_cache, progress=progress)
        result["rung"] = list(_norm(rung_signature))
        result["rung_label"] = label
        result["batch"] = len(instances)
        if store is not None:
            result["sidecar"] = store.store(
                algo, rung_signature, result["best"], result["table"],
                rung_label=label)
            say(f"[autotune] {algo} {label} -> "
                f"{result['best_label']} "
                f"({result['best_ms_per_cycle']} ms/cycle, "
                f"default {result['default_ms_per_cycle']}) "
                f"persisted")
        results.append(result)
    return results

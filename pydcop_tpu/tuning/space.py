"""The declarative knob space the autotuner searches.

One table declares, per knob: which dispatch *contexts* it exists in
(``batched`` — the vmapped campaign runners behind ``runner_for_rung``;
``engine`` — the single-instance sync engine; ``sharded`` — the device
mesh; ``warm`` — the dynamic warm engine) and which algorithm families
accept it.  The validity predicate :func:`invalid_reason` mirrors the
LOUD-rejection rules the runtime already enforces — it never invents a
new rule, so a config the space admits is a config the runners accept:

* batched runners reject ``bnb`` (pruned-reduction plans are
  build-time constants of one instance's cubes —
  ``parallel/batch.BatchedMaxSum``);
* ``amaxsum`` has no fused layout (``parallel/__init__`` raises);
* sharded convergence keeps message-delta semantics
  (``delta_on:beliefs`` is single-chip only — ``commands/solve``);
* the sharded mesh stays ``edge_major`` except the maxsum fused
  layout (``ShardedFusedMaxSum``).

:func:`enumerate_configs` expands the grid for one (algo, context),
always listing the **default config** (``{}``) first — the autotuner's
never-slower contract is an argmin over a candidate set that contains
the default, so tuning can only match or improve it.
"""

from typing import Dict, List, Optional, Tuple

#: every dispatch surface a tuned config can apply to
CONTEXTS = ("batched", "engine", "sharded", "warm")

#: the tunable knobs, in the canonical (sidecar/record) order
KNOBS = ("layout", "precision", "chunk_size", "warm_budget",
         "nary_max_cells", "bnb", "delta_on")

#: how a knob's value was resolved at dispatch (echoed per knob in
#: result blocks and telemetry — schema minor 9)
TUNING_SOURCES = ("explicit", "tuned", "default")

#: algo families the batched campaign runners implement
#: (``parallel/batch.BATCHED_CLASSES``)
BATCHED_FAMILIES = ("maxsum", "dsa", "mgm")

#: knob -> (applicable contexts, candidate values).  Values are the
#: SEARCHED grid; validity per (algo, context) is refined below.
_KNOB_TABLE: Dict[str, Tuple[Tuple[str, ...], Tuple]] = {
    "layout": (("warm", "sharded"),
               ("edge_major", "lane_major", "fused")),
    "precision": (("batched", "engine", "sharded", "warm"),
                  ("f32", "bf16")),
    "chunk_size": (("engine", "warm"), (8, 16, 32, 64)),
    "warm_budget": (("warm",), ("adaptive", "fixed")),
    "nary_max_cells": (("engine",), (2048, 4096, 8192)),
    "bnb": (("engine", "sharded"), (False, True)),
    "delta_on": (("batched", "engine"), ("messages", "beliefs")),
}


def knob_domain(knob: str, algo: str, context: str) -> Tuple:
    """The candidate values of ``knob`` for one (algo, context) —
    empty when the knob does not exist on that dispatch surface."""
    if knob not in _KNOB_TABLE:
        raise ValueError(
            f"unknown knob {knob!r}; known: {', '.join(KNOBS)}")
    if context not in CONTEXTS:
        raise ValueError(
            f"unknown context {context!r}; known: "
            f"{', '.join(CONTEXTS)}")
    contexts, values = _KNOB_TABLE[knob]
    if context not in contexts:
        return ()
    kept = tuple(
        v for v in values
        if invalid_reason(algo, {knob: v}, context) is None)
    return kept


def invalid_reason(algo: str, config: Dict, context: str
                   ) -> Optional[str]:
    """Why ``config`` is invalid for (algo, context) — None when it is
    valid.  Each rule names the runtime rejection it mirrors, so the
    space and the runners cannot drift silently."""
    for knob in config:
        if knob not in _KNOB_TABLE:
            return (f"unknown knob {knob!r}; known: "
                    f"{', '.join(KNOBS)}")
        if context not in _KNOB_TABLE[knob][0]:
            return (f"{knob} is not a {context}-context knob "
                    f"(applies to: "
                    f"{', '.join(_KNOB_TABLE[knob][0])})")
    if config.get("bnb") and context == "batched":
        # mirror: parallel/batch.BatchedMaxSum raises — pruned
        # reduction plans are build-time constants of ONE instance's
        # cubes, batched cubes are vmapped arguments
        return ("batched runners reject bnb: pruned-reduction plans "
                "are build-time constants of one instance's cubes")
    if config.get("bnb") and algo not in ("maxsum", "amaxsum"):
        return f"bnb is a maxsum-family knob, not {algo}"
    if config.get("layout") == "fused" and algo == "amaxsum":
        # mirror: parallel/__init__._build_sharded_solver raises
        return ("amaxsum has no fused mesh layout (only maxsum's "
                "ShardedFusedMaxSum speaks it)")
    if context == "sharded" and \
            config.get("layout") not in (None, "edge_major") and \
            not (algo == "maxsum" and config.get("layout") == "fused"):
        # the mesh families compile the edge-major step; only maxsum
        # grew the fused shard-local alternative
        return (f"sharded {algo} stays edge_major "
                f"(layout {config['layout']!r} has no mesh program)")
    if config.get("delta_on", "messages") != "messages":
        if algo != "maxsum":
            return f"delta_on is a maxsum knob, not {algo}"
        if context == "sharded":
            # mirror: commands/solve rejects -p delta_on:beliefs in
            # sharded mode — mesh convergence keeps message deltas
            return ("delta_on:beliefs is a single-chip engine knob; "
                    "sharded convergence keeps message-delta "
                    "semantics")
    if context == "batched" and algo not in BATCHED_FAMILIES:
        return (f"{algo} has no batched campaign runner (families: "
                f"{', '.join(BATCHED_FAMILIES)})")
    return None


def enumerate_configs(algo: str, context: str = "batched",
                      pinned: Optional[Dict] = None) -> List[Dict]:
    """The valid candidate grid for one (algo, context), default
    config first.  ``pinned`` knobs (the operator's explicit ``-p``
    params) are excluded from the search dimensions — an explicit
    knob always wins, so searching over it would measure configs
    dispatch can never run."""
    pinned = dict(pinned or {})
    dims: List[Tuple[str, Tuple]] = []
    for knob in KNOBS:
        if knob in pinned:
            continue
        values = knob_domain(knob, algo, context)
        # only knobs with a real choice become search dimensions
        if len(values) > 1:
            dims.append((knob, values))
    configs: List[Dict] = [{}]
    for knob, values in dims:
        default = _KNOB_TABLE[knob][1][0]
        configs = [
            dict(c, **({} if v == default else {knob: v}))
            for c in configs for v in values]
    # dedupe (defaults collapse to {}), keep {} first, drop invalid
    seen, out = set(), []
    for c in configs:
        key = tuple(sorted(c.items()))
        if key in seen:
            continue
        seen.add(key)
        if invalid_reason(algo, dict(pinned, **c), context) is None:
            out.append(c)
    out.sort(key=lambda c: (len(c) != 0, config_label(c)))
    return out


def config_label(config: Dict) -> str:
    """One compact token per candidate (tables, logs, metric labels):
    ``default`` for the empty config, else ``knob:value`` pairs in
    canonical knob order."""
    if not config:
        return "default"
    return ",".join(f"{k}:{config[k]}" for k in KNOBS if k in config)

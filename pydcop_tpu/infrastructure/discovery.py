"""Name resolution plane: central directory + per-agent discovery cache.

reference parity: pydcop/infrastructure/discovery.py:95-1496.

The directory is a message-passing computation hosted on the orchestrator
agent; every agent keeps a local :class:`Discovery` cache that registers
agents / computations / replicas with the directory and can subscribe to
changes.  The interface is deliberately swappable for a fully distributed
implementation (reference: discovery.py:31-43).

On the TPU build this is pure control plane: the data plane's "routing" is
array indexing inside a jitted step; discovery only matters for host-side
orchestration (deploy/repair/multi-host DCN bootstrap).
"""

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .communication import DIRECTORY_COMP_NAME, MSG_DISCOVERY, \
    UnknownAgent, UnknownComputation
from .computations import Message, MessagePassingComputation, \
    message_type, register

logger = logging.getLogger("pydcop_tpu.infrastructure.discovery")

DIRECTORY_COMP = DIRECTORY_COMP_NAME


class DiscoveryException(Exception):
    pass


# Directory protocol vocabulary (reference: discovery.py:95-117)
RegisterAgentMessage = message_type(
    "register_agent", ["agent", "address"])
UnregisterAgentMessage = message_type(
    "unregister_agent", ["agent"])
RegisterComputationMessage = message_type(
    "register_computation", ["computation", "agent", "address"])
UnregisterComputationMessage = message_type(
    "unregister_computation", ["computation", "agent"])
RegisterReplicaMessage = message_type(
    "register_replica", ["replica", "agent"])
UnregisterReplicaMessage = message_type(
    "unregister_replica", ["replica", "agent"])
SubscribeAgentMessage = message_type(
    "subscribe_agent", ["agent", "subscribe"])
SubscribeComputationMessage = message_type(
    "subscribe_computation", ["computation", "subscribe"])
SubscribeReplicaMessage = message_type(
    "subscribe_replica", ["replica", "subscribe"])
PublishAgentMessage = message_type(
    "publish_agent", ["event", "agent", "address"])
PublishComputationMessage = message_type(
    "publish_computation", ["event", "computation", "agent", "address"])
PublishReplicaMessage = message_type(
    "publish_replica", ["event", "replica", "agent"])


class DirectoryComputation(MessagePassingComputation):
    """Central registry hosted on the orchestrator agent
    (reference: discovery.py:121-292)."""

    def __init__(self, discovery: "Discovery"):
        super().__init__(DIRECTORY_COMP)
        self.discovery = discovery
        # subscriptions: name -> set of subscriber computation names
        self._agent_subs: Dict[str, Set[str]] = {}
        self._comp_subs: Dict[str, Set[str]] = {}
        self._replica_subs: Dict[str, Set[str]] = {}

    @register("register_agent")
    def _on_register_agent(self, sender, msg, t):
        self.discovery.register_agent(msg.agent, msg.address, publish=False)
        for sub in self._agent_subs.get(msg.agent, set()) | \
                self._agent_subs.get("*", set()):
            self.post_msg(sub, PublishAgentMessage(
                "agent_added", msg.agent, msg.address), MSG_DISCOVERY)

    @register("unregister_agent")
    def _on_unregister_agent(self, sender, msg, t):
        try:
            self.discovery.unregister_agent(msg.agent, publish=False)
        except UnknownAgent:
            pass
        for sub in self._agent_subs.get(msg.agent, set()) | \
                self._agent_subs.get("*", set()):
            self.post_msg(sub, PublishAgentMessage(
                "agent_removed", msg.agent, None), MSG_DISCOVERY)

    @register("register_computation")
    def _on_register_computation(self, sender, msg, t):
        if msg.address is not None:
            self.discovery.register_agent(msg.agent, msg.address,
                                          publish=False)
        self.discovery.register_computation(
            msg.computation, msg.agent, publish=False)
        for sub in self._comp_subs.get(msg.computation, set()) | \
                self._comp_subs.get("*", set()):
            self.post_msg(sub, PublishComputationMessage(
                "computation_added", msg.computation, msg.agent,
                msg.address), MSG_DISCOVERY)

    @register("unregister_computation")
    def _on_unregister_computation(self, sender, msg, t):
        try:
            self.discovery.unregister_computation(
                msg.computation, msg.agent, publish=False)
        except UnknownComputation:
            pass
        for sub in self._comp_subs.get(msg.computation, set()) | \
                self._comp_subs.get("*", set()):
            self.post_msg(sub, PublishComputationMessage(
                "computation_removed", msg.computation, msg.agent, None),
                MSG_DISCOVERY)

    @register("register_replica")
    def _on_register_replica(self, sender, msg, t):
        self.discovery.register_replica(msg.replica, msg.agent,
                                        publish=False)
        for sub in self._replica_subs.get(msg.replica, set()) | \
                self._replica_subs.get("*", set()):
            self.post_msg(sub, PublishReplicaMessage(
                "replica_added", msg.replica, msg.agent), MSG_DISCOVERY)

    @register("unregister_replica")
    def _on_unregister_replica(self, sender, msg, t):
        self.discovery.unregister_replica(msg.replica, msg.agent,
                                          publish=False)
        for sub in self._replica_subs.get(msg.replica, set()) | \
                self._replica_subs.get("*", set()):
            self.post_msg(sub, PublishReplicaMessage(
                "replica_removed", msg.replica, msg.agent), MSG_DISCOVERY)

    @register("subscribe_agent")
    def _on_subscribe_agent(self, sender, msg, t):
        if msg.subscribe:
            self._agent_subs.setdefault(msg.agent, set()).add(sender)
            # answer with current state so the subscriber syncs up
            if msg.agent != "*":
                try:
                    addr = self.discovery.agent_address(msg.agent)
                    self.post_msg(sender, PublishAgentMessage(
                        "agent_added", msg.agent, addr), MSG_DISCOVERY)
                except UnknownAgent:
                    pass
            else:
                for a in self.discovery.agents():
                    self.post_msg(sender, PublishAgentMessage(
                        "agent_added", a,
                        self.discovery.agent_address(a)), MSG_DISCOVERY)
        else:
            self._agent_subs.get(msg.agent, set()).discard(sender)

    @register("subscribe_computation")
    def _on_subscribe_computation(self, sender, msg, t):
        if msg.subscribe:
            self._comp_subs.setdefault(msg.computation, set()).add(sender)
            if msg.computation != "*":
                try:
                    agt = self.discovery.computation_agent(msg.computation)
                    addr = None
                    try:
                        addr = self.discovery.agent_address(agt)
                    except UnknownAgent:
                        pass
                    self.post_msg(sender, PublishComputationMessage(
                        "computation_added", msg.computation, agt, addr),
                        MSG_DISCOVERY)
                except UnknownComputation:
                    pass
        else:
            self._comp_subs.get(msg.computation, set()).discard(sender)

    @register("subscribe_replica")
    def _on_subscribe_replica(self, sender, msg, t):
        if msg.subscribe:
            self._replica_subs.setdefault(msg.replica, set()).add(sender)
            for agt in self.discovery.replica_agents(msg.replica):
                self.post_msg(sender, PublishReplicaMessage(
                    "replica_added", msg.replica, agt), MSG_DISCOVERY)
        else:
            self._replica_subs.get(msg.replica, set()).discard(sender)


class Directory:
    """The directory service object, owned by the orchestrator agent
    (reference: discovery.py:294-651)."""

    def __init__(self, discovery: "Discovery"):
        self.discovery = discovery
        self.directory_computation = DirectoryComputation(discovery)

    @property
    def address(self):
        return self.discovery.agent_address(self.discovery.agent_name)


class _DiscoveryComputation(MessagePassingComputation):
    """Per-agent computation receiving directory publications
    (reference: discovery.py:654-727)."""

    def __init__(self, name: str, discovery: "Discovery"):
        super().__init__(name)
        self.discovery = discovery

    @register("publish_agent")
    def _on_publish_agent(self, sender, msg, t):
        if msg.event == "agent_added":
            self.discovery.register_agent(msg.agent, msg.address,
                                          publish=False)
        else:
            try:
                # unregister_agent fires 'agent_removed' itself: removal
                # events must fire exactly once per publication
                self.discovery.unregister_agent(msg.agent, publish=False)
            except UnknownAgent:
                # agent unknown locally: subscribers still expect the event
                self.discovery._fire_agent(msg.event, msg.agent,
                                           msg.address)

    @register("publish_computation")
    def _on_publish_computation(self, sender, msg, t):
        if msg.event == "computation_added":
            if msg.address is not None:
                self.discovery.register_agent(msg.agent, msg.address,
                                              publish=False)
            self.discovery.register_computation(
                msg.computation, msg.agent, publish=False)
        else:
            try:
                # unregister_computation fires 'computation_removed'
                # itself — except for *stale* removals (the computation
                # has since re-registered on another agent), which must
                # not fire a false removal event
                self.discovery.unregister_computation(
                    msg.computation, msg.agent, publish=False)
            except UnknownComputation:
                self.discovery._fire_computation(
                    msg.event, msg.computation, msg.agent)

    @register("publish_replica")
    def _on_publish_replica(self, sender, msg, t):
        if msg.event == "replica_added":
            self.discovery.register_replica(msg.replica, msg.agent,
                                            publish=False)
        else:
            self.discovery.unregister_replica(msg.replica, msg.agent,
                                              publish=False)


class Discovery:
    """Local, eventually-consistent view of agents / computations /
    replicas (reference: discovery.py:654-1496).

    All mutating calls optionally *publish* to the central directory via
    the agent's discovery computation; publications come back to
    subscribers as ``publish_*`` messages.
    """

    def __init__(self, agent_name: str, address: Any = None):
        self.agent_name = agent_name
        self._lock = threading.RLock()
        self._agents_data: Dict[str, Any] = {}
        if address is not None:
            self._agents_data[agent_name] = address
        self._computations_data: Dict[str, str] = {}
        self._replicas_data: Dict[str, Set[str]] = {}
        # callbacks: name -> list of (cb, one_shot)
        self._agent_cbs: Dict[str, List[Tuple[Callable, bool]]] = {}
        self._comp_cbs: Dict[str, List[Tuple[Callable, bool]]] = {}
        self._replica_cbs: Dict[str, List[Tuple[Callable, bool]]] = {}
        self.discovery_computation = _DiscoveryComputation(
            f"_discovery_{agent_name}", self)

    # ------------------------------------------------------------- agents

    def agents(self) -> List[str]:
        with self._lock:
            return list(self._agents_data)

    def agent_address(self, agent: str):
        with self._lock:
            try:
                return self._agents_data[agent]
            except KeyError:
                raise UnknownAgent(agent)

    def register_agent(self, agent: str, address: Any = None,
                       publish: bool = True):
        with self._lock:
            known = agent in self._agents_data
            self._agents_data[agent] = address
        if publish:
            self._send_to_directory(RegisterAgentMessage(agent, address))
        if not known:
            self._fire_agent("agent_added", agent, address)

    def unregister_agent(self, agent: str, publish: bool = True):
        with self._lock:
            if agent not in self._agents_data:
                raise UnknownAgent(agent)
            del self._agents_data[agent]
            # drop computations hosted there
            for c, a in list(self._computations_data.items()):
                if a == agent:
                    del self._computations_data[c]
        if publish:
            self._send_to_directory(UnregisterAgentMessage(agent))
        self._fire_agent("agent_removed", agent, None)

    def subscribe_agent(self, agent: str, cb: Optional[Callable] = None,
                        one_shot: bool = False):
        if cb is not None:
            with self._lock:
                self._agent_cbs.setdefault(agent, []).append((cb, one_shot))
        self._send_to_directory(SubscribeAgentMessage(agent, True))

    def subscribe_agent_local(self, agent: str, cb: Callable,
                              one_shot: bool = False):
        """Callback-only subscription, no directory round-trip — used by
        the directory's own host (the orchestrator)."""
        with self._lock:
            self._agent_cbs.setdefault(agent, []).append((cb, one_shot))

    def subscribe_computation_local(self, computation: str, cb: Callable,
                                    one_shot: bool = False):
        with self._lock:
            self._comp_cbs.setdefault(computation, []).append(
                (cb, one_shot))

    def unsubscribe_agent(self, agent: str, cb: Optional[Callable] = None):
        with self._lock:
            if cb is None:
                self._agent_cbs.pop(agent, None)
            else:
                self._agent_cbs[agent] = [
                    (c, o) for c, o in self._agent_cbs.get(agent, [])
                    if c != cb]
        self._send_to_directory(SubscribeAgentMessage(agent, False))

    # ------------------------------------------------------- computations

    def computations(self, include_technical: bool = False) -> List[str]:
        with self._lock:
            return [c for c in self._computations_data
                    if include_technical or not c.startswith("_")]

    def computation_agent(self, computation: str) -> str:
        with self._lock:
            try:
                return self._computations_data[computation]
            except KeyError:
                raise UnknownComputation(computation)

    def agent_computations(self, agent: str,
                           include_technical: bool = False) -> List[str]:
        with self._lock:
            return [
                c for c, a in self._computations_data.items()
                if a == agent and
                (include_technical or not c.startswith("_"))]

    def register_computation(self, computation: str,
                             agent: Optional[str] = None,
                             address: Any = None, publish: bool = True):
        agent = agent if agent is not None else self.agent_name
        with self._lock:
            if address is not None:
                self._agents_data[agent] = address
            elif agent not in self._agents_data:
                self._agents_data.setdefault(agent, None)
            known = self._computations_data.get(computation)
            self._computations_data[computation] = agent
        if publish:
            self._send_to_directory(RegisterComputationMessage(
                computation, agent, address))
        if known != agent:
            self._fire_computation("computation_added", computation, agent)

    def unregister_computation(self, computation: str,
                               agent: Optional[str] = None,
                               publish: bool = True):
        with self._lock:
            known = self._computations_data.get(computation)
            if known is None and computation not in self._computations_data:
                raise UnknownComputation(computation)
            if agent is not None and known != agent:
                # stale unregistration, someone else re-registered it
                return
            del self._computations_data[computation]
        if publish:
            self._send_to_directory(UnregisterComputationMessage(
                computation, agent))
        self._fire_computation("computation_removed", computation, agent)

    def subscribe_computation(self, computation: str,
                              cb: Optional[Callable] = None,
                              one_shot: bool = False):
        if cb is not None:
            with self._lock:
                self._comp_cbs.setdefault(computation, []).append(
                    (cb, one_shot))
        self._send_to_directory(SubscribeComputationMessage(
            computation, True))

    def unsubscribe_computation(self, computation: str,
                                cb: Optional[Callable] = None):
        with self._lock:
            if cb is None:
                self._comp_cbs.pop(computation, None)
            else:
                self._comp_cbs[computation] = [
                    (c, o) for c, o in self._comp_cbs.get(computation, [])
                    if c != cb]
        self._send_to_directory(SubscribeComputationMessage(
            computation, False))

    # ------------------------------------------------------------ replicas

    def replica_agents(self, replica: str) -> Set[str]:
        with self._lock:
            return set(self._replicas_data.get(replica, set()))

    def register_replica(self, replica: str, agent: Optional[str] = None,
                         publish: bool = True):
        agent = agent if agent is not None else self.agent_name
        with self._lock:
            self._replicas_data.setdefault(replica, set()).add(agent)
        if publish:
            self._send_to_directory(RegisterReplicaMessage(replica, agent))
        self._fire_replica("replica_added", replica, agent)

    def unregister_replica(self, replica: str,
                           agent: Optional[str] = None,
                           publish: bool = True):
        agent = agent if agent is not None else self.agent_name
        with self._lock:
            self._replicas_data.get(replica, set()).discard(agent)
        if publish:
            self._send_to_directory(UnregisterReplicaMessage(
                replica, agent))

    def subscribe_replica(self, replica: str,
                          cb: Optional[Callable] = None):
        if cb is not None:
            with self._lock:
                self._replica_cbs.setdefault(replica, []).append(
                    (cb, False))
        self._send_to_directory(SubscribeReplicaMessage(replica, True))

    # ------------------------------------------------------------ internal

    def _send_to_directory(self, msg: Message):
        sender = self.discovery_computation.message_sender
        if sender is None:
            # not attached to an agent yet (standalone/test use): the
            # local cache is authoritative, nothing to publish to
            return
        self.discovery_computation.post_msg(DIRECTORY_COMP, msg,
                                            MSG_DISCOVERY)

    def _fire(self, cbs_map, key: str, event: str, name: str, agent):
        with self._lock:
            cbs = list(cbs_map.get(key, [])) + list(cbs_map.get("*", []))
        for cb, one_shot in cbs:
            try:
                cb(event, name, agent)
            except Exception:
                logger.exception("Error in discovery callback for %s", name)
            if one_shot:
                with self._lock:
                    for k in (key, "*"):
                        if (cb, one_shot) in cbs_map.get(k, []):
                            cbs_map[k].remove((cb, one_shot))

    def _fire_agent(self, event, agent, address):
        self._fire(self._agent_cbs, agent, event, agent, address)

    def _fire_computation(self, event, computation, agent):
        self._fire(self._comp_cbs, computation, event, computation, agent)

    def _fire_replica(self, event, replica, agent):
        self._fire(self._replica_cbs, replica, event, replica, agent)
